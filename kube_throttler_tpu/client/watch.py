"""Watch streams over the store (the clientset's Watch verb).

The reference's generated clients expose ``Watch(ctx, opts)`` returning a
``watch.Interface`` whose ``ResultChan()`` yields typed events
(clientset/versioned/typed/schedule/v1alpha1/throttle.go:110-125). Here a
``Watch`` is an iterator over :class:`~..engine.store.Event` objects fed by
the store's synchronous dispatch, decoupled through a queue so consumers run
on their own thread at their own pace.

The queue is BOUNDED (client-go's watch channels are too — chanSize 100 in
the reflector): the store dispatches events synchronously under its lock,
so a slow or dead consumer on an unbounded queue would grow memory without
limit, and on a blocking one would wedge every mutator in the process. The
default policy is ``drop-oldest``: the dispatch thread never blocks, the
consumer keeps the newest events, and the watch is marked ``overflowed`` so
the consumer knows its stream has a gap and can relist (the same contract
as a 410 on a real watch). ``block`` restores the old apply-backpressure
behavior for consumers that must see every event and guarantee their own
pace.
"""

from __future__ import annotations

import queue
import threading
import weakref
from collections import deque
from typing import Callable, Iterator, List, Optional, Union

from ..utils.lockorder import make_lock
from ..engine.store import Event, EventType, Store


class Watch:
    """A stoppable stream of events for one kind.

    With ``replay`` the stream begins with synthetic ADDED events for every
    object currently in the store (list-then-watch semantics).

    ``maxsize`` bounds the queue (0 = unbounded); ``overflow`` picks the
    slow-consumer policy: ``"drop-oldest"`` (default — dispatch never
    blocks, ``overflowed``/``dropped`` record the gap) or ``"block"``
    (dispatch waits; the pre-hardening behavior).
    """

    _SENTINEL = object()

    DEFAULT_MAXSIZE = 4096
    OVERFLOW_POLICIES = ("drop-oldest", "block")

    # class-level aggregates for /metrics (see metrics.register_watch_metrics):
    # live instances tracked weakly so an abandoned, never-stopped watch
    # doesn't pin the stats forever
    _live: "weakref.WeakSet[Watch]" = weakref.WeakSet()
    _stats_lock = make_lock("watch.stats")
    _dropped_total = 0

    def __init__(
        self,
        store: Store,
        kind: str,
        filter: Optional[Callable[[Event], bool]] = None,
        replay: bool = False,
        maxsize: Optional[int] = None,
        overflow: str = "drop-oldest",
    ) -> None:
        if overflow not in self.OVERFLOW_POLICIES:
            raise ValueError(
                f"overflow must be one of {self.OVERFLOW_POLICIES}, got {overflow!r}"
            )
        self._store = store
        self._kind = kind
        self._filter = filter
        self._maxsize = self.DEFAULT_MAXSIZE if maxsize is None else max(0, maxsize)
        self._overflow = overflow
        self._queue: "queue.Queue" = queue.Queue(maxsize=self._maxsize)
        self._stopped = threading.Event()
        self._terminal = False  # consumer-side: sentinel observed
        # consumer-side unpack buffer for batch items (micro-batched ingest
        # delivers one LIST per store batch — see on_batch)
        self._pending: "deque[Event]" = deque()
        self.dropped = 0  # events shed by drop-oldest on this watch (PER EVENT)
        self.overflowed = False  # the stream has a gap — consumer should relist

        def handler(event: Event) -> None:
            if self._stopped.is_set():
                return
            if store.in_batch_dispatch:
                return  # delivered as a batch item by on_batch
            if self._filter is not None and not self._filter(event):
                return
            self._put(event, 1)

        self._handler = handler
        Watch._live.add(self)
        store.add_event_handler(kind, handler, replay=replay)
        store.add_batch_listener(self)

    def on_batch(self, events: List[Event]) -> None:
        """Store batch-listener hook: the batch's matching events enqueue
        as ONE item (a list — a slow consumer pays one queue round trip
        per ingest batch, and the wire watch can encode them in one
        write). Shedding accounts PER EVENT: a dropped list moves the
        overflow counters by its length — counting batches would
        under-report the stream's gap by the batch size."""
        if self._stopped.is_set():
            return
        matched = [
            e
            for e in events
            if e.kind == self._kind and (self._filter is None or self._filter(e))
        ]
        if not matched:
            return
        self._put(matched[0] if len(matched) == 1 else matched, len(matched))

    def _put(self, item: Union[Event, List[Event]], n_events: int) -> None:
        """Enqueue one item (an Event or a batch list) under the overflow
        policy; drop-oldest counts shed EVENTS, not items."""
        if self._overflow == "block":
            self._queue.put(item)
            return
        while True:
            try:
                self._queue.put_nowait(item)
                return
            except queue.Full:
                try:
                    shed = self._queue.get_nowait()
                except queue.Empty:
                    continue  # consumer raced us; retry the put
                if shed is self._SENTINEL:
                    # never shed the terminator: the stream is stopping,
                    # losing THIS item instead is fine
                    self._queue.put_nowait(shed)
                    return
                n = len(shed) if isinstance(shed, list) else 1
                self.overflowed = True
                self.dropped += n
                with Watch._stats_lock:
                    Watch._dropped_total += n

    def stop(self) -> None:
        """Terminate the stream; pending and future ``next()`` calls raise
        StopIteration once drained."""
        if not self._stopped.is_set():
            self._stopped.set()
            self._store.remove_event_handler(self._kind, self._handler)
            self._store.remove_batch_listener(self)
            while True:
                try:
                    self._queue.put_nowait(self._SENTINEL)
                    return
                except queue.Full:
                    # full bounded queue with a gone consumer: shed one event
                    # to make room for the terminator (never block stop())
                    try:
                        self._queue.get_nowait()
                    except queue.Empty:
                        continue

    def qsize(self) -> int:
        # queue items plus the consumer-side unpack buffer; a batch item
        # counts once here (the depth gauge reads this — cheap, slightly
        # under events when batches are queued)
        return self._queue.qsize() + len(self._pending)

    def next(self, timeout: Optional[float] = None) -> Event:
        """Block for the next event. Raises ``queue.Empty`` on timeout,
        ``StopIteration`` after :meth:`stop`. Batch items unpack
        transparently — consumers keep their one-event-at-a-time view."""
        # once the sentinel has been observed the stream is terminal — a
        # straggler event that raced in behind the sentinel must never be
        # returned, so the flag (not the queue contents) is authoritative
        if self._terminal:
            raise StopIteration
        if self._pending:
            return self._pending.popleft()
        item = self._queue.get(timeout=timeout)
        if item is self._SENTINEL:
            self._terminal = True
            raise StopIteration
        if isinstance(item, list):
            self._pending.extend(item)
            return self._pending.popleft()
        return item

    def next_batch(self, timeout: Optional[float] = None, max_events: int = 256) -> List[Event]:
        """Drain up to ``max_events`` immediately-available events in one
        call (blocking like :meth:`next` for the first) — the consumer-side
        micro-batch for wire encoders and reflectors: one socket write /
        one store application per drained batch instead of per event."""
        out = [self.next(timeout=timeout)]
        while len(out) < max_events:
            if self._pending:
                out.append(self._pending.popleft())
                continue
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is self._SENTINEL:
                # re-stage the terminator for the NEXT call: this batch's
                # events are real and must be delivered first
                self._queue.put_nowait(item)
                break
            if isinstance(item, list):
                self._pending.extend(item)
            else:
                out.append(item)
        return out

    def __iter__(self) -> Iterator[Event]:
        while True:
            try:
                yield self.next()
            except StopIteration:
                return

    def __enter__(self) -> "Watch":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- metrics ----------------------------------------------------------

    @classmethod
    def stats(cls) -> dict:
        """Aggregate snapshot across live watches (scrape-time reader for
        the watch-queue gauge/counter families)."""
        live = [w for w in cls._live if not w._stopped.is_set()]
        with cls._stats_lock:
            dropped_total = cls._dropped_total
        return {
            "open": len(live),
            "depth": sum(w.qsize() for w in live),
            "dropped_total": dropped_total,
        }


__all__ = ["Watch", "Event", "EventType"]
