"""Client layer — hand-written analog of the reference's generated API
machinery (pkg/generated/, SURVEY.md §2.2): typed clientset with the full
verb set, watch streams, shared informers with resync + indexers, and
indexer-backed listers, plus a fake clientset for tests.
"""

from .clientset import (
    Clientset,
    ClusterThrottleInterface,
    CoreV1Client,
    NamespaceInterface,
    PodInterface,
    ScheduleV1alpha1Client,
    ThrottleInterface,
    json_merge_patch,
    new_fake_clientset,
)
from .informers import NAMESPACE_INDEX, Indexer, SharedIndexInformer, SharedInformerFactory
from .listers import (
    ClusterThrottleLister,
    NamespaceLister,
    PodLister,
    ThrottleLister,
)
from .watch import Watch

__all__ = [
    "Clientset",
    "ClusterThrottleInterface",
    "ClusterThrottleLister",
    "CoreV1Client",
    "Indexer",
    "NAMESPACE_INDEX",
    "NamespaceInterface",
    "NamespaceLister",
    "PodInterface",
    "PodLister",
    "ScheduleV1alpha1Client",
    "SharedIndexInformer",
    "SharedInformerFactory",
    "ThrottleInterface",
    "ThrottleLister",
    "Watch",
    "json_merge_patch",
    "new_fake_clientset",
]
