"""Indexer-backed listers — the lister-gen analog
(pkg/generated/listers/schedule/v1alpha1/).

``ThrottleLister.throttles(ns).list(selector)`` mirrors
listers/schedule/v1alpha1/throttle.go:46-99: list from the shared
informer's indexer using the namespace index, optionally filtered by a
predicate (the Go version takes ``labels.Selector``; throttle objects here
carry no metadata labels, so the filter is a generic predicate — the
everything-selector is ``None``).
"""

from __future__ import annotations

from typing import Callable, List, Optional, TypeVar

from ..api.pod import Namespace, Pod
from ..api.types import ClusterThrottle, Throttle
from .informers import NAMESPACE_INDEX, Indexer

T = TypeVar("T")
Predicate = Optional[Callable[[T], bool]]


def _filtered(objs: List[T], predicate: Predicate) -> List[T]:
    if predicate is None:
        return objs
    return [o for o in objs if predicate(o)]


class ThrottleNamespaceLister:
    def __init__(self, indexer: Indexer, namespace: str) -> None:
        self._indexer = indexer
        self._namespace = namespace

    def list(self, predicate: Predicate = None) -> List[Throttle]:
        return _filtered(self._indexer.by_index(NAMESPACE_INDEX, self._namespace), predicate)

    def get(self, name: str) -> Throttle:
        obj = self._indexer.get(f"{self._namespace}/{name}")
        if obj is None:
            raise KeyError(f"throttle {self._namespace}/{name} not found")
        return obj


class ThrottleLister:
    def __init__(self, indexer: Indexer) -> None:
        self._indexer = indexer

    def list(self, predicate: Predicate = None) -> List[Throttle]:
        return _filtered(self._indexer.list(), predicate)

    def throttles(self, namespace: str) -> ThrottleNamespaceLister:
        return ThrottleNamespaceLister(self._indexer, namespace)

    def get_by_keys(self, keys) -> List[Optional[Throttle]]:
        """Bulk fetch by full "ns/name" store keys (one indexer lock hold);
        None per missing key. Serving fast path — see Indexer.get_many."""
        return self._indexer.get_many(keys)


class ClusterThrottleLister:
    def __init__(self, indexer: Indexer) -> None:
        self._indexer = indexer

    def list(self, predicate: Predicate = None) -> List[ClusterThrottle]:
        return _filtered(self._indexer.list(), predicate)

    def get(self, name: str) -> ClusterThrottle:
        obj = self._indexer.get(name)
        if obj is None:
            raise KeyError(f"clusterthrottle {name} not found")
        return obj

    def get_by_names(self, names) -> List[Optional[ClusterThrottle]]:
        """Bulk fetch by bare names (one indexer lock hold); None per
        missing name. Serving fast path — see Indexer.get_many."""
        return self._indexer.get_many(names)


class PodNamespaceLister:
    def __init__(self, indexer: Indexer, namespace: str) -> None:
        self._indexer = indexer
        self._namespace = namespace

    def list(self, predicate: Predicate = None) -> List[Pod]:
        return _filtered(self._indexer.by_index(NAMESPACE_INDEX, self._namespace), predicate)

    def get(self, name: str) -> Pod:
        obj = self._indexer.get(f"{self._namespace}/{name}")
        if obj is None:
            raise KeyError(f"pod {self._namespace}/{name} not found")
        return obj


class PodLister:
    def __init__(self, indexer: Indexer) -> None:
        self._indexer = indexer

    def list(self, predicate: Predicate = None) -> List[Pod]:
        return _filtered(self._indexer.list(), predicate)

    def pods(self, namespace: str) -> PodNamespaceLister:
        return PodNamespaceLister(self._indexer, namespace)


class NamespaceLister:
    def __init__(self, indexer: Indexer) -> None:
        self._indexer = indexer

    def list(self, predicate: Predicate = None) -> List[Namespace]:
        return _filtered(self._indexer.list(), predicate)

    def get(self, name: str) -> Namespace:
        obj = self._indexer.get(name)
        if obj is None:
            raise KeyError(f"namespace {name} not found")
        return obj


class Listers:
    """The bundle the plugin hands its controllers: every read the hot/async
    paths do goes through these indexer-backed listers (the reference reads
    through exactly this layer — plugin.go:76-88 wires listers from the two
    informer factories into the controllers)."""

    def __init__(
        self,
        throttles: ThrottleLister,
        cluster_throttles: ClusterThrottleLister,
        pods: PodLister,
        namespaces: NamespaceLister,
    ) -> None:
        self.throttles = throttles
        self.cluster_throttles = cluster_throttles
        self.pods = pods
        self.namespaces = namespaces

    @classmethod
    def from_factories(cls, schedule_factory, core_factory) -> "Listers":
        """Build from the two shared informer factories (the reference keeps
        throttle kinds and core kinds in separate factories because the
        framework's pod informer lacks a namespace indexer, plugin.go:81-84)."""
        return cls(
            throttles=ThrottleLister(schedule_factory.throttles().indexer),
            cluster_throttles=ClusterThrottleLister(
                schedule_factory.cluster_throttles().indexer
            ),
            pods=PodLister(core_factory.pods().indexer),
            namespaces=NamespaceLister(core_factory.namespaces().indexer),
        )
