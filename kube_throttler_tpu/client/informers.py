"""Shared informers + indexer cache — the informer-gen analog
(pkg/generated/informers/externalversions/).

The reference builds a SharedInformerFactory with a 5-minute resync
(plugin.go:76-79) plus a second factory for Pods/Namespaces with a
namespace indexer (plugin.go:81-88). Here:

- :class:`Indexer` — thread-safe keyed cache with named secondary indexes
  (client-go ``cache.Indexer``; the namespace index is built in).
- :class:`SharedIndexInformer` — one per kind, shared via the factory;
  mirrors the store into its indexer, fans events out to its own handlers,
  and runs a periodic resync that re-delivers MODIFIED(obj, obj) "sync"
  events exactly like client-go's resync.
- :class:`SharedInformerFactory` — lazily creates/shares informers,
  ``start()`` / ``wait_for_cache_sync()`` / ``shutdown()`` lifecycle
  (factory.go:126-181).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Set

from ..engine.store import Event, EventType, Store, key_of
from ..utils.lockorder import assert_held, make_lock, make_rlock

Handler = Callable[[Event], None]


class Indexer:
    """Keyed object cache with named secondary indexes.

    With a ``resolver`` (the columnar store's ``materialize_pod``) the
    indexer retains NO objects: it keeps keys + the index values computed
    at upsert time and materializes through the resolver on every read —
    the informer cache stops being a second full copy of the pod
    population (at 1M pods that copy alone was ~10 heap objects/pod).
    The resolver is a LEAF call (arena lock only), so holding ``_lock``
    across it cannot invert any order."""

    GUARDED_BY = {
        "_objects": "self._lock",
        "_meta": "self._lock",
        "_indices": "self._lock",
    }

    def __init__(
        self,
        index_funcs: Optional[Dict[str, Callable[[object], List[str]]]] = None,
        resolver: Optional[Callable[[str], Optional[object]]] = None,
    ):
        self._lock = make_rlock("informers.indexer")
        self._objects: Dict[str, object] = {}
        self._resolver = resolver
        # resolver mode: key -> {index name: values tuple} computed at
        # upsert (single-value indexes store the bare string — zero
        # per-key container objects for the namespace index)
        self._meta: Dict[str, dict] = {}
        self._index_funcs = index_funcs or {}
        # index name -> index value -> set of object keys
        self._indices: Dict[str, Dict[str, Set[str]]] = {
            name: defaultdict(set) for name in self._index_funcs
        }

    @staticmethod
    def _pack_values(values: List[str]):
        return values[0] if len(values) == 1 else tuple(values)

    @staticmethod
    def _unpack_values(packed) -> tuple:
        return (packed,) if isinstance(packed, str) else packed

    def _unindex_values_locked(self, key: str, name: str, values) -> None:
        assert_held(self._lock, "Indexer._unindex_values_locked")
        for value in values:
            bucket = self._indices[name].get(value)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._indices[name][value]

    def _unindex_locked(self, key: str, obj: object) -> None:
        assert_held(self._lock, "Indexer._unindex_locked")
        for name, fn in self._index_funcs.items():
            self._unindex_values_locked(key, name, fn(obj))

    def _index_locked(self, key: str, obj: object) -> None:
        assert_held(self._lock, "Indexer._index_locked")
        for name, fn in self._index_funcs.items():
            for value in fn(obj):
                self._indices[name][value].add(key)

    def upsert(self, key: str, obj: object) -> None:
        with self._lock:
            if self._resolver is not None:
                old_meta = self._meta.get(key)
                new_meta = {
                    name: self._pack_values(fn(obj))
                    for name, fn in self._index_funcs.items()
                }
                self._meta[key] = new_meta
                if old_meta == new_meta and old_meta is not None:
                    return
                if old_meta is not None:
                    for name, packed in old_meta.items():
                        self._unindex_values_locked(
                            key, name, self._unpack_values(packed)
                        )
                self._index_locked(key, obj)
                return
            old = self._objects.get(key)
            self._objects[key] = obj
            if old is not None:
                # skip the unindex/index set churn when every index value
                # is unchanged — true for ~every status-write echo (the
                # namespace index reads only obj.namespace), which at drain
                # saturation is thousands of upserts/s
                if all(
                    fn(old) == fn(obj) for fn in self._index_funcs.values()
                ):
                    return
                self._unindex_locked(key, old)
            self._index_locked(key, obj)

    def delete(self, key: str) -> None:
        with self._lock:
            if self._resolver is not None:
                old_meta = self._meta.pop(key, None)
                if old_meta is not None:
                    for name, packed in old_meta.items():
                        self._unindex_values_locked(
                            key, name, self._unpack_values(packed)
                        )
                return
            old = self._objects.pop(key, None)
            if old is not None:
                self._unindex_locked(key, old)

    def get(self, key: str):
        with self._lock:
            if self._resolver is not None:
                return self._resolver(key) if key in self._meta else None
            return self._objects.get(key)

    def get_many(self, keys) -> List[object]:
        """Batch ``get`` under ONE lock hold — None per missing key. The
        serving hot path resolves ~K affected-throttle objects per
        decision; per-key get() paid a lock acquire + two frames each
        (~3µs × K measured at the 100k×10k scale)."""
        with self._lock:
            if self._resolver is not None:
                r, meta = self._resolver, self._meta
                return [r(k) if k in meta else None for k in keys]
            g = self._objects.get
            return [g(k) for k in keys]

    def list(self) -> List[object]:
        with self._lock:
            if self._resolver is not None:
                r = self._resolver
                out = [r(k) for k in self._meta]
                return [o for o in out if o is not None]
            return list(self._objects.values())

    def keys(self) -> List[str]:
        with self._lock:
            if self._resolver is not None:
                return list(self._meta.keys())
            return list(self._objects.keys())

    def snapshot(self) -> Dict[str, object]:
        """Keyed copy of the cache under one lock hold (recovery's
        first-relist reconcile walks this rather than the raw store)."""
        with self._lock:
            if self._resolver is not None:
                r = self._resolver
                out = {k: r(k) for k in self._meta}
                return {k: o for k, o in out.items() if o is not None}
            return dict(self._objects)

    def by_index(self, index_name: str, value: str) -> List[object]:
        with self._lock:
            keys = self._indices[index_name].get(value, set())
            if self._resolver is not None:
                r, meta = self._resolver, self._meta
                out = [r(k) for k in keys if k in meta]
                return [o for o in out if o is not None]
            return [self._objects[k] for k in keys if k in self._objects]


NAMESPACE_INDEX = "namespace"


class SharedIndexInformer:
    """One shared informer for one kind; handlers added late get a replay of
    the cache as synthetic ADDED events (cache-sync semantics)."""

    GUARDED_BY = {"_handlers": "self._lock"}

    def __init__(self, store: Store, kind: str, resync_period: float) -> None:
        self._store = store
        self.kind = kind
        self._resync_period = resync_period
        index_funcs = {}
        if kind in ("Pod", "Throttle"):
            index_funcs[NAMESPACE_INDEX] = lambda obj: [obj.namespace]
        # columnar store: the Pod informer cache holds keys only and
        # materializes through the arena on read — no second full copy of
        # the pod population
        resolver = (
            store.materialize_pod
            if kind == "Pod" and getattr(store, "pod_arena", None) is not None
            else None
        )
        self.indexer = Indexer(index_funcs, resolver=resolver)
        self._handlers: List[Handler] = []
        self._lock = make_rlock(f"informers.{kind}.handlers")
        # ALL handler deliveries (store events and resync) serialize through
        # this lock — client-go's contract is per-listener serial delivery,
        # and without it the resync thread could interleave with a mutator
        # thread inside one handler, or deliver MODIFIED after DELETED.
        # Lock order is store-lock → dispatch-lock (store events arrive
        # holding the store lock); handlers must therefore never mutate the
        # store synchronously — enqueue only, like informer handlers.
        self._dispatch_lock = make_rlock(f"informers.{kind}.dispatch")
        self._synced = threading.Event()
        self._stop: Optional[threading.Event] = None
        self._resync_thread: Optional[threading.Thread] = None

        # the store-facing subscription mirrors every event into the indexer
        # BEFORE fanning out, so handlers observe a cache ≥ the event
        self._store.add_event_handler(kind, self._on_store_event, replay=True)
        # batched mutations deliver through on_batch (one mirror pass + one
        # fan-out per batch); the per-event handler skips those dispatches
        self._store.add_batch_listener(self)
        self._synced.set()

    def _on_store_event(self, event: Event) -> None:
        if self._store.in_batch_dispatch:
            return  # mirrored + fanned out by on_batch
        with self._dispatch_lock:
            key = key_of(self.kind, event.obj)
            if event.type == EventType.DELETED:
                self.indexer.delete(key)
            else:
                self.indexer.upsert(key, event.obj)
            with self._lock:
                handlers = list(self._handlers)
            for h in handlers:
                h(event)

    def on_batch(self, events: List[Event]) -> None:
        """Store batch-listener hook: mirror the batch's events of this
        kind into the indexer under ONE dispatch-lock hold, then fan out —
        handlers that expose ``on_events`` (the controllers' batch
        handlers, controllers/base._BatchEventHandler) get the whole
        ordered list in one call; plain handlers still see every event.
        The per-listener serial-delivery contract is unchanged: everything
        runs under the dispatch lock in event order."""
        events = [e for e in events if e.kind == self.kind]
        if not events:
            return
        with self._dispatch_lock:
            for event in events:
                key = key_of(self.kind, event.obj)
                if event.type == EventType.DELETED:
                    self.indexer.delete(key)
                else:
                    self.indexer.upsert(key, event.obj)
            with self._lock:
                handlers = list(self._handlers)
            for h in handlers:
                on_events = getattr(h, "on_events", None)
                if on_events is not None:
                    on_events(events)
                else:
                    for event in events:
                        h(event)

    def add_event_handler(self, handler: Handler, replay: bool = True) -> None:
        # registration + replay under the dispatch lock: otherwise a
        # concurrent DELETED could reach the new handler before the stale
        # replay ADDED, resurrecting a deleted object downstream
        with self._dispatch_lock:
            with self._lock:
                self._handlers.append(handler)
            if replay:
                for obj in self.indexer.list():
                    handler(Event(EventType.ADDED, self.kind, obj))

    def remove_event_handler(self, handler: Handler) -> None:
        with self._lock:
            try:
                self._handlers.remove(handler)
            except ValueError:
                pass

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def snapshot_objects(self) -> Dict[str, object]:
        """The informer cache as ``{key: object}`` — the "first relist"
        view recovery reconciles recovered state against (engine/recovery
        reads through the informer, not the raw store, so informer-mirror
        drift is part of what the divergence counter would catch)."""
        return self.indexer.snapshot()

    def run(self, stop: threading.Event) -> None:
        """Start the resync loop (no-op when resync_period == 0)."""
        self._stop = stop
        if self._resync_period <= 0 or self._resync_thread is not None:
            return

        def loop() -> None:
            while not stop.wait(self._resync_period):
                # loop-level routing (threads checker): a handler raising
                # must not silently kill periodic resync for good
                try:
                    for key in self.indexer.keys():
                        with self._dispatch_lock:
                            # re-read under the dispatch lock: if the object
                            # was deleted since the snapshot, skip — a sync
                            # event must never resurrect a deleted object
                            # downstream
                            obj = self.indexer.get(key)
                            if obj is None:
                                continue
                            with self._lock:
                                handlers = list(self._handlers)
                            for h in handlers:
                                h(Event(EventType.MODIFIED, self.kind, obj, old_obj=obj))
                except Exception:  # noqa: BLE001 — keep resyncing
                    logger.exception("%s resync sweep failed", self.kind)

        self._resync_thread = threading.Thread(
            target=loop, name=f"resync-{self.kind}", daemon=True
        )
        self._resync_thread.start()

    def detach(self) -> None:
        self._store.remove_event_handler(self.kind, self._on_store_event)
        self._store.remove_batch_listener(self)


class InformerBundle:
    """Routes each kind to the factory that owns it — the reference keeps
    throttle kinds in the schedule factory and Pods/Namespaces in a second
    core factory built specifically for its namespace indexer
    (plugin.go:76-88). Controllers subscribe through this facade."""

    def __init__(
        self, schedule_factory: "SharedInformerFactory", core_factory: "SharedInformerFactory"
    ) -> None:
        self.schedule_factory = schedule_factory
        self.core_factory = core_factory

    def throttles(self) -> "SharedIndexInformer":
        return self.schedule_factory.throttles()

    def cluster_throttles(self) -> "SharedIndexInformer":
        return self.schedule_factory.cluster_throttles()

    def pods(self) -> "SharedIndexInformer":
        return self.core_factory.pods()

    def namespaces(self) -> "SharedIndexInformer":
        return self.core_factory.namespaces()


class SharedInformerFactory:
    """factory.go:126-181: lazily shared informers, start-once lifecycle."""

    DEFAULT_RESYNC = 300.0  # 5 minutes (plugin.go:77)

    GUARDED_BY = {
        "_informers": "self._lock",
        "_started": "self._lock",
        "_shutdown": "self._lock",
    }

    def __init__(self, store: Store, resync_period: float = DEFAULT_RESYNC) -> None:
        self._store = store
        self._resync = resync_period
        self._lock = make_lock("informers.factory")
        self._informers: Dict[str, SharedIndexInformer] = {}
        self._stop = threading.Event()
        self._started = False
        self._shutdown = False

    def _informer(self, kind: str) -> SharedIndexInformer:
        with self._lock:
            if self._shutdown:
                raise RuntimeError("SharedInformerFactory has been shut down")
            inf = self._informers.get(kind)
            if inf is None:
                inf = SharedIndexInformer(self._store, kind, self._resync)
                self._informers[kind] = inf
                if self._started:
                    inf.run(self._stop)
            return inf

    def throttles(self) -> SharedIndexInformer:
        return self._informer("Throttle")

    def cluster_throttles(self) -> SharedIndexInformer:
        return self._informer("ClusterThrottle")

    def pods(self) -> SharedIndexInformer:
        return self._informer("Pod")

    def namespaces(self) -> SharedIndexInformer:
        return self._informer("Namespace")

    def start(self) -> None:
        with self._lock:
            if self._shutdown:
                raise RuntimeError("SharedInformerFactory has been shut down")
            self._started = True
            informers = list(self._informers.values())
        for inf in informers:
            inf.run(self._stop)

    def wait_for_cache_sync(self) -> bool:
        """True once every informer's cache is warm. The store mirror is
        synchronous, so this never blocks — kept for lifecycle parity with
        WaitForCacheSync (plugin.go:114-130)."""
        with self._lock:
            informers = list(self._informers.values())
        return all(inf.has_synced() for inf in informers)

    def shutdown(self) -> None:
        self._stop.set()
        with self._lock:
            self._shutdown = True
            informers = list(self._informers.values())
            self._informers.clear()
        for inf in informers:
            inf.detach()
