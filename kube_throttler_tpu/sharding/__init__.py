"""Shared-nothing multiprocess keyspace sharding (ROADMAP item 1).

Measured thread scaling of the single-process engine is ~1.05 at 1→4
threads (BENCH_PR5/PR2): the GIL, not the kernels, caps decisions/s and
ingest/s per host. This package cuts the control plane along PAPER.md's
layer 4-5 controller/plugin seam into N worker *processes*:

- :mod:`ring` — consistent-hash partitioning of the Throttle /
  ClusterThrottle keyspace with **selector-affinity route keys**
  (throttles sharing a selector land on one shard, so a pod event
  routes to few shards instead of all of them);
- :mod:`worker` — one shard's full vertical: store + SelectorIndex +
  journal/snapshot/recovery + device planes + micro-batch ingest + both
  controllers + PR 6's fenced leadership, behind a framed IPC server;
- :mod:`front` — the thin admission front: routes watch/relist events
  to owning shards, scatter-gathers ``pre_filter``/``pre_filter_batch``
  with an AND-merge of shard-local verdicts, two-phase reserves, and
  gang routing by group id;
- :mod:`ipc` — the local transport (length-prefixed pickle frames over
  a socketpair; JSON-line event bodies reuse the journal/replication
  event encoding where objects cross a durability boundary);
- :mod:`supervisor` — spawns and monitors the worker processes,
  restarting and re-syncing a shard that dies.

See docs/PERFORMANCE.md "Multiprocess keyspace sharding".
"""

from .ring import HashRing, route_key_for, stable_hash64  # noqa: F401
from .front import AdmissionFront  # noqa: F401

__all__ = ["HashRing", "route_key_for", "stable_hash64", "AdmissionFront"]
