"""Shard worker supervisor: spawn, monitor, restart, resync.

``cli.py serve --shards N`` builds one of these. Each worker is a real
OS process (``python -m kube_throttler_tpu.sharding.worker``) connected
over an inherited socketpair — SIGKILLing a worker is exactly the chaos
case the kill-a-shard smoke drives, and the monitor turns it into:
mark down (front degrades fail-safe) → respawn → full resync from the
front's merged store (replay + prune) → shard recomputes and re-pushes
every status (no lost flips).
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..utils.lockorder import guard_attrs, make_lock
from .front import AdmissionFront
from .ipc import ShardClient

logger = logging.getLogger(__name__)


@guard_attrs
class ShardSupervisor:
    """Spawns and babysits ``n_shards`` worker processes for a front."""

    # the proc/restart tables are shared between the spawning thread
    # (start), the monitor thread, and stop() — snapshot under the lock,
    # operate on locals (never hold it across a spawn or a sleep)
    GUARDED_BY = {
        "procs": "self._proc_lock",
        "restarts": "self._proc_lock",
    }

    def __init__(
        self,
        front: AdmissionFront,
        name: str = "kube-throttler",
        target_scheduler: str = "my-scheduler",
        use_device: bool = True,
        data_dir: Optional[str] = None,
        ingest_batch="adaptive",
        restart_backoff: float = 0.5,
        max_restarts: int = 10,
        worker_args: Optional[List[str]] = None,
        per_shard_args: Optional[Dict[int, List[str]]] = None,
        env: Optional[dict] = None,
    ):
        self.front = front
        self.n_shards = front.n_shards
        self.name = name
        self.target_scheduler = target_scheduler
        self.use_device = use_device
        self.data_dir = data_dir
        self.ingest_batch = ingest_batch
        self.restart_backoff = restart_backoff
        self.max_restarts = max_restarts
        self.worker_args = list(worker_args or [])
        # one-shot per-shard args for each shard's FIRST incarnation only
        # (chaos rules that must not re-arm on a monitor respawn)
        self.per_shard_args: Dict[int, List[str]] = dict(per_shard_args or {})
        self.env = env
        self._proc_lock = make_lock("shard.supervisor.procs")
        self.procs: Dict[int, subprocess.Popen] = {}
        self.restarts: Dict[int, int] = {i: 0 for i in range(self.n_shards)}
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # one rescale at a time: concurrent callers fail fast (two ring
        # retargets would fight over the front's single transition
        # router); callers wanting a scale PATH sequence steps themselves
        self._rescale_busy = threading.Lock()

    # ------------------------------------------------------------- spawning

    def _spawn(
        self, shard_id: int, extra_args: Optional[List[str]] = None
    ) -> subprocess.Popen:
        parent_sock, child_sock = socket.socketpair()
        try:
            argv = [
                sys.executable, "-m", "kube_throttler_tpu.sharding.worker",
                "--shard-id", str(shard_id),
                "--shards", str(self.n_shards),
                "--ipc-fd", str(child_sock.fileno()),
                "--name", self.name,
                "--target-scheduler-name", self.target_scheduler,
                "--ingest-batch", str(self.ingest_batch),
            ]
            if not self.use_device:
                argv.append("--no-device")
            if self.data_dir:
                argv += ["--data-dir", os.path.join(self.data_dir, f"shard-{shard_id}")]
            argv += self.worker_args
            # one-shot args (a chaos rule armed for THIS incarnation only:
            # a monitor respawn after the armed kill must come up clean,
            # not re-arm the same crash forever)
            if extra_args is None:
                extra_args = self.per_shard_args.pop(shard_id, None)
            if extra_args:
                argv += list(extra_args)
            env = dict(os.environ if self.env is None else self.env)
            env.setdefault("JAX_PLATFORMS", "cpu")
            proc = subprocess.Popen(
                argv,
                pass_fds=[child_sock.fileno()],
                env=env,
                stdout=subprocess.DEVNULL if env.get("KT_SHARD_QUIET") else None,
                stderr=None,
            )
            child_sock.close()
            client = ShardClient(
                shard_id,
                parent_sock,
                on_push=self.front.apply_status_push,
                on_down=self._on_shard_down,
                faults=self.front.faults,
            )
        except BaseException:
            # a failed exec (or client construction) must not leak the
            # socketpair: each monitor-driven respawn retry would strand
            # two fds, and fd exhaustion then takes down the FRONT — the
            # exact lease-elector leak class from the PR 6 review
            parent_sock.close()
            child_sock.close()
            raise
        with self._proc_lock:
            self.procs[shard_id] = proc
        self.front.attach_shard(shard_id, client)
        return proc

    def start(self, ready_timeout: float = 120.0) -> None:
        """Spawn every worker and block until each answers a ping (the
        workers compile/prewarm serially on small hosts — be patient)."""
        spawned = [self._spawn(sid) for sid in range(self.n_shards)]
        deadline = time.monotonic() + ready_timeout
        for sid in range(self.n_shards):
            while True:
                try:
                    self.front.shards[sid].request("ping", None, timeout=5.0)
                    break
                except Exception:  # noqa: BLE001 — keep waiting until deadline
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"shard {sid} did not become ready in {ready_timeout}s"
                        ) from None
                    if spawned[sid].poll() is not None:
                        raise RuntimeError(
                            f"shard {sid} exited rc={spawned[sid].returncode} "
                            "during startup"
                        ) from None
                    time.sleep(0.1)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="shard-monitor", daemon=True
        )
        self._monitor.start()

    # ------------------------------------------------------------ monitoring

    def _on_shard_down(self, shard_id: int) -> None:
        logger.warning("shard %d transport down", shard_id)

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.2):
            # loop-level routing (threads checker): the monitor IS the
            # restart policy — if it died of an unexpected exception, dead
            # shards would stay dead forever while the front reports
            # degraded and nothing ever repairs it
            try:
                self._monitor_tick()
            except Exception:  # noqa: BLE001 — keep the restart policy alive
                logger.exception("shard monitor tick failed")

    def _monitor_tick(self) -> None:
        with self._proc_lock:
            sids = sorted(self.procs)
        for sid in sids:
            with self._proc_lock:
                proc = self.procs.get(sid)
            if proc is None or proc.poll() is None:
                continue
            if self._stop.is_set():
                return
            with self._proc_lock:
                self.restarts[sid] = self.restarts.get(sid, 0) + 1
                budget_spent = self.restarts[sid] > self.max_restarts
                attempt = self.restarts[sid]
            if budget_spent:
                logger.error(
                    "shard %d died rc=%s; restart budget exhausted",
                    sid, proc.returncode,
                )
                with self._proc_lock:
                    self.procs[sid] = None
                continue
            logger.warning(
                "shard %d died rc=%s; restarting (%d/%d)",
                sid, proc.returncode, attempt, self.max_restarts,
            )
            old = self.front.shards.get(sid)
            if old is not None:
                old.close()
            time.sleep(self.restart_backoff)
            try:
                fresh = self._spawn(sid)
                # wait for readiness, then replay its keyspace slice
                deadline = time.monotonic() + 120.0
                while True:
                    try:
                        self.front.shards[sid].request("ping", None, timeout=5.0)
                        break
                    except Exception:  # noqa: BLE001
                        if (
                            time.monotonic() > deadline
                            or self._stop.is_set()
                            or fresh.poll() is not None
                        ):
                            raise
                        time.sleep(0.1)
                self.front.resync_shard(sid)
            except Exception:  # noqa: BLE001 — retried on the next tick
                logger.exception("shard %d restart failed", sid)

    # ------------------------------------------------------ live resharding

    def _wait_ready(self, sid: int, proc: subprocess.Popen,
                    ready_timeout: float) -> None:
        deadline = time.monotonic() + ready_timeout
        while True:
            try:
                self.front.shards[sid].request("ping", None, timeout=5.0)
                return
            except Exception:  # noqa: BLE001 — keep waiting until deadline
                if time.monotonic() > deadline or self._stop.is_set():
                    raise RuntimeError(
                        f"shard {sid} did not become ready in {ready_timeout}s"
                    ) from None
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"shard {sid} exited rc={proc.returncode} during startup"
                    ) from None
                time.sleep(0.1)

    def restart_counts(self) -> Dict[int, int]:
        """Copy of the per-shard restart counters under their lock — the
        polling surface for tests/scenarios (``restarts`` is GUARDED_BY;
        bare dict reads from the poll loops raced the monitor's bumps)."""
        with self._proc_lock:
            return dict(self.restarts)

    def shard_proc(self, shard_id: int):
        """The shard's live Popen (or None), read under the proc lock.
        Callers may poll()/kill() the returned handle lock-free — only
        the ``procs`` map itself is guarded."""
        with self._proc_lock:
            return self.procs.get(shard_id)

    def rescale(
        self,
        n_new: int,
        ready_timeout: float = 120.0,
        handoff_deadline_s: float = 180.0,
        spawn_args: Optional[Dict[int, List[str]]] = None,
    ) -> Dict:
        """Live split/merge to ``n_new`` shards, NO restarts of existing
        workers: spawn any missing destinations, run the fenced two-phase
        handoff for every moving range (sharding/reshard.py), then retire
        workers above the new count. ``spawn_args`` arms one-shot chaos
        flags (e.g. ``--fault-site reshard.dest.crash:kill:2``) on a
        specific NEW shard's first incarnation — its monitor respawn
        comes up clean, which is exactly the kill-mid-handoff retry path
        the resharding scenario drives."""
        from .reshard import ReshardCoordinator
        from .ring import HashRing

        if not self._rescale_busy.acquire(blocking=False):
            raise RuntimeError("a rescale is already in progress")
        try:
            return self._rescale_step(
                n_new, ready_timeout, handoff_deadline_s, spawn_args,
                ReshardCoordinator, HashRing,
            )
        finally:
            self._rescale_busy.release()

    def _rescale_step(
        self, n_new, ready_timeout, handoff_deadline_s, spawn_args,
        ReshardCoordinator, HashRing,
    ) -> Dict:
        n_old = self.n_shards
        if n_new == n_old:
            return {"from_shards": n_old, "to_shards": n_new, "moves": 0}
        if n_new < 1:
            raise ValueError("n_new must be >= 1")
        new_ring = HashRing(n_new)
        # the front spans the union while ranges are in flight (health,
        # batch triage, and the scatter pool all index by shard id)
        self.front.n_shards = max(n_old, n_new)
        for sid in range(n_old, n_new):
            extra = (spawn_args or {}).get(sid)
            proc = self._spawn(sid, extra_args=extra)
            with self._proc_lock:
                self.restarts.setdefault(sid, 0)
            self._wait_ready(sid, proc, ready_timeout)
            # seed the empty destination with namespaces (it owns no keys
            # yet, so this is broadcast-state only + a no-op prune)
            self.front.resync_shard(sid)
        coordinator = ReshardCoordinator(self.front)
        report = coordinator.rescale(new_ring, deadline_s=handoff_deadline_s)
        for sid in range(n_new, n_old):
            handle = self.front.shards.pop(sid, None)
            if handle is not None:
                handle.close()
            with self._proc_lock:
                proc = self.procs.pop(sid, None)
                self.restarts.pop(sid, None)
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5.0)
        self.n_shards = n_new
        return report

    # -------------------------------------------------------------- shutdown

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        for sid, handle in list(self.front.shards.items()):
            try:
                handle.close()
            except Exception:  # noqa: BLE001
                pass
        deadline = time.monotonic() + timeout
        with self._proc_lock:
            procs = [p for p in self.procs.values() if p is not None]
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)


__all__ = ["ShardSupervisor"]
