"""Shard worker supervisor: spawn, monitor, restart, resync.

``cli.py serve --shards N`` builds one of these. Each worker is a real
OS process (``python -m kube_throttler_tpu.sharding.worker``) connected
over an inherited socketpair — SIGKILLing a worker is exactly the chaos
case the kill-a-shard smoke drives, and the monitor turns it into:
mark down (front degrades fail-safe) → respawn → full resync from the
front's merged store (replay + prune) → shard recomputes and re-pushes
every status (no lost flips).

Fleet modes (ROADMAP 2(b), "from one wide host to a fleet"):

- ``transport="tcp"`` — children still spawn locally but serve the
  framed protocol over TCP (``--listen 127.0.0.1:0``; the bound port
  rendezvous is an atomically-written ``--port-file``, race-free even
  with ephemeral ports). The front talks :class:`~.ipc.TcpShardClient`.
- ``remote_workers={sid: "host:port"}`` — those shards are NOT spawned:
  somebody else runs them (another host, a StatefulSet pod). The
  supervisor only dials them; there is no process to babysit.

The monitor distinguishes **process died** (``proc.poll()`` — respawn +
resync) from **connection lost** (``on_down`` while the process is
alive, or any remote worker): the TCP client reconnects on its own with
jittered-exponential backoff and the heal path (``on_up``) runs the
epoch-bump + resync — a transient partition never triggers a spurious
local restart.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..utils.lockorder import guard_attrs, make_lock
from .front import AdmissionFront
from .ipc import ShardClient, TcpShardClient
from .shmring import ShmEventLane, ShmRingWriter, shm_available, sweep_segments

logger = logging.getLogger(__name__)


@guard_attrs
class ShardSupervisor:
    """Spawns and babysits ``n_shards`` worker processes for a front."""

    # the proc/restart tables are shared between the spawning thread
    # (start), the monitor thread, and stop() — snapshot under the lock,
    # operate on locals (never hold it across a spawn or a sleep)
    GUARDED_BY = {
        "procs": "self._proc_lock",
        "restarts": "self._proc_lock",
        "conn_lost": "self._proc_lock",
        "_backoffs": "self._proc_lock",
        "_last_backoff": "self._proc_lock",
        "_suspended": "self._proc_lock",
        "_shm_seq": "self._proc_lock",
    }

    def __init__(
        self,
        front: AdmissionFront,
        name: str = "kube-throttler",
        target_scheduler: str = "my-scheduler",
        use_device: bool = True,
        data_dir: Optional[str] = None,
        ingest_batch="adaptive",
        restart_backoff: float = 0.5,
        restart_backoff_cap: float = 30.0,
        max_restarts: int = 10,
        worker_args: Optional[List[str]] = None,
        per_shard_args: Optional[Dict[int, List[str]]] = None,
        env: Optional[dict] = None,
        transport: str = "socketpair",
        remote_workers: Optional[Dict[int, str]] = None,
        auth_key: Optional[bytes] = None,
    ):
        if transport not in ("socketpair", "tcp"):
            raise ValueError(f"unknown shard transport {transport!r}")
        self.front = front
        # the build-metrics flush samples backoff_seconds() through this
        # (register_build_metrics — the front registers the family before
        # any supervisor exists, so the wiring is late-bound)
        front.supervisor_ref = self
        self.n_shards = front.n_shards
        self.name = name
        self.target_scheduler = target_scheduler
        self.use_device = use_device
        self.data_dir = data_dir
        self.ingest_batch = ingest_batch
        self.restart_backoff = restart_backoff
        # crash-loop guard ceiling: per-shard restart delays grow
        # jittered-exponentially (PR 1 Backoff) from restart_backoff up
        # to this cap, and reset once a restarted shard resyncs healthy —
        # a worker dying on a version refusal or bad config paces out
        # instead of hot-spinning through its restart budget
        self.restart_backoff_cap = max(float(restart_backoff_cap),
                                       float(restart_backoff))
        self.max_restarts = max_restarts
        self.worker_args = list(worker_args or [])
        # one-shot per-shard args for each shard's FIRST incarnation only
        # (chaos rules that must not re-arm on a monitor respawn)
        self.per_shard_args: Dict[int, List[str]] = dict(per_shard_args or {})
        self.env = env
        self.transport = transport
        # shards somebody else runs (cross-host fleet): dialed, never
        # spawned, never restarted — their heal path is reconnect+resync
        self.remote_workers: Dict[int, str] = dict(remote_workers or {})
        # fleet frame-auth PSK (HMAC per frame, ipc.py trust boundary):
        # used by every TcpShardClient and exported to spawned TCP
        # children via $KT_SHARD_AUTH_KEY so both ends of a local lane
        # run the same keyed framing the remote workers require
        self.auth_key = auth_key
        self._rendezvous_dir: Optional[str] = None
        self._port_seq = 0
        # per-incarnation shm ring generation: a respawned worker gets a
        # FRESH segment (the crashed reader may have died mid-frame; a
        # fresh ring + fresh encoder string table is the resync story)
        self._shm_seq = 0
        self._proc_lock = make_lock("shard.supervisor.procs")
        self.procs: Dict[int, subprocess.Popen] = {}
        self.restarts: Dict[int, int] = {i: 0 for i in range(self.n_shards)}
        self.conn_lost: Dict[int, int] = {i: 0 for i in range(self.n_shards)}
        # per-shard restart pacing state (crash-loop guard) + the shards
        # a rolling_restart() currently owns (the monitor must not race
        # the roll's own bounce with a second restart)
        self._backoffs: Dict[int, object] = {}
        self._last_backoff: Dict[int, float] = {i: 0.0 for i in range(self.n_shards)}
        self._suspended: set = set()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # one rescale at a time: concurrent callers fail fast (two ring
        # retargets would fight over the front's single transition
        # router); callers wanting a scale PATH sequence steps themselves
        self._rescale_busy = threading.Lock()

    # ------------------------------------------------------------- spawning

    def _base_argv(self, shard_id: int) -> List[str]:
        argv = [
            sys.executable, "-m", "kube_throttler_tpu.sharding.worker",
            "--shard-id", str(shard_id),
            "--shards", str(self.n_shards),
            "--name", self.name,
            "--target-scheduler-name", self.target_scheduler,
            "--ingest-batch", str(self.ingest_batch),
        ]
        if not self.use_device:
            argv.append("--no-device")
        if self.data_dir:
            argv += ["--data-dir", os.path.join(self.data_dir, f"shard-{shard_id}")]
        return argv

    def _extra_argv(self, shard_id: int, extra_args: Optional[List[str]]) -> List[str]:
        argv = list(self.worker_args)
        # one-shot args (a chaos rule armed for THIS incarnation only:
        # a monitor respawn after the armed kill must come up clean,
        # not re-arm the same crash forever)
        if extra_args is None:
            extra_args = self.per_shard_args.pop(shard_id, None)
        if extra_args:
            argv += list(extra_args)
        return argv

    def _child_env(self) -> dict:
        env = dict(os.environ if self.env is None else self.env)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.auth_key is not None:
            env["KT_SHARD_AUTH_KEY"] = self.auth_key.decode("utf-8")
        # rolling-upgrade skew knobs (version.py): reach children even
        # when a custom env snapshot predates the harness exporting them
        # (tools/upgradetest.py re-masks capabilities between bounces)
        for var in ("KT_PROTO_CAPS_MASK", "KT_PROTO_MAJOR"):
            if var in os.environ:
                env.setdefault(var, os.environ[var])
        return env

    def _tcp_client(self, shard_id: int, host: str, port: int) -> TcpShardClient:
        return TcpShardClient(
            shard_id,
            host,
            port,
            on_push=self.front.apply_status_push,
            on_down=self._on_shard_down,
            on_up=self._on_shard_up,
            faults=self.front.faults,
            default_deadline=self.front.rpc_deadline,
            deadlines=self.front.rpc_deadlines,
            auth_key=self.auth_key,
        )

    def _attach_remote(self, shard_id: int) -> None:
        """Dial a worker somebody else runs (``remote_workers``): no
        process, no restarts — connection loss is the client's problem
        (backoff + reconnect + resync), never the monitor's."""
        host, _, port = self.remote_workers[shard_id].rpartition(":")
        client = self._tcp_client(shard_id, host or "127.0.0.1", int(port))
        self.front.attach_shard(shard_id, client)
        return None

    def _spawn_tcp(
        self, shard_id: int, extra_args: Optional[List[str]] = None
    ) -> subprocess.Popen:
        """Spawn a local child serving TCP (``--listen 127.0.0.1:0``) and
        dial it. The kernel picks the port; the child publishes it via an
        atomically-renamed port file — no parse-the-stdout races."""
        if self._rendezvous_dir is None:
            self._rendezvous_dir = tempfile.mkdtemp(prefix="kt-shard-ports-")
        self._port_seq += 1
        port_file = os.path.join(
            self._rendezvous_dir, f"shard-{shard_id}.{self._port_seq}.port"
        )
        argv = self._base_argv(shard_id) + [
            "--listen", "127.0.0.1:0",
            "--port-file", port_file,
        ] + self._extra_argv(shard_id, extra_args)
        proc = subprocess.Popen(
            argv,
            env=self._child_env(),
            stdout=subprocess.DEVNULL if self._child_env().get("KT_SHARD_QUIET") else None,
            stderr=None,
        )
        try:
            hostport = self._await_port_file(port_file, proc, timeout=120.0)
            host, _, port = hostport.rpartition(":")
            client = self._tcp_client(shard_id, host, int(port))
        except BaseException:
            proc.kill()
            raise
        with self._proc_lock:
            self.procs[shard_id] = proc
        self.front.attach_shard(shard_id, client)
        return proc

    @staticmethod
    def _await_port_file(path: str, proc: subprocess.Popen, timeout: float) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    text = fh.read().strip()
                if text:
                    return text
            except OSError:
                pass
            if proc.poll() is not None:
                raise RuntimeError(
                    f"worker exited rc={proc.returncode} before publishing "
                    "its port"
                )
            time.sleep(0.05)
        raise RuntimeError(f"no port file at {path} within {timeout}s")

    def _spawn(
        self, shard_id: int, extra_args: Optional[List[str]] = None
    ) -> Optional[subprocess.Popen]:
        if shard_id in self.remote_workers:
            return self._attach_remote(shard_id)
        if self.transport == "tcp":
            return self._spawn_tcp(shard_id, extra_args)
        parent_sock, child_sock = socket.socketpair()
        ring_writer: Optional[ShmRingWriter] = None
        door_rfd = -1
        try:
            argv = (
                self._base_argv(shard_id)
                + ["--ipc-fd", str(child_sock.fileno())]
                + self._extra_argv(shard_id, extra_args)
            )
            if os.environ.get("KT_SHM_RING", "1") != "0" and shm_available():
                # zero-copy event lane: a per-incarnation SPSC ring the
                # child attaches read-only by name, doorbelled over an
                # inherited pipe. Any failure here degrades to the plain
                # pickle socketpair — the ring is a fast path, never a
                # spawn dependency.
                with self._proc_lock:
                    self._shm_seq += 1
                    gen = self._shm_seq
                door_wfd = -1
                try:
                    door_rfd, door_wfd = os.pipe()
                    ring_writer = ShmRingWriter(
                        f"kt_evt_{os.getpid()}_{shard_id}_{gen}",
                        slots=int(os.environ.get("KT_SHM_RING_SLOTS", "1024")),
                        arena_bytes=int(
                            os.environ.get("KT_SHM_RING_ARENA", str(4 << 20))
                        ),
                        doorbell_wfd=door_wfd,
                        faults=self.front.faults,
                    )
                except Exception:  # noqa: BLE001 — fall back to pickle
                    logger.warning(
                        "shard %d: shm ring unavailable, falling back to "
                        "pickle socketpair", shard_id, exc_info=True,
                    )
                    if door_rfd >= 0:
                        os.close(door_rfd)
                    if door_wfd >= 0 and ring_writer is None:
                        os.close(door_wfd)
                    door_rfd = -1
                    ring_writer = None
            if ring_writer is not None:
                argv += [
                    "--shm-ring", ring_writer.name,
                    "--shm-doorbell-fd", str(door_rfd),
                ]
            env = self._child_env()
            pass_fds = [child_sock.fileno()]
            if door_rfd >= 0:
                pass_fds.append(door_rfd)
            proc = subprocess.Popen(
                argv,
                pass_fds=pass_fds,
                env=env,
                stdout=subprocess.DEVNULL if env.get("KT_SHARD_QUIET") else None,
                stderr=None,
            )
            child_sock.close()
            if door_rfd >= 0:
                os.close(door_rfd)  # child inherited its copy
                door_rfd = -1
            client = ShardClient(
                shard_id,
                parent_sock,
                on_push=self.front.apply_status_push,
                on_down=self._on_shard_down,
                faults=self.front.faults,
                default_deadline=self.front.rpc_deadline,
                deadlines=self.front.rpc_deadlines,
            )
            if ring_writer is not None:
                client.shm_lane = ShmEventLane(ring_writer)
        except BaseException:
            # a failed exec (or client construction) must not leak the
            # socketpair: each monitor-driven respawn retry would strand
            # two fds, and fd exhaustion then takes down the FRONT — the
            # exact lease-elector leak class from the PR 6 review. Same
            # rule for the ring: close(unlink=True) drops the /dev/shm
            # segment and the doorbell write end.
            parent_sock.close()
            child_sock.close()
            if ring_writer is not None:
                try:
                    ring_writer.close(unlink=True)
                except Exception:  # noqa: BLE001
                    pass
            if door_rfd >= 0:
                os.close(door_rfd)
            raise
        with self._proc_lock:
            self.procs[shard_id] = proc
        self.front.attach_shard(shard_id, client)
        return proc

    def start(self, ready_timeout: float = 120.0) -> None:
        """Spawn every worker and block until each answers a ping (the
        workers compile/prewarm serially on small hosts — be patient)."""
        spawned = [self._spawn(sid) for sid in range(self.n_shards)]
        deadline = time.monotonic() + ready_timeout
        for sid in range(self.n_shards):
            while True:
                try:
                    self.front.shards[sid].request("ping", None, timeout=5.0)
                    break
                except Exception:  # noqa: BLE001 — keep waiting until deadline
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"shard {sid} did not become ready in {ready_timeout}s"
                        ) from None
                    if spawned[sid] is not None and spawned[sid].poll() is not None:
                        raise RuntimeError(
                            f"shard {sid} exited rc={spawned[sid].returncode} "
                            "during startup"
                        ) from None
                    time.sleep(0.1)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="shard-monitor", daemon=True
        )
        self._monitor.start()

    # ------------------------------------------------------------ monitoring

    def _on_shard_down(self, shard_id: int) -> None:
        with self._proc_lock:
            proc = self.procs.get(shard_id)
        if shard_id in self.remote_workers or (
            proc is not None and proc.poll() is None
        ):
            # CONNECTION lost, not a process death: the TCP client is
            # already backing off toward a reconnect, and the heal path
            # (on_up → epoch bump + resync) repairs state. The monitor
            # keys restarts on proc.poll() alone, so a transient
            # partition never triggers a spurious local restart
            with self._proc_lock:
                self.conn_lost[shard_id] = self.conn_lost.get(shard_id, 0) + 1
            logger.warning(
                "shard %d connection lost (worker alive; reconnecting)",
                shard_id,
            )
            return
        logger.warning("shard %d transport down", shard_id)

    def _on_shard_up(self, shard_id: int) -> None:
        """TCP heal path: the client reconnected on its own (the worker
        never died, it was partitioned). Epoch-bump + full resync — the
        PR 9 no-lost-flips repair, fenced against the stale past."""
        logger.info("shard %d reconnected; resyncing", shard_id)
        try:
            self.front.resync_shard(shard_id)
        except Exception:  # noqa: BLE001 — the reconnector must survive
            logger.exception("shard %d post-reconnect resync failed", shard_id)
            handle = self.front.shards.get(shard_id)
            if handle is not None:
                handle.mark_dirty()

    def connection_losses(self) -> Dict[int, int]:
        """Copy of the per-shard connection-loss counters (the monitor's
        'connection lost ≠ process died' evidence; tests/scenarios poll
        this next to restart_counts)."""
        with self._proc_lock:
            return dict(self.conn_lost)

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.2):
            # loop-level routing (threads checker): the monitor IS the
            # restart policy — if it died of an unexpected exception, dead
            # shards would stay dead forever while the front reports
            # degraded and nothing ever repairs it
            try:
                self._monitor_tick()
            except Exception:  # noqa: BLE001 — keep the restart policy alive
                logger.exception("shard monitor tick failed")

    def _monitor_tick(self) -> None:
        with self._proc_lock:
            sids = sorted(self.procs)
        for sid in sids:
            with self._proc_lock:
                if sid in self._suspended:
                    continue  # rolling_restart() owns this bounce
                proc = self.procs.get(sid)
            if proc is None or proc.poll() is None:
                continue
            if self._stop.is_set():
                return
            with self._proc_lock:
                self.restarts[sid] = self.restarts.get(sid, 0) + 1
                budget_spent = self.restarts[sid] > self.max_restarts
                attempt = self.restarts[sid]
            if budget_spent:
                logger.error(
                    "shard %d died rc=%s; restart budget exhausted",
                    sid, proc.returncode,
                )
                with self._proc_lock:
                    self.procs[sid] = None
                continue
            logger.warning(
                "shard %d died rc=%s; restarting (%d/%d)",
                sid, proc.returncode, attempt, self.max_restarts,
            )
            old = self.front.shards.get(sid)
            if old is not None:
                old.close()
            time.sleep(self._restart_delay(sid))
            if self._stop.is_set():
                return
            try:
                fresh = self._spawn(sid)
                # wait for readiness, then replay its keyspace slice
                deadline = time.monotonic() + 120.0
                while True:
                    try:
                        self.front.shards[sid].request("ping", None, timeout=5.0)
                        break
                    except Exception:  # noqa: BLE001
                        if (
                            time.monotonic() > deadline
                            or self._stop.is_set()
                            or fresh.poll() is not None
                        ):
                            raise
                        time.sleep(0.1)
                self.front.resync_shard(sid)
                self._reset_backoff(sid)
            except Exception:  # noqa: BLE001 — retried on the next tick
                logger.exception("shard %d restart failed", sid)

    def _restart_delay(self, sid: int) -> float:
        """Next restart delay for a shard that just died: per-shard
        jittered-exponential growth (PR 1 Backoff) from restart_backoff
        to restart_backoff_cap. A shard whose restart resyncs healthy
        resets to the base — only consecutive deaths pace out."""
        from ..client.transport import Backoff

        with self._proc_lock:
            bo = self._backoffs.get(sid)
            if bo is None:
                bo = Backoff(base=self.restart_backoff,
                             cap=self.restart_backoff_cap)
                self._backoffs[sid] = bo
            delay = bo.next()
            self._last_backoff[sid] = delay
        return delay

    def _reset_backoff(self, sid: int) -> None:
        with self._proc_lock:
            bo = self._backoffs.get(sid)
            if bo is not None:
                bo.reset()
            self._last_backoff[sid] = 0.0

    def backoff_seconds(self) -> Dict[int, float]:
        """Per-shard most-recent restart-backoff delay, 0.0 when healthy
        (the kube_throttler_shard_restart_backoff_seconds gauge samples
        this at scrape; tests pin growth-then-reset)."""
        with self._proc_lock:
            return dict(self._last_backoff)

    # ------------------------------------------------------ live resharding

    def _wait_ready(self, sid: int, proc: Optional[subprocess.Popen],
                    ready_timeout: float) -> None:
        deadline = time.monotonic() + ready_timeout
        while True:
            try:
                self.front.shards[sid].request("ping", None, timeout=5.0)
                return
            except Exception:  # noqa: BLE001 — keep waiting until deadline
                if time.monotonic() > deadline or self._stop.is_set():
                    raise RuntimeError(
                        f"shard {sid} did not become ready in {ready_timeout}s"
                    ) from None
                if proc is not None and proc.poll() is not None:
                    raise RuntimeError(
                        f"shard {sid} exited rc={proc.returncode} during startup"
                    ) from None
                time.sleep(0.1)

    def restart_counts(self) -> Dict[int, int]:
        """Copy of the per-shard restart counters under their lock — the
        polling surface for tests/scenarios (``restarts`` is GUARDED_BY;
        bare dict reads from the poll loops raced the monitor's bumps)."""
        with self._proc_lock:
            return dict(self.restarts)

    def shard_proc(self, shard_id: int):
        """The shard's live Popen (or None), read under the proc lock.
        Callers may poll()/kill() the returned handle lock-free — only
        the ``procs`` map itself is guarded."""
        with self._proc_lock:
            return self.procs.get(shard_id)

    def rolling_restart(
        self,
        ready_timeout: float = 120.0,
        settle_timeout: float = 60.0,
        shard_ids: Optional[List[int]] = None,
        gate=None,
    ) -> Dict:
        """Bounce every local worker ONE AT A TIME behind a resync
        barrier — the orchestrated roll of a live upgrade (new binary,
        new env, new capability mask). Each bounce: suspend the monitor's
        restart policy for that shard, terminate the old incarnation (the
        front degrades fail-safe for exactly that keyspace slice), spawn
        the replacement, wait ready, resync (replay + prune + flip
        re-publication), then hold at the barrier until the shard reports
        healthy (alive + not dirty) before the next bounce begins — the
        roll never darkens two keyspaces at once.

        ``gate`` (optional, ``gate(shard_id) -> falsy | reason``) runs
        after every bounce; a truthy reason ABORTS the roll with the rest
        of the fleet still on its old incarnation. Remote workers are
        skipped (somebody else's process; roll them from their own host).
        Returns ``{"bounces": [...], "aborted": None | {...}}``."""
        if not self._rescale_busy.acquire(blocking=False):
            raise RuntimeError("a rescale or rolling restart is already in progress")
        try:
            return self._rolling_restart_locked(
                ready_timeout, settle_timeout, shard_ids, gate
            )
        finally:
            self._rescale_busy.release()

    def _rolling_restart_locked(
        self, ready_timeout, settle_timeout, shard_ids, gate
    ) -> Dict:
        sids = sorted(range(self.n_shards) if shard_ids is None else shard_ids)
        report: Dict = {"bounces": [], "aborted": None}
        for sid in sids:
            if sid in self.remote_workers:
                continue
            t0 = time.monotonic()
            with self._proc_lock:
                self._suspended.add(sid)
                proc = self.procs.get(sid)
            try:
                old = self.front.shards.get(sid)
                if old is not None:
                    old.close()
                if proc is not None and proc.poll() is None:
                    proc.terminate()
                    try:
                        proc.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait(timeout=5.0)
                fresh = self._spawn(sid)
                self._wait_ready(sid, fresh, ready_timeout)
                self.front.resync_shard(sid)
                self._settle_shard(sid, settle_timeout)
            except Exception as e:  # noqa: BLE001 — abort, don't cascade
                # abort the roll: the rest of the fleet stays on its old
                # incarnation, and the monitor resumes babysitting this
                # shard once it leaves the suspended set below
                logger.exception("rolling restart aborted at shard %d", sid)
                report["aborted"] = {
                    "shard": sid,
                    "reason": f"{e.__class__.__name__}: {e}",
                }
                break
            finally:
                with self._proc_lock:
                    self._suspended.discard(sid)
            self._reset_backoff(sid)
            bounce = {"shard": sid, "seconds": time.monotonic() - t0}
            if gate is not None:
                breach = gate(sid)
                if breach:
                    bounce["gate"] = str(breach)
                    report["bounces"].append(bounce)
                    report["aborted"] = {
                        "shard": sid, "reason": f"gate breach: {breach}",
                    }
                    return report
            report["bounces"].append(bounce)
        return report

    def _settle_shard(self, sid: int, settle_timeout: float) -> None:
        """The resync barrier: a bounced shard must report healthy
        (alive, resynced, not dirty) before the roll moves on — taking a
        second worker down while the first still warms is the
        double-failure the one-at-a-time discipline exists to avoid."""
        deadline = time.monotonic() + settle_timeout
        while time.monotonic() < deadline:
            handle = self.front.shards.get(sid)
            if handle is not None and handle.alive and not handle.is_dirty():
                return
            if self._stop.is_set():
                raise RuntimeError("supervisor stopping mid-roll")
            time.sleep(0.05)
        raise RuntimeError(
            f"shard {sid} did not settle within {settle_timeout}s of its bounce"
        )

    def rescale(
        self,
        n_new: int,
        ready_timeout: float = 120.0,
        handoff_deadline_s: float = 180.0,
        spawn_args: Optional[Dict[int, List[str]]] = None,
    ) -> Dict:
        """Live split/merge to ``n_new`` shards, NO restarts of existing
        workers: spawn any missing destinations, run the fenced two-phase
        handoff for every moving range (sharding/reshard.py), then retire
        workers above the new count. ``spawn_args`` arms one-shot chaos
        flags (e.g. ``--fault-site reshard.dest.crash:kill:2``) on a
        specific NEW shard's first incarnation — its monitor respawn
        comes up clean, which is exactly the kill-mid-handoff retry path
        the resharding scenario drives."""
        from .reshard import ReshardCoordinator
        from .ring import HashRing

        if not self._rescale_busy.acquire(blocking=False):
            raise RuntimeError("a rescale is already in progress")
        try:
            return self._rescale_step(
                n_new, ready_timeout, handoff_deadline_s, spawn_args,
                ReshardCoordinator, HashRing,
            )
        finally:
            self._rescale_busy.release()

    def _rescale_step(
        self, n_new, ready_timeout, handoff_deadline_s, spawn_args,
        ReshardCoordinator, HashRing,
    ) -> Dict:
        n_old = self.n_shards
        if n_new == n_old:
            return {"from_shards": n_old, "to_shards": n_new, "moves": 0}
        if n_new < 1:
            raise ValueError("n_new must be >= 1")
        new_ring = HashRing(n_new)
        # the front spans the union while ranges are in flight (health,
        # batch triage, and the scatter pool all index by shard id)
        self.front.n_shards = max(n_old, n_new)
        for sid in range(n_old, n_new):
            extra = (spawn_args or {}).get(sid)
            proc = self._spawn(sid, extra_args=extra)
            with self._proc_lock:
                self.restarts.setdefault(sid, 0)
            self._wait_ready(sid, proc, ready_timeout)
            # seed the empty destination with namespaces (it owns no keys
            # yet, so this is broadcast-state only + a no-op prune)
            self.front.resync_shard(sid)
        coordinator = ReshardCoordinator(self.front)
        report = coordinator.rescale(new_ring, deadline_s=handoff_deadline_s)
        for sid in range(n_new, n_old):
            handle = self.front.shards.pop(sid, None)
            if handle is not None:
                handle.close()
            with self._proc_lock:
                proc = self.procs.pop(sid, None)
                self.restarts.pop(sid, None)
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5.0)
        self.n_shards = n_new
        return report

    # -------------------------------------------------------------- shutdown

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        for sid, handle in list(self.front.shards.items()):
            try:
                handle.close()
            except Exception:  # noqa: BLE001
                pass
        deadline = time.monotonic() + timeout
        with self._proc_lock:
            procs = [p for p in self.procs.values() if p is not None]
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        # backstop: handle.close() unlinks each ring, but a handle that
        # never attached (spawn raced stop) or a writer whose unlink was
        # fault-injected away would strand a /dev/shm segment — sweep
        # everything this supervisor process created
        leaked = sweep_segments(f"kt_evt_{os.getpid()}_")
        if leaked:
            logger.warning("swept %d leaked shm segment(s): %s",
                           len(leaked), ", ".join(leaked))


__all__ = ["ShardSupervisor"]
