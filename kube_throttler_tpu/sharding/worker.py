"""One keyspace shard: a full engine vertical behind the IPC server.

Each worker process owns its slice of the Throttle/ClusterThrottle
keyspace end to end — store + SelectorIndex + journal + snapshot/
recovery + device planes + micro-batch ingest + both controllers — and
answers the front's scatter-gather RPCs. Nothing is shared between
workers: no locks, no memory, no GIL. PR 6's fenced leadership runs
independently per shard when a data dir is given (per-shard epoch file,
per-shard journal fencing; a standby for shard *i* replicates from
shard *i* alone).

Run as a process:

    python -m kube_throttler_tpu.sharding.worker \
        --shard-id 0 --shards 4 --ipc-fd 3 [--data-dir DIR] [--no-device]

The supervisor passes the socketpair fd; everything else arrives over
the socket (events to ingest, RPCs to answer).

Two-phase reserve, shard side: ``reserve_prepare`` performs the real
reserve on this shard's matching throttles and parks the transaction in
a pending table; ``txn_commit`` finalizes (drops the table entry, the
reservation stays); ``txn_abort`` unreserves. A prepared transaction
whose front died before deciding is ABORTED by the reaper once it ages
past ``prepare_ttl`` — a prepare-crash can never leave an orphan
reservation (tests/test_sharding.py pins this).
"""

from __future__ import annotations

import argparse
import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.lockorder import guard_attrs, make_lock

logger = logging.getLogger(__name__)

# control verbs carried in-stream with store ops (front → shard)
RESYNC_PRUNE = "__prune__"


@guard_attrs
class ShardCore:
    """The shard's engine stack + RPC dispatch, transport-agnostic.

    ``push(items)`` (settable) receives ``[(kind, obj), ...]`` status
    events the shard's controllers wrote — the worker main sends them to
    the front as ``push`` frames; tests wire it straight into the
    front's applier.
    """

    GUARDED_BY = {
        "_pending_txns": "self._txn_lock",
        "_pending_gangs": "self._txn_lock",
        "_gang_members": "self._txn_lock",
        "reaped_txns": "self._txn_lock",
        "_push_buf": "self._push_lock",
    }

    def __init__(
        self,
        shard_id: int,
        n_shards: int,
        name: str = "kube-throttler",
        target_scheduler: str = "my-scheduler",
        use_device: bool = True,
        data_dir: Optional[str] = None,
        ingest_batch="adaptive",
        faults=None,
        prepare_ttl: float = 30.0,
        snapshot_every: int = 5000,
    ):
        from ..engine.store import Store
        from ..engine.ingest import MicroBatchIngest
        from ..plugin import KubeThrottler, decode_plugin_args

        self.shard_id = shard_id
        self.n_shards = n_shards
        self.faults = faults
        self.prepare_ttl = prepare_ttl
        self.store = Store()
        self.journal = None
        self.recovery = None
        self.snapshotter = None
        self.epoch = None
        self.ha = None
        if data_dir:
            from ..engine.recovery import RecoveryManager
            from ..engine.replication import FencingEpoch, HaCoordinator
            from ..engine.snapshot import SnapshotManager

            os.makedirs(data_dir, exist_ok=True)
            self.recovery = RecoveryManager(data_dir)
            self.journal = self.recovery.recover_store(self.store)
            self.snapshotter = SnapshotManager(data_dir, self.store)
            # PR 6 fenced leadership, per shard: this process claims a
            # term for ITS keyspace slice; journal appends and snapshot
            # cuts refuse once the epoch goes stale
            self.epoch = FencingEpoch(data_dir)
            self.epoch.observe(self.recovery.report.epoch)
            self.journal.fencing = self.epoch
            self.snapshotter.fencing = self.epoch
            self.ha = HaCoordinator(
                self.epoch, role="leader", journal=self.journal,
                snapshotter=self.snapshotter,
            )
            self.ha.become_leader()
        self.plugin = KubeThrottler(
            decode_plugin_args(
                {"name": name, "targetSchedulerName": target_scheduler}
            ),
            self.store,
            use_device=use_device,
            start_workers=True,
        )
        if self.recovery is not None:
            caches = {
                "throttle": self.plugin.throttle_ctr.cache,
                "clusterthrottle": self.plugin.cluster_throttle_ctr.cache,
            }
            self.recovery.restore_reservations(caches)
            self.plugin.gang.journal = self.journal
            self.recovery.restore_gangs(self.plugin.gang, self.journal)
            self.recovery.reconcile(
                self.plugin.informers,
                device_manager=self.plugin.device_manager,
                enqueue={
                    "throttle": self.plugin.throttle_ctr.enqueue,
                    "clusterthrottle": self.plugin.cluster_throttle_ctr.enqueue,
                },
            )
            self.snapshotter.reservations = caches
            self.snapshotter.gang_ledger = self.plugin.gang
            self.snapshotter.device_manager = self.plugin.device_manager
            self.snapshotter.bind_journal(self.journal, every_lines=snapshot_every)
        if ingest_batch in ("off", "none", "", None):
            ingest_batch = 1
        self.pipeline = MicroBatchIngest(
            self.store, batch_policy=ingest_batch, faults=faults
        )
        # two-phase reserve bookkeeping
        self._txn_lock = make_lock(f"shard.txn.{shard_id}")
        self._pending_txns: Dict[str, Tuple[object, float]] = {}  # txn → (pod, t)
        self._pending_gangs: Dict[str, Tuple[str, float]] = {}  # txn → (group, t)
        # NON-owner shards hold a gang's member reservations as plain
        # reservations (the authoritative ledger record lives only on the
        # group's hash-owner shard): group → member pods, so a rollback
        # releases them without a ledger
        self._gang_members: Dict[str, List[object]] = {}
        self.reaped_txns = 0
        # status push plumbing: handlers append under the push lock (they
        # run inside the store lock and must stay informer-cheap); the
        # pusher thread flushes batches to ``push``
        self.push = None  # set by the transport wrapper
        self._push_lock = make_lock(f"shard.push.{shard_id}")
        self._push_cond = threading.Condition(self._push_lock)
        self._push_buf: List[Tuple[str, object]] = []
        self._stop = threading.Event()
        for kind in ("Throttle", "ClusterThrottle"):
            self.store.add_event_handler(kind, self._on_status_event, replay=False)
        self._pusher = threading.Thread(
            target=self._push_loop, name=f"shard{shard_id}-push", daemon=True
        )
        self._pusher.start()
        self._reaper = threading.Thread(
            target=self._reap_loop, name=f"shard{shard_id}-reaper", daemon=True
        )
        self._reaper.start()

    # ----------------------------------------------------------- status push

    def _on_status_event(self, event) -> None:
        from ..engine.store import EventType

        if event.type is not EventType.MODIFIED or event.old_obj is None:
            return
        if event.obj.status == event.old_obj.status:
            return  # spec echo routed by the front — not ours to re-publish
        with self._push_cond:
            self._push_buf.append((event.kind, event.obj))
            self._push_cond.notify()

    def _push_loop(self) -> None:
        while not self._stop.is_set():
            # loop-level routing (threads checker): a pusher killed by an
            # unexpected exception would silently stop ALL status flow to
            # the front while every probe stayed green — the PR 6 silent-
            # replicator-death class, shard-flavored
            try:
                with self._push_cond:
                    while not self._push_buf and not self._stop.is_set():
                        self._push_cond.wait(0.2)
                    buf, self._push_buf = self._push_buf, []
                if buf and self.push is not None:
                    try:
                        self.push(buf)
                    except Exception:  # noqa: BLE001 — front gone; supervisor acts
                        logger.warning("shard %d: status push failed", self.shard_id,
                                       exc_info=True)
            except Exception:  # noqa: BLE001 — keep the pusher alive
                logger.exception("shard %d: push loop error", self.shard_id)
                self._stop.wait(0.05)

    # ---------------------------------------------------------------- events

    def handle_events(self, ops: Sequence[Tuple[str, str, object]]) -> None:
        """Apply a routed event batch through the micro-batch pipeline.
        Control ops (resync prune) are handled in-stream, in order."""
        if self.faults is not None:
            fault = self.faults.check("shard.worker.kill")
            if fault is not None and fault.mode == "kill":
                fault.kill()
        batch: List[Tuple[str, str, object]] = []
        for op in ops:
            if op[0] == RESYNC_PRUNE:
                if batch:
                    self.pipeline.submit_many(batch)
                    batch = []
                self._prune(op[2])
                continue
            batch.append(op)
        if batch:
            self.pipeline.submit_many(batch)

    def _prune(self, want: Dict[str, Sequence[str]]) -> None:
        """Resync epilogue: everything this shard holds that the front's
        replay did not name was deleted while the shard was down — drop
        it (the StandbyReplicator bootstrap rule, applied over IPC)."""
        from ..engine.store import key_of

        self.pipeline.flush(timeout=30.0)
        ops = []
        for kind, lister in (
            ("Pod", self.store.list_pods),
            ("Throttle", self.store.list_throttles),
            ("ClusterThrottle", self.store.list_cluster_throttles),
            ("Namespace", self.store.list_namespaces),
        ):
            have = set(want.get(kind, ()))
            for obj in lister():
                if key_of(kind, obj) not in have:
                    ops.append(("delete", kind, key_of(kind, obj)))
        if ops:
            self.store.apply_events(ops)

    # ------------------------------------------------------------------- RPC

    def rpc(self, op: str, payload) -> Tuple[bool, object]:
        """Dispatch one RPC; returns (ok, body). Never raises."""
        try:
            handler = getattr(self, f"_rpc_{op}", None)
            if handler is None:
                return False, f"unknown rpc {op!r}"
            return True, handler(payload)
        except Exception as e:  # noqa: BLE001 — reported to the front
            return False, f"{e.__class__.__name__}: {e}"

    def _rpc_ping(self, _payload):
        return {
            "shard": self.shard_id,
            "epoch": self.epoch.current() if self.epoch is not None else 0,
        }

    def _rpc_pre_filter(self, pod):
        """Shard-local admission check: both kinds' ``check_throttled``
        against this shard's throttles. Returns per-kind name lists —
        the front AND-merges and composes the reason strings."""
        out = {}
        for kind, ctr in (
            ("throttle", self.plugin.throttle_ctr),
            ("clusterthrottle", self.plugin.cluster_throttle_ctr),
        ):
            try:
                active, insufficient, exceeds, _ = ctr.check_throttled(pod, False)
            except Exception as e:  # noqa: BLE001 — the per-kind error contract
                out[kind] = {"error": str(e)}
                continue
            out[kind] = {
                "active": [t.key for t in active],
                "insufficient": [t.key for t in insufficient],
                "exceeds": [t.key for t in exceeds],
            }
        return out

    def _rpc_pre_filter_batch(self, _payload):
        return self.plugin.pre_filter_batch()

    def _rpc_reserve_prepare(self, payload):
        txn, pod = payload["txn"], payload["pod"]
        status = self.plugin.reserve(pod)
        if not status.is_success():
            raise RuntimeError("; ".join(status.reasons) or "reserve failed")
        with self._txn_lock:
            self._pending_txns[txn] = (pod, time.monotonic())
        return True

    def _rpc_txn_commit(self, payload):
        with self._txn_lock:
            self._pending_txns.pop(payload["txn"], None)
            self._pending_gangs.pop(payload["txn"], None)
        return True

    def _rpc_txn_abort(self, payload):
        with self._txn_lock:
            entry = self._pending_txns.pop(payload["txn"], None)
            gang = self._pending_gangs.pop(payload["txn"], None)
        if entry is not None:
            self.plugin.unreserve(entry[0])
        if gang is not None:
            self._gang_release(gang[0])
        return True

    def _rpc_unreserve(self, pod):
        self.plugin.unreserve(pod)
        return True

    def _rpc_gang_check(self, payload):
        status = self.plugin.pre_filter_gang(payload["group"], payload["pods"])
        return {"code": status.code.value, "reasons": list(status.reasons)}

    def _rpc_gang_prepare(self, payload):
        """Gang prepare. On the group's hash-OWNER shard this is the real
        ledger reserve (all-or-nothing locally, GANG journal stamps, TTL
        authority). On other matching shards the members reserve as plain
        reservations — the ledger record exists on exactly one shard."""
        txn, group, pods = payload["txn"], payload["group"], payload["pods"]
        owner = bool(payload.get("owner", True))
        if owner:
            status = self.plugin.reserve_gang(group, pods)
            if not status.is_success():
                raise RuntimeError("; ".join(status.reasons) or "gang reserve failed")
        else:
            reserved: List[object] = []
            try:
                for pod in pods:
                    st = self.plugin.reserve(pod)
                    if not st.is_success():
                        raise RuntimeError("; ".join(st.reasons) or "member reserve failed")
                    reserved.append(pod)
            except Exception:
                for pod in reserved:
                    self.plugin.unreserve(pod)
                raise
            with self._txn_lock:
                self._gang_members[group] = list(pods)
        with self._txn_lock:
            self._pending_gangs[txn] = (group, time.monotonic())
        return True

    def _gang_release(self, group: str) -> None:
        with self._txn_lock:
            members = self._gang_members.pop(group, None)
        if members is not None:
            for pod in members:
                self.plugin.unreserve(pod)
        self.plugin.unreserve_gang(group)  # no-op where no ledger record

    def _rpc_gang_rollback(self, payload):
        self._gang_release(payload["group"])
        return True

    def _rpc_gang_groups(self, _payload):
        """Group keys with live ledger records on this shard (tests pin
        the one-owner property of the authoritative ledger entry)."""
        return sorted(self.plugin.gang.snapshot_state().keys())

    def _rpc_stats(self, _payload):
        ps = self.pipeline.stats()
        with self._txn_lock:
            reaped = self.reaped_txns
            pending = len(self._pending_txns) + len(self._pending_gangs)
        return {
            "shard": self.shard_id,
            "ingest": ps,
            "workqueues": {
                "throttle": len(self.plugin.throttle_ctr.workqueue),
                "clusterthrottle": len(self.plugin.cluster_throttle_ctr.workqueue),
            },
            "objects": {
                "pods": len(self.store.list_pods()),
                "throttles": len(self.store.list_throttles()),
                "clusterthrottles": len(self.store.list_cluster_throttles()),
            },
            "reaped_txns": reaped,
            "pending_txns": pending,
            "epoch": self.epoch.current() if self.epoch is not None else 0,
        }

    def _rpc_drain(self, payload):
        timeout = float(payload.get("timeout", 5.0)) if payload else 5.0
        flushed = self.pipeline.flush(timeout=timeout)
        return {
            "flushed": flushed,
            "queue": self.pipeline.qsize(),
            "workqueues": {
                "throttle": len(self.plugin.throttle_ctr.workqueue),
                "clusterthrottle": len(self.plugin.cluster_throttle_ctr.workqueue),
            },
            "applied": self.pipeline.stats()["events_applied"],
        }

    # ---------------------------------------------------------------- reaper

    def _reap_loop(self) -> None:
        while not self._stop.wait(min(1.0, self.prepare_ttl / 4 or 1.0)):
            # loop-level routing (threads checker): a dead reaper means
            # orphaned prepares hold reservations forever — silently
            try:
                self.reap_stale_txns()
            except Exception:  # noqa: BLE001 — keep the reaper alive
                logger.exception("shard %d: txn reaper error", self.shard_id)

    def reap_stale_txns(self, now: Optional[float] = None) -> int:
        """Abort prepared transactions older than ``prepare_ttl`` (the
        front died between prepare and commit). Returns aborts done."""
        now = time.monotonic() if now is None else now
        stale_pods, stale_gangs = [], []
        with self._txn_lock:
            for txn, (pod, t0) in list(self._pending_txns.items()):
                if now - t0 >= self.prepare_ttl:
                    stale_pods.append(pod)
                    del self._pending_txns[txn]
            for txn, (group, t0) in list(self._pending_gangs.items()):
                if now - t0 >= self.prepare_ttl:
                    stale_gangs.append(group)
                    del self._pending_gangs[txn]
            self.reaped_txns += len(stale_pods) + len(stale_gangs)
        for pod in stale_pods:
            self.plugin.unreserve(pod)
        for group in stale_gangs:
            self._gang_release(group)
        return len(stale_pods) + len(stale_gangs)

    # ------------------------------------------------------------- lifecycle

    def stop(self) -> None:
        self._stop.set()
        with self._push_cond:
            self._push_cond.notify_all()
        self.pipeline.stop()
        self.plugin.stop()
        if self.snapshotter is not None:
            self.snapshotter.write(reason="shutdown")
        if self.journal is not None:
            self.journal.close()


def serve(core: ShardCore, sock: socket.socket) -> None:
    """The worker's IPC loop: read frames until EOF. Events apply via the
    ingest pipeline (non-blocking); RPCs answer from a small pool so a
    long batch call cannot park the event stream."""
    from concurrent.futures import ThreadPoolExecutor

    from .ipc import read_frame, send_frame

    send_lock = make_lock(f"shard.serve.{core.shard_id}")
    core.push = lambda items: send_frame(sock, send_lock, "push", 0, items)
    pool = ThreadPoolExecutor(max_workers=2, thread_name_prefix="shard-rpc")
    rfile = sock.makefile("rb")

    def answer(rid: int, op: str, payload) -> None:
        result = core.rpc(op, payload)
        try:
            send_frame(sock, send_lock, "res", rid, result)
        except OSError:
            pass  # front went away; the supervisor restarts us if needed

    try:
        while True:
            frame = read_frame(rfile)
            if frame is None:
                return
            mtype, rid, body = frame
            if mtype == "evt":
                core.handle_events(body)
            elif mtype == "req":
                op, payload = body
                pool.submit(answer, rid, op, payload)
    except OSError:
        return
    finally:
        pool.shutdown(wait=False)
        rfile.close()


def main(argv: Optional[List[str]] = None) -> int:
    from ..utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    parser = argparse.ArgumentParser(prog="kube-throttler-shard")
    parser.add_argument("--shard-id", type=int, required=True)
    parser.add_argument("--shards", type=int, required=True)
    parser.add_argument("--ipc-fd", type=int, required=True)
    parser.add_argument("--name", default="kube-throttler")
    parser.add_argument("--target-scheduler-name", default="my-scheduler")
    parser.add_argument("--data-dir", default="")
    parser.add_argument("--no-device", action="store_true")
    parser.add_argument("--ingest-batch", default="adaptive")
    parser.add_argument("--prepare-ttl", type=float, default=30.0)
    parser.add_argument("--fault-seed", type=int, default=0)
    parser.add_argument(
        "--fault-site", default="",
        help="arm one seeded fault rule (site[:mode[:after]]) — the chaos "
        "harness's kill/err injection, e.g. shard.worker.kill:kill:25",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s %(levelname).1s shard{args.shard_id} %(name)s] %(message)s",
    )
    faults = None
    if args.fault_site:
        from ..faults.plan import FaultPlan

        parts = args.fault_site.split(":")
        site = parts[0]
        mode = parts[1] if len(parts) > 1 else "error"
        after = int(parts[2]) if len(parts) > 2 else 0
        faults = FaultPlan(seed=args.fault_seed).rule(
            site, mode=mode, after=after, times=1
        )
    ingest_batch = args.ingest_batch
    if ingest_batch not in ("adaptive", "off", "none", ""):
        ingest_batch = int(ingest_batch)
    core = ShardCore(
        args.shard_id,
        args.shards,
        name=args.name,
        target_scheduler=args.target_scheduler_name,
        use_device=not args.no_device,
        data_dir=args.data_dir or None,
        ingest_batch=ingest_batch,
        faults=faults,
        prepare_ttl=args.prepare_ttl,
    )
    sock = socket.socket(fileno=args.ipc_fd)
    print(f"shard {args.shard_id}/{args.shards} ready", flush=True)
    try:
        serve(core, sock)
    finally:
        core.stop()
        sock.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
