"""One keyspace shard: a full engine vertical behind the IPC server.

Each worker process owns its slice of the Throttle/ClusterThrottle
keyspace end to end — store + SelectorIndex + journal + snapshot/
recovery + device planes + micro-batch ingest + both controllers — and
answers the front's scatter-gather RPCs. Nothing is shared between
workers: no locks, no memory, no GIL. PR 6's fenced leadership runs
independently per shard when a data dir is given (per-shard epoch file,
per-shard journal fencing; a standby for shard *i* replicates from
shard *i* alone).

Run as a process:

    python -m kube_throttler_tpu.sharding.worker \
        --shard-id 0 --shards 4 --ipc-fd 3 [--data-dir DIR] [--no-device]

The supervisor passes the socketpair fd; everything else arrives over
the socket (events to ingest, RPCs to answer). A cross-host fleet
worker listens instead of inheriting:

    python -m kube_throttler_tpu.sharding.worker \
        --shard-id 0 --shards 4 --listen 0.0.0.0:7781 [--port-file F]

and serves the SAME framed protocol over TCP (``serve_tcp``): each
accepted connection is one front lane; frames carry the fencing epoch
so a partitioned-then-healed peer is fenced, not trusted.

Two-phase reserve, shard side: ``reserve_prepare`` performs the real
reserve on this shard's matching throttles and parks the transaction in
a pending table; ``txn_commit`` finalizes (drops the table entry, the
reservation stays); ``txn_abort`` unreserves. A prepared transaction
whose front died before deciding is ABORTED by the reaper once it ages
past ``prepare_ttl`` — a prepare-crash can never leave an orphan
reservation (tests/test_sharding.py pins this).

Live resharding, shard side (the ``reshard_*`` RPC family —
sharding/reshard.py drives it): a SOURCE stages its moving keyspace
slice with ``reshard_prepare`` (store objects + reservation ledger
entries + gang records + published statuses, pickled once) and serves it
in prefix-sha-verified chunks (``reshard_chunk``, the StandbyReplicator
chunk contract over the framed-pickle IPC); ``reshard_fence`` makes the
range-scoped fence refuse every later authoritative write for the moved
ranges; ``reshard_retire`` drops the slice after cutover (fence lifted
with it). A DESTINATION assembles chunks (``reshard_import``), applies
the slice into its own engine stack (statuses suppressed — its verdicts
are advisory while warming), and on ``reshard_activate`` re-enqueues
every moved key through the two-lane PRIORITY path so every flip it
computed during warm-up is re-published. ``reshard_abort`` rolls either
side back to the pre-handoff state, and the txn reaper TTLs any handoff
orphaned by a front crash between prepare and cutover — zero orphan
reservations by the same clock that reaps two-phase reserves.
"""

from __future__ import annotations

import argparse
import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.lockorder import guard_attrs, make_lock

logger = logging.getLogger(__name__)

# control verbs carried in-stream with store ops (front → shard)
RESYNC_PRUNE = "__prune__"


@guard_attrs
class ShardCore:
    """The shard's engine stack + RPC dispatch, transport-agnostic.

    ``push(items)`` (settable) receives ``[(kind, obj), ...]`` status
    events the shard's controllers wrote — the worker main sends them to
    the front as ``push`` frames; tests wire it straight into the
    front's applier.
    """

    GUARDED_BY = {
        "_pending_txns": "self._txn_lock",
        "_pending_gangs": "self._txn_lock",
        "_gang_members": "self._txn_lock",
        "reaped_txns": "self._txn_lock",
        "_handoffs_out": "self._txn_lock",
        "_handoffs_in": "self._txn_lock",
        "reshard_aborts": "self._txn_lock",
        "reaped_handoffs": "self._txn_lock",
        "_push_buf": "self._push_lock",
        "wire_epoch": "self._epoch_lock",
        "fenced_events": "self._epoch_lock",
        "fenced_reqs": "self._epoch_lock",
        "negotiated_proto": "self._epoch_lock",
        "negotiated_caps": "self._epoch_lock",
        "peer_build": "self._epoch_lock",
        "version_mismatches": "self._epoch_lock",
    }

    def __init__(
        self,
        shard_id: int,
        n_shards: int,
        name: str = "kube-throttler",
        target_scheduler: str = "my-scheduler",
        use_device: bool = True,
        data_dir: Optional[str] = None,
        ingest_batch="adaptive",
        faults=None,
        prepare_ttl: float = 30.0,
        snapshot_every: int = 5000,
    ):
        from ..engine.store import Store
        from ..engine.ingest import MicroBatchIngest
        from ..plugin import KubeThrottler, decode_plugin_args

        self.shard_id = shard_id
        self.n_shards = n_shards
        self.faults = faults
        self.prepare_ttl = prepare_ttl
        self.store = Store()
        self.journal = None
        self.recovery = None
        self.snapshotter = None
        self.epoch = None
        self.ha = None
        if data_dir:
            from ..engine.recovery import RecoveryManager
            from ..engine.replication import FencingEpoch, HaCoordinator
            from ..engine.snapshot import SnapshotManager

            os.makedirs(data_dir, exist_ok=True)
            self.recovery = RecoveryManager(data_dir)
            self.journal = self.recovery.recover_store(self.store)
            self.snapshotter = SnapshotManager(data_dir, self.store)
            # PR 6 fenced leadership, per shard: this process claims a
            # term for ITS keyspace slice; journal appends and snapshot
            # cuts refuse once the epoch goes stale
            self.epoch = FencingEpoch(data_dir)
            self.epoch.observe(self.recovery.report.epoch)
            self.journal.fencing = self.epoch
            self.snapshotter.fencing = self.epoch
            self.ha = HaCoordinator(
                self.epoch, role="leader", journal=self.journal,
                snapshotter=self.snapshotter,
            )
            self.ha.become_leader()
        self.plugin = KubeThrottler(
            decode_plugin_args(
                {"name": name, "targetSchedulerName": target_scheduler}
            ),
            self.store,
            use_device=use_device,
            start_workers=True,
        )
        if self.recovery is not None:
            caches = {
                "throttle": self.plugin.throttle_ctr.cache,
                "clusterthrottle": self.plugin.cluster_throttle_ctr.cache,
            }
            self.recovery.restore_reservations(caches)
            self.plugin.gang.journal = self.journal
            self.recovery.restore_gangs(self.plugin.gang, self.journal)
            self.recovery.reconcile(
                self.plugin.informers,
                device_manager=self.plugin.device_manager,
                enqueue={
                    "throttle": self.plugin.throttle_ctr.enqueue,
                    "clusterthrottle": self.plugin.cluster_throttle_ctr.enqueue,
                },
            )
            self.snapshotter.reservations = caches
            self.snapshotter.gang_ledger = self.plugin.gang
            self.snapshotter.device_manager = self.plugin.device_manager
            self.snapshotter.bind_journal(self.journal, every_lines=snapshot_every)
        if ingest_batch in ("off", "none", "", None):
            ingest_batch = 1
        self.pipeline = MicroBatchIngest(
            self.store, batch_policy=ingest_batch, faults=faults
        )
        # two-phase reserve bookkeeping
        self._txn_lock = make_lock(f"shard.txn.{shard_id}")
        self._pending_txns: Dict[str, Tuple[object, float]] = {}  # txn → (pod, t)
        self._pending_gangs: Dict[str, Tuple[str, float]] = {}  # txn → (group, t)
        # NON-owner shards hold a gang's member reservations as plain
        # reservations (the authoritative ledger record lives only on the
        # group's hash-owner shard): group → member pods, so a rollback
        # releases them without a ledger
        self._gang_members: Dict[str, List[object]] = {}
        self.reaped_txns = 0
        # live resharding: staged outbound slices (this shard is a handoff
        # SOURCE), assembling inbound slices (DESTINATION), and the
        # range-scoped fence the event path consults post-cutover
        from ..engine.replication import RangeFence

        self._handoffs_out: Dict[str, dict] = {}  # handoff → staged slice
        self._handoffs_in: Dict[str, dict] = {}  # handoff → assembling sink
        self.range_fence = RangeFence()
        self.reshard_aborts = 0
        # bound by worker main() when a shared-memory event ring is
        # attached (ShmEventPump); stats/metrics sample it read-only
        self.shm_pump = None
        self.reaped_handoffs = 0
        # status push plumbing: handlers append under the push lock (they
        # run inside the store lock and must stay informer-cheap); the
        # pusher thread flushes batches to ``push``
        self.push = None  # set by the transport wrapper
        self._push_lock = make_lock(f"shard.push.{shard_id}")
        self._push_cond = threading.Condition(self._push_lock)
        self._push_buf: List[Tuple[str, object]] = []
        # wire fencing (sharding/ipc.py): the max fencing epoch seen on
        # ANY connection. The front bumps its counter at the head of
        # every resync, so a frame stamped below this watermark is from
        # before a heal/reshard — fenced, not trusted
        self._epoch_lock = make_lock(f"shard.wire_epoch.{shard_id}")
        self.wire_epoch = 0
        self.fenced_events = 0  # stale-epoch evt ops dropped
        self.fenced_reqs = 0  # stale-epoch RPCs refused (the wire 409)
        # rolling-upgrade handshake outcome (version.py): the negotiated
        # (major, minor) + capability intersection for the current
        # primary lane, and the count of incompatible-major hellos this
        # worker refused with a typed VersionMismatch frame
        self.negotiated_proto: Optional[Tuple[int, int]] = None
        self.negotiated_caps: frozenset = frozenset()
        self.peer_build: Optional[str] = None
        self.version_mismatches = 0
        self._stop = threading.Event()
        for kind in ("Throttle", "ClusterThrottle"):
            self.store.add_event_handler(kind, self._on_status_event, replay=False)
        self._pusher = threading.Thread(
            target=self._push_loop, name=f"shard{shard_id}-push", daemon=True
        )
        self._pusher.start()
        self._reaper = threading.Thread(
            target=self._reap_loop, name=f"shard{shard_id}-reaper", daemon=True
        )
        self._reaper.start()

    # ----------------------------------------------------------- status push

    def _on_status_event(self, event) -> None:
        from ..engine.store import EventType

        if event.type is not EventType.MODIFIED or event.old_obj is None:
            return
        if event.obj.status == event.old_obj.status:
            return  # spec echo routed by the front — not ours to re-publish
        if self._import_pending_covers(event.kind, event.obj):
            # warming destination: verdicts are ADVISORY until cutover —
            # don't push statuses for not-yet-activated ranges (activation
            # re-enqueues every moved key priority-first, so every flip
            # computed during warm-up is re-published then)
            return
        with self._push_cond:
            self._push_buf.append((event.kind, event.obj))
            self._push_cond.notify()

    def _import_pending_covers(self, kind: str, obj) -> bool:
        with self._txn_lock:
            ranges = [
                rng
                for entry in self._handoffs_in.values()
                for rng in entry["ranges"]
            ]
        if not ranges:
            return False
        from .ring import route_key_for, stable_hash64

        h = stable_hash64(route_key_for(kind, obj))
        return any(lo <= h < hi for lo, hi in ranges)

    def _push_loop(self) -> None:
        while not self._stop.is_set():
            # loop-level routing (threads checker): a pusher killed by an
            # unexpected exception would silently stop ALL status flow to
            # the front while every probe stayed green — the PR 6 silent-
            # replicator-death class, shard-flavored
            try:
                with self._push_cond:
                    while not self._push_buf and not self._stop.is_set():
                        self._push_cond.wait(0.2)
                    buf, self._push_buf = self._push_buf, []
                if buf and self.push is not None:
                    try:
                        self.push(buf)
                    except Exception:  # noqa: BLE001 — front gone; supervisor acts
                        logger.warning("shard %d: status push failed", self.shard_id,
                                       exc_info=True)
            except Exception:  # noqa: BLE001 — keep the pusher alive
                logger.exception("shard %d: push loop error", self.shard_id)
                self._stop.wait(0.05)

    # ---------------------------------------------------------------- fencing

    def observe_epoch(self, epoch: int, mtype: str = "req", n: int = 1) -> bool:
        """Track the max fencing epoch seen on the wire; ``False`` means
        the frame is from the PAST — a partitioned-then-healed peer (or
        bytes that sat in a kernel buffer across a heal) replaying a view
        that missed a resync/reshard — and must be fenced, not trusted."""
        with self._epoch_lock:
            if epoch >= self.wire_epoch:
                self.wire_epoch = epoch
                return True
            if mtype == "evt":
                self.fenced_events += n
            else:
                self.fenced_reqs += 1
            return False

    def current_epoch(self) -> int:
        with self._epoch_lock:
            return self.wire_epoch

    # ------------------------------------------------------------ handshake

    def record_negotiation(self, proto, caps, build) -> None:
        with self._epoch_lock:
            self.negotiated_proto = (int(proto[0]), int(proto[1]))
            self.negotiated_caps = frozenset(caps)
            self.peer_build = build

    def record_version_mismatch(self) -> None:
        with self._epoch_lock:
            self.version_mismatches += 1

    def negotiated_state(self) -> Dict[str, object]:
        """The build_info view: this build's identity plus the current
        primary lane's negotiated version/caps (version.py contracts)."""
        from ..version import BUILD_ID, local_proto_version

        with self._epoch_lock:
            proto = self.negotiated_proto
            caps = self.negotiated_caps
            build = self.peer_build
            mismatches = self.version_mismatches
        return {
            "build": BUILD_ID,
            "proto": list(local_proto_version()),
            "negotiated_proto": None if proto is None else list(proto),
            "negotiated_caps": sorted(caps),
            "peer_build": build,
            "version_mismatches": mismatches,
        }

    # ---------------------------------------------------------------- events

    def handle_events(self, ops: Sequence[Tuple[str, str, object]]) -> None:
        """Apply a routed event batch through the micro-batch pipeline.
        Control ops (resync prune) are handled in-stream, in order."""
        if self.faults is not None:
            fault = self.faults.check("shard.worker.kill")
            if fault is not None and fault.mode == "kill":
                fault.kill()
        fenced = self.range_fence.fenced_handoffs()
        batch: List[Tuple[str, str, object]] = []
        for op in ops:
            if op[0] == RESYNC_PRUNE:
                if batch:
                    self._submit_batch(batch)
                    batch = []
                self._prune(op[2])
                continue
            if fenced and self._fence_refuses(op):
                continue
            batch.append(op)
        if batch:
            self._submit_batch(batch)

    def _submit_batch(self, batch: List[Tuple[str, str, object]]) -> None:
        """Apply a routed batch — and, while a handoff slice is still
        streaming IN, buffer a copy per unsealed handoff: a mirrored
        event that lands mid-stream would otherwise be overwritten by the
        (older) slice snapshot at seal time; the seal replays the buffer
        after the snapshot so the race always resolves newest-last."""
        with self._txn_lock:
            for entry in self._handoffs_in.values():
                if not entry.get("sealed"):
                    entry["evbuf"].append(list(batch))
        self.pipeline.submit_many(batch)

    def _fence_refuses(self, op: Tuple[str, str, object]) -> bool:
        """Post-cutover write refusal: an authoritative throttle-keyspace
        write whose route hash lands in a fenced range is dropped and
        counted — the destination owns that range now; a racing event the
        front routed pre-cutover must not mutate the retiring slice (it
        was mirrored to the destination, so nothing is lost). Pod events
        pass: pods have no range identity and a non-matching pod is inert."""
        verb, kind, payload = op
        if kind not in ("Throttle", "ClusterThrottle"):
            return False
        if verb == "delete":
            return False  # cleanup is always allowed (retire uses it)
        from .ring import route_key_for, stable_hash64

        h = stable_hash64(route_key_for(kind, payload))
        if self.range_fence.covers(h):
            self.range_fence.refuse()
            return True
        return False

    def _prune(self, want: Dict[str, Sequence[str]]) -> None:
        """Resync epilogue: everything this shard holds that the front's
        replay did not name was deleted while the shard was down — drop
        it (the StandbyReplicator bootstrap rule, applied over IPC)."""
        from ..engine.store import key_of

        self.pipeline.flush(timeout=30.0)
        ops = []
        for kind, lister in (
            ("Pod", self.store.list_pods),
            ("Throttle", self.store.list_throttles),
            ("ClusterThrottle", self.store.list_cluster_throttles),
            ("Namespace", self.store.list_namespaces),
        ):
            have = set(want.get(kind, ()))
            for obj in lister():
                if key_of(kind, obj) not in have:
                    ops.append(("delete", kind, key_of(kind, obj)))
        if ops:
            self.store.apply_events(ops)

    # ------------------------------------------------------------------- RPC

    def rpc(self, op: str, payload) -> Tuple[bool, object]:
        """Dispatch one RPC; returns (ok, body). Never raises."""
        try:
            handler = getattr(self, f"_rpc_{op}", None)
            if handler is None:
                return False, f"unknown rpc {op!r}"
            return True, handler(payload)
        except Exception as e:  # noqa: BLE001 — reported to the front
            return False, f"{e.__class__.__name__}: {e}"

    def _rpc_ping(self, _payload):
        return {
            "shard": self.shard_id,
            "epoch": self.epoch.current() if self.epoch is not None else 0,
        }

    def _rpc_pre_filter(self, pod):
        """Shard-local admission check: both kinds' ``check_throttled``
        against this shard's throttles. Returns per-kind name lists —
        the front AND-merges and composes the reason strings."""
        out = {}
        for kind, ctr in (
            ("throttle", self.plugin.throttle_ctr),
            ("clusterthrottle", self.plugin.cluster_throttle_ctr),
        ):
            try:
                active, insufficient, exceeds, _ = ctr.check_throttled(pod, False)
            except Exception as e:  # noqa: BLE001 — the per-kind error contract
                out[kind] = {"error": str(e)}
                continue
            out[kind] = {
                "active": [t.key for t in active],
                "insufficient": [t.key for t in insufficient],
                "exceeds": [t.key for t in exceeds],
            }
        return out

    def _rpc_pre_filter_batch(self, _payload):
        return self.plugin.pre_filter_batch()

    def _rpc_reserve_prepare(self, payload):
        txn, pod = payload["txn"], payload["pod"]
        status = self.plugin.reserve(pod)
        if not status.is_success():
            raise RuntimeError("; ".join(status.reasons) or "reserve failed")
        with self._txn_lock:
            self._pending_txns[txn] = (pod, time.monotonic())
        return True

    def _rpc_txn_commit(self, payload):
        with self._txn_lock:
            self._pending_txns.pop(payload["txn"], None)
            self._pending_gangs.pop(payload["txn"], None)
        return True

    def _rpc_txn_abort(self, payload):
        with self._txn_lock:
            entry = self._pending_txns.pop(payload["txn"], None)
            gang = self._pending_gangs.pop(payload["txn"], None)
        if entry is not None:
            self.plugin.unreserve(entry[0])
        if gang is not None:
            self._gang_release(gang[0])
        return True

    def _rpc_unreserve(self, pod):
        self.plugin.unreserve(pod)
        return True

    def _rpc_gang_check(self, payload):
        status = self.plugin.pre_filter_gang(payload["group"], payload["pods"])
        return {"code": status.code.value, "reasons": list(status.reasons)}

    def _rpc_gang_prepare(self, payload):
        """Gang prepare. On the group's hash-OWNER shard this is the real
        ledger reserve (all-or-nothing locally, GANG journal stamps, TTL
        authority). On other matching shards the members reserve as plain
        reservations — the ledger record exists on exactly one shard."""
        txn, group, pods = payload["txn"], payload["group"], payload["pods"]
        owner = bool(payload.get("owner", True))
        if owner:
            status = self.plugin.reserve_gang(group, pods)
            if not status.is_success():
                raise RuntimeError("; ".join(status.reasons) or "gang reserve failed")
        else:
            reserved: List[object] = []
            try:
                for pod in pods:
                    st = self.plugin.reserve(pod)
                    if not st.is_success():
                        raise RuntimeError("; ".join(st.reasons) or "member reserve failed")
                    reserved.append(pod)
            except Exception:
                for pod in reserved:
                    self.plugin.unreserve(pod)
                raise
            with self._txn_lock:
                self._gang_members[group] = list(pods)
        with self._txn_lock:
            self._pending_gangs[txn] = (group, time.monotonic())
        return True

    def _gang_release(self, group: str) -> None:
        with self._txn_lock:
            members = self._gang_members.pop(group, None)
        if members is not None:
            for pod in members:
                self.plugin.unreserve(pod)
        self.plugin.unreserve_gang(group)  # no-op where no ledger record

    def _rpc_gang_rollback(self, payload):
        self._gang_release(payload["group"])
        return True

    def _rpc_gang_groups(self, _payload):
        """Group keys with live ledger records on this shard (tests pin
        the one-owner property of the authoritative ledger entry)."""
        return sorted(self.plugin.gang.snapshot_state().keys())

    def _rpc_stats(self, _payload):
        ps = self.pipeline.stats()
        with self._txn_lock:
            reaped = self.reaped_txns
            pending = len(self._pending_txns) + len(self._pending_gangs)
            pending_handoffs = len(self._handoffs_out) + len(self._handoffs_in)
            reshard_aborts = self.reshard_aborts
            reaped_handoffs = self.reaped_handoffs
        reservations = sum(
            len(ctr.cache.reserved_pod_keys(tk))
            for ctr in (self.plugin.throttle_ctr, self.plugin.cluster_throttle_ctr)
            for tk in ctr.cache.throttle_keys()
        )
        return {
            "pending_handoffs": pending_handoffs,
            "reshard_aborts": reshard_aborts,
            "reaped_handoffs": reaped_handoffs,
            "fenced_writes_refused": self.range_fence.refused(),
            "fenced_handoffs": self.range_fence.fenced_handoffs(),
            "reservations": reservations,
            "gang_groups": len(self.plugin.gang.snapshot_state()),
            "shard": self.shard_id,
            "ingest": ps,
            "workqueues": {
                "throttle": len(self.plugin.throttle_ctr.workqueue),
                "clusterthrottle": len(self.plugin.cluster_throttle_ctr.workqueue),
            },
            "objects": {
                "pods": len(self.store.list_pods()),
                "throttles": len(self.store.list_throttles()),
                "clusterthrottles": len(self.store.list_cluster_throttles()),
            },
            "reaped_txns": reaped,
            "pending_txns": pending,
            "epoch": self.epoch.current() if self.epoch is not None else 0,
            "wire_epoch": self.current_epoch(),
            "fenced_frames": self._fenced_counts(),
            "version": self.negotiated_state(),
            "shm": (
                {
                    "frames": self.shm_pump.frames,
                    "events": self.shm_pump.events,
                    "depth": self.shm_pump.depth(),
                }
                if self.shm_pump is not None
                else None
            ),
        }

    def _fenced_counts(self) -> Dict[str, int]:
        with self._epoch_lock:
            return {"events": self.fenced_events, "reqs": self.fenced_reqs}

    def _rpc_drain(self, payload):
        timeout = float(payload.get("timeout", 5.0)) if payload else 5.0
        flushed = self.pipeline.flush(timeout=timeout)
        return {
            "flushed": flushed,
            "queue": self.pipeline.qsize(),
            "workqueues": {
                "throttle": len(self.plugin.throttle_ctr.workqueue),
                "clusterthrottle": len(self.plugin.cluster_throttle_ctr.workqueue),
            },
            "applied": self.pipeline.stats()["events_applied"],
        }

    # ------------------------------------------------- live resharding RPCs

    @staticmethod
    def _parse_ranges(raw) -> List[Tuple[int, int]]:
        return [(int(lo), int(hi)) for lo, hi in raw]

    @staticmethod
    def _hash_in(ranges: Sequence[Tuple[int, int]], h: int) -> bool:
        return any(lo <= h < hi for lo, hi in ranges)

    def _rpc_reshard_prepare(self, payload):
        """SOURCE: stage the moving slice as one pickled blob behind a
        prefix-sha chunk source. The pipeline is flushed first so the
        slice reflects every event routed before the front turned
        double-routing on — the mirror stream covers everything after."""
        import pickle

        from ..engine.store import key_of
        from .ipc import PICKLE_PROTO
        from .ring import route_key_for, stable_hash64

        handoff = payload["handoff"]
        ranges = self._parse_ranges(payload["ranges"])
        self.pipeline.flush(timeout=30.0)
        moved: Dict[str, List[Tuple[str, str]]] = {}  # kind → [(store_key, t.key)]
        objects: Dict[str, list] = {}
        for kind, lister in (
            ("Throttle", self.store.list_throttles),
            ("ClusterThrottle", self.store.list_cluster_throttles),
        ):
            moved[kind] = []
            objects[kind] = []
            for thr in lister():
                h = stable_hash64(route_key_for(kind, thr))
                if self._hash_in(ranges, h):
                    moved[kind].append((key_of(kind, thr), thr.key))
                    objects[kind].append(thr)
        moved_keys = {
            "throttle": {tk for _, tk in moved["Throttle"]},
            "clusterthrottle": {tk for _, tk in moved["ClusterThrottle"]},
        }
        reservations = {}
        for rkind, ctr in (
            ("throttle", self.plugin.throttle_ctr),
            ("clusterthrottle", self.plugin.cluster_throttle_ctr),
        ):
            state = ctr.cache.snapshot_state()
            reservations[rkind] = {
                tk: entry for tk, entry in state.items() if tk in moved_keys[rkind]
            }
        gangs = {
            gk: entry
            for gk, entry in self.plugin.gang.snapshot_state().items()
            if self._hash_in(ranges, stable_hash64(route_key_for("Gang", gk)))
        }
        # pods travel as the full population: every pod on this shard
        # matches SOME local throttle; one matching both a moving and a
        # staying throttle must exist on both sides, and a non-matching
        # extra is inert for verdicts (aggregation is per throttle). The
        # front's routing deletes prune the leftovers on later pod events.
        blob = pickle.dumps(
            {
                "throttles": objects["Throttle"],
                "clusterthrottles": objects["ClusterThrottle"],
                "pods": self.store.list_pods(),
                "reservations": reservations,
                "gangs": gangs,
            },
            protocol=PICKLE_PROTO,
        )
        from ..engine.replication import SliceChunkSource

        entry = {
            "source": SliceChunkSource(blob),
            "ranges": ranges,
            "t0": time.monotonic(),
            "moved": moved,
            "gang_keys": sorted(gangs),
        }
        with self._txn_lock:
            self._handoffs_out[handoff] = entry
        return {
            "bytes": len(blob),
            "throttles": len(moved["Throttle"]) + len(moved["ClusterThrottle"]),
            "pods": len(self.store.list_pods()),
            "gangs": len(gangs),
        }

    def _rpc_reshard_chunk(self, payload):
        """SOURCE: serve one verified slice chunk (the replication wire's
        offset+hash continuity). ``reshard.handoff.torn`` mode ``torn``
        flips a byte so the sink's hash check MUST catch it; mode
        ``error`` tears the stream outright."""
        with self._txn_lock:
            entry = self._handoffs_out.get(payload["handoff"])
        if entry is None:
            raise RuntimeError(f"unknown handoff {payload['handoff']!r}")
        chunk = entry["source"].chunk(payload.get("offset", 0), payload.get("sha", ""))
        if self.faults is not None:
            fault = self.faults.check("reshard.handoff.torn")
            if fault is not None:
                if fault.mode == "torn" and chunk["data"]:
                    data = bytearray(chunk["data"])
                    data[len(data) // 2] ^= 0xFF
                    chunk = dict(chunk, data=bytes(data))
                else:
                    raise OSError(
                        f"injected handoff stream tear (hit {fault.hit})"
                    )
        return chunk

    def _rpc_reshard_import(self, payload):
        """DESTINATION: assemble verified chunks; on the final one, apply
        the slice into this shard's engine stack (objects through the
        normal event path — index/planes follow via handler fan-out —
        then reservation ledgers and gang records). Statuses for these
        ranges stay suppressed until ``reshard_activate``."""
        import pickle

        from ..engine.replication import SliceChunkSink

        handoff = payload["handoff"]
        if self.faults is not None:
            fault = self.faults.check("reshard.dest.crash")
            if fault is not None:
                if fault.mode == "kill":
                    fault.kill()
                raise fault.make_error()
        with self._txn_lock:
            entry = self._handoffs_in.get(handoff)
            if entry is None:
                entry = {
                    "sink": SliceChunkSink(),
                    "ranges": self._parse_ranges(payload["ranges"]),
                    "t0": time.monotonic(),
                    "applied": None,
                    "sealed": False,
                    "evbuf": [],
                }
                self._handoffs_in[handoff] = entry
        entry["sink"].feed(payload["chunk"])
        if not entry["sink"].done:
            return {"done": False, "offset": entry["sink"].offset()}
        slice_doc = pickle.loads(entry["sink"].payload())
        # everything already routed to us must be applied before the
        # snapshot lands (FIFO on the socket guarantees nothing newer is
        # still queued behind this RPC only AFTER the pipeline drains)
        self.pipeline.flush(timeout=30.0)
        ops = [("upsert", "Throttle", t) for t in slice_doc["throttles"]]
        ops += [("upsert", "ClusterThrottle", t) for t in slice_doc["clusterthrottles"]]
        ops += [("upsert", "Pod", p) for p in slice_doc["pods"]]
        for i in range(0, len(ops), 512):
            self.store.apply_events(ops[i : i + 512])
        # seal: replay every routed batch that raced the stream (they
        # post-date the snapshot — newest content re-asserts itself),
        # draining until no new batch sneaks in, then stop buffering
        while True:
            with self._txn_lock:
                evbuf, entry["evbuf"] = entry["evbuf"], []
                if not evbuf:
                    entry["sealed"] = True
                    break
            for batch in evbuf:
                replay = [op for op in batch if op[0] != RESYNC_PRUNE]
                for i in range(0, len(replay), 512):
                    self.store.apply_events(replay[i : i + 512])
        restored = {}
        for rkind, ctr in (
            ("throttle", self.plugin.throttle_ctr),
            ("clusterthrottle", self.plugin.cluster_throttle_ctr),
        ):
            state = slice_doc["reservations"].get(rkind) or {}
            ctr.cache.restore_state(state)
            for tk in state:
                if self.plugin.device_manager is not None:
                    self.plugin.device_manager.on_reservation_change(
                        ctr.KIND, tk, ctr.cache
                    )
            restored[rkind] = sorted(state)
        self.plugin.gang.restore_state(slice_doc["gangs"])
        entry["applied"] = {
            "throttle_keys": {
                "Throttle": [t.key for t in slice_doc["throttles"]],
                "ClusterThrottle": [t.key for t in slice_doc["clusterthrottles"]],
            },
            "reservations": restored,
            "gang_keys": sorted(slice_doc["gangs"]),
        }
        return {
            "done": True,
            "objects": len(ops),
            "gangs": len(slice_doc["gangs"]),
        }

    def _rpc_reshard_fence(self, payload):
        """SOURCE: fence the moved ranges at the handoff's epoch — every
        later authoritative write for them is refused (range-scoped
        FencedEpoch semantics). The fence lifts on retire or abort, or by
        the TTL reaper if the front dies before deciding."""
        handoff = payload["handoff"]
        self.range_fence.fence(
            handoff, self._parse_ranges(payload["ranges"]),
            int(payload.get("epoch", 0)),
        )
        with self._txn_lock:
            entry = self._handoffs_out.get(handoff)
            if entry is not None:
                entry["fenced"] = True
        return True

    def _rpc_reshard_activate(self, payload):
        """DESTINATION cutover: adopt the warmed slice as authoritative
        and re-enqueue every moved key on BOTH controllers' priority
        lanes — every flip computed during warm-up (suppressed as
        advisory) re-publishes flips-first through the two-lane path, so
        nothing the source never committed is lost."""
        handoff = payload["handoff"]
        with self._txn_lock:
            entry = self._handoffs_in.pop(handoff, None)
        if entry is None or entry["applied"] is None:
            raise RuntimeError(f"handoff {handoff!r} not warmed on shard "
                               f"{self.shard_id}")
        requeued = 0
        for kind, ctr in (
            ("Throttle", self.plugin.throttle_ctr),
            ("ClusterThrottle", self.plugin.cluster_throttle_ctr),
        ):
            keys = entry["applied"]["throttle_keys"][kind]
            if keys:
                ctr.workqueue.add_all_priority(keys)
                requeued += len(keys)
        return {"requeued": requeued}

    def _rpc_reshard_retire(self, payload):
        """SOURCE post-cutover: the slice left with the range — delete the
        moved objects, release their reservations, forget their gang
        records, lift the fence. The destination re-published everything;
        keeping a fenced zombie copy would only feed the next resync."""
        handoff = payload["handoff"]
        with self._txn_lock:
            entry = self._handoffs_out.pop(handoff, None)
        if entry is None:
            raise RuntimeError(f"unknown handoff {payload['handoff']!r}")
        dropped = self._drop_slice(
            entry["moved"],
            {
                "throttle": [tk for _, tk in entry["moved"]["Throttle"]],
                "clusterthrottle": [tk for _, tk in entry["moved"]["ClusterThrottle"]],
            },
            entry["gang_keys"],
        )
        self.range_fence.lift(handoff)
        return dropped

    def _rpc_reshard_abort(self, payload):
        """Either side, abort-back-to-source. SOURCE: lift the fence and
        unstage — authority never left. DESTINATION: drop whatever the
        torn handoff imported (objects, reservations, gang records) so no
        orphan reservation and no stale verdict state survives the
        abort."""
        handoff = payload["handoff"]
        with self._txn_lock:
            out_entry = self._handoffs_out.pop(handoff, None)
            in_entry = self._handoffs_in.pop(handoff, None)
            if out_entry is not None or in_entry is not None:
                self.reshard_aborts += 1
        if out_entry is not None:
            self.range_fence.lift(handoff)
        if in_entry is not None and in_entry["applied"] is not None:
            self._drop_imported(in_entry["applied"])
        return {
            "aborted_out": out_entry is not None,
            "aborted_in": in_entry is not None,
        }

    def _rpc_reshard_audit(self, _payload):
        """The zero-orphan witness: reservations held against throttle
        keys this shard's store no longer carries (a handoff that dropped
        the object but leaked its ledger entry), plus any pending handoff
        or standing fence. All three must be zero/empty after every abort
        path — the resharding scenario and the kill matrix gate on it."""
        from ..engine.store import NotFoundError

        orphans = []
        for ctr, getter in (
            (
                self.plugin.throttle_ctr,
                lambda k: self.store.get_throttle(*k.split("/", 1)),
            ),
            (
                self.plugin.cluster_throttle_ctr,
                lambda k: self.store.get_cluster_throttle(k.lstrip("/")),
            ),
        ):
            for tk in ctr.cache.throttle_keys():
                if not ctr.cache.reserved_pod_keys(tk):
                    continue
                try:
                    getter(tk)
                except NotFoundError:
                    orphans.append(tk)
        with self._txn_lock:
            pending = len(self._handoffs_out) + len(self._handoffs_in)
        return {
            "orphan_reservations": sorted(orphans),
            "pending_handoffs": pending,
            "fenced_handoffs": self.range_fence.fenced_handoffs(),
            "fenced_writes_refused": self.range_fence.refused(),
        }

    def _drop_slice(self, moved: Dict[str, list], res_keys: Dict[str, list],
                    gang_keys) -> Dict[str, int]:
        """Remove a slice's footprint from this shard: reservations first
        (so the delete-driven aggregate recompute sees them gone), then
        gang records, then the objects themselves."""
        released = 0
        for rkind, ctr in (
            ("throttle", self.plugin.throttle_ctr),
            ("clusterthrottle", self.plugin.cluster_throttle_ctr),
        ):
            for tk in res_keys.get(rkind, ()):
                for pk in ctr.cache.reserved_pod_keys(tk):
                    if ctr.cache.remove_pod_key(tk, pk):
                        released += 1
                if self.plugin.device_manager is not None:
                    self.plugin.device_manager.on_reservation_change(
                        ctr.KIND, tk, ctr.cache
                    )
        gangs_dropped = self.plugin.gang.drop_groups(gang_keys)
        ops = []
        for kind in ("Throttle", "ClusterThrottle"):
            for store_key, _tk in moved.get(kind, ()):
                ops.append(("delete", kind, store_key))
        if ops:
            self.store.apply_events(ops)
        return {
            "objects": len(ops),
            "reservations": released,
            "gangs": gangs_dropped,
        }

    def _drop_imported(self, applied: Dict) -> None:
        from ..engine.store import NotFoundError, key_of

        moved = {"Throttle": [], "ClusterThrottle": []}
        for kind, getter in (
            ("Throttle", lambda k: self.store.get_throttle(*k.split("/", 1))),
            ("ClusterThrottle", lambda k: self.store.get_cluster_throttle(k.lstrip("/"))),
        ):
            for tk in applied["throttle_keys"][kind]:
                try:
                    obj = getter(tk)
                except NotFoundError:
                    continue
                moved[kind].append((key_of(kind, obj), tk))
        self._drop_slice(moved, applied["reservations"], applied["gang_keys"])

    # ---------------------------------------------------------------- reaper

    def _reap_loop(self) -> None:
        while not self._stop.wait(min(1.0, self.prepare_ttl / 4 or 1.0)):
            # loop-level routing (threads checker): a dead reaper means
            # orphaned prepares hold reservations forever — silently
            try:
                self.reap_stale_txns()
            except Exception:  # noqa: BLE001 — keep the reaper alive
                logger.exception("shard %d: txn reaper error", self.shard_id)

    def reap_stale_txns(self, now: Optional[float] = None) -> int:
        """Abort prepared transactions older than ``prepare_ttl`` (the
        front died between prepare and commit). Returns aborts done."""
        now = time.monotonic() if now is None else now
        stale_pods, stale_gangs = [], []
        with self._txn_lock:
            for txn, (pod, t0) in list(self._pending_txns.items()):
                if now - t0 >= self.prepare_ttl:
                    stale_pods.append(pod)
                    del self._pending_txns[txn]
            for txn, (group, t0) in list(self._pending_gangs.items()):
                if now - t0 >= self.prepare_ttl:
                    stale_gangs.append(group)
                    del self._pending_gangs[txn]
            self.reaped_txns += len(stale_pods) + len(stale_gangs)
        for pod in stale_pods:
            self.plugin.unreserve(pod)
        for group in stale_gangs:
            self._gang_release(group)
        return len(stale_pods) + len(stale_gangs) + self.reap_stale_handoffs(now)

    def reap_stale_handoffs(self, now: Optional[float] = None) -> int:
        """The two-phase handoff reaper: a handoff orphaned past
        ``prepare_ttl`` (front crashed between prepare and cutover) is
        aborted on whichever side this shard played — the SOURCE lifts
        its fence and unstages (authority never left, so the front's
        still-source routing stays correct), the DESTINATION drops the
        imported slice including every imported reservation. Zero orphan
        reservations by the same clock that reaps two-phase reserves."""
        now = time.monotonic() if now is None else now
        stale_out, stale_in = [], []
        with self._txn_lock:
            for handoff, entry in list(self._handoffs_out.items()):
                if now - entry["t0"] >= self.prepare_ttl:
                    stale_out.append(handoff)
                    del self._handoffs_out[handoff]
            for handoff, entry in list(self._handoffs_in.items()):
                if now - entry["t0"] >= self.prepare_ttl:
                    stale_in.append((handoff, entry))
                    del self._handoffs_in[handoff]
            self.reaped_handoffs += len(stale_out) + len(stale_in)
        for handoff in stale_out:
            self.range_fence.lift(handoff)
            logger.warning("shard %d: reaped orphaned outbound handoff %s",
                           self.shard_id, handoff)
        for handoff, entry in stale_in:
            if entry["applied"] is not None:
                self._drop_imported(entry["applied"])
            logger.warning("shard %d: reaped orphaned inbound handoff %s",
                           self.shard_id, handoff)
        return len(stale_out) + len(stale_in)

    # ------------------------------------------------------------- lifecycle

    def stop(self) -> None:
        self._stop.set()
        with self._push_cond:
            self._push_cond.notify_all()
        self.pipeline.stop()
        self.plugin.stop()
        if self.snapshotter is not None:
            self.snapshotter.write(reason="shutdown")
        if self.journal is not None:
            self.journal.close()


class ShmEventPump:
    """Worker-side consumer of the shared-memory event ring
    (sharding/shmring.py): one thread pops columnar frames, decodes
    them through a persistent :class:`~.shmring.FrameDecoder`, and
    feeds the batches into the core's ingest path — the same
    ``observe_epoch`` fence and ``handle_events`` entry the socket
    ``evt`` frames use, so the two lanes are semantically identical.

    The reader advances its cursor only AFTER the batch reached the
    ingest pipeline: ``widx - ridx`` stays an honest in-flight count
    for the front's drain gate, and the writer never reclaims arena
    bytes under a frame still being decoded.

    A torn slot commit (or any decode failure) is unrecoverable in
    place — the ring's write cursor is beyond repair from this side —
    so the pump routes it into the worker's own death (``on_fatal``
    shuts the control socket): the supervisor's restart + resync with a
    fresh segment is the repair, exactly like a dead socket.

    ``frames``/``events`` are pump-thread single-writer stats, read at
    scrape by the worker-side shm metrics and the stats RPC."""

    def __init__(self, core: ShardCore, reader, on_fatal):
        self.core = core
        self.reader = reader
        self.on_fatal = on_fatal
        from .shmring import FrameDecoder

        self.decoder = FrameDecoder()
        self.frames = 0
        self.events = 0
        self._stop = False

    def stop(self) -> None:
        self._stop = True

    def depth(self) -> int:
        try:
            return self.reader.depth()
        except (ValueError, OSError):
            return 0

    def run(self) -> None:
        from .shmring import TornSlotError

        try:
            while not self._stop:
                try:
                    view = self.reader.peek(timeout=0.2)
                except TornSlotError as e:
                    logger.error(
                        "shard %d: shm ring torn, dying for restart+resync: %s",
                        self.core.shard_id, e,
                    )
                    self.on_fatal()
                    return
                if view is None:
                    continue
                try:
                    epoch, _seq, ops = self.decoder.decode(view)
                finally:
                    del view  # release the exported segment view
                if self.core.observe_epoch(epoch, "evt", len(ops)):
                    self.core.handle_events(ops)
                self.reader.advance()
                self.frames += 1
                self.events += len(ops)
        except Exception:  # noqa: BLE001 — route the death, don't hide it
            logger.exception("shard %d: shm pump died", self.core.shard_id)
            self.on_fatal()


def serve(
    core: ShardCore, sock: socket.socket, bind_push: bool = True,
    auth_key: Optional[bytes] = None,
) -> None:
    """The worker's IPC loop: read frames until EOF. Events apply via the
    ingest pipeline (non-blocking); RPCs answer from a small pool so a
    long batch call cannot park the event stream.

    Over TCP every accepted connection runs its own ``serve()`` against
    the shared core (``bind_push=False``): the client's primary lane
    subscribes to the push stream with a ``sub`` frame, extra lanes are
    parallel RPC lanes. Responses and pushes are stamped with the max
    fencing epoch the core has seen; stale-epoch frames are fenced —
    ``evt`` batches dropped, ``req`` refused with a ``FencedError`` body
    (the wire-level 409).

    ``auth_key`` arms per-frame HMAC auth (cross-host mode): inbound
    frames that fail the MAC die as a torn stream BEFORE the pickle
    deserializer runs, outbound frames are stamped so the front's keyed
    reader accepts them. Keyless is the trusted-local posture
    (socketpair children, loopback test rigs)."""
    from concurrent.futures import ThreadPoolExecutor

    from ..version import (
        BUILD_ID,
        NegotiationError,
        advertised_capabilities,
        local_proto_version,
        negotiate,
    )
    from .ipc import decode_evt_batch, read_frame, send_frame

    send_lock = make_lock(f"shard.serve.{core.shard_id}")

    def push(items) -> None:
        send_frame(sock, send_lock, "push", 0, items,
                   epoch=core.current_epoch(), faults=core.faults,
                   key=auth_key)

    if bind_push:
        core.push = push
    pool = ThreadPoolExecutor(max_workers=2, thread_name_prefix="shard-rpc")
    rfile = sock.makefile("rb")

    def answer(rid: int, op: str, payload) -> None:
        result = core.rpc(op, payload)
        try:
            send_frame(sock, send_lock, "res", rid, result,
                       epoch=core.current_epoch(), faults=core.faults,
                       key=auth_key)
        except OSError:
            pass  # front went away; the supervisor restarts us if needed

    def refuse(rid: int, stale_epoch: int) -> None:
        body = (
            False,
            f"FencedError: stale epoch {stale_epoch} < {core.current_epoch()}",
        )
        try:
            send_frame(sock, send_lock, "res", rid, body,
                       epoch=core.current_epoch(), faults=core.faults,
                       key=auth_key)
        except OSError:
            pass

    try:
        while True:
            frame = read_frame(rfile, core.faults, key=auth_key)
            if frame is None:
                return
            mtype, rid, body, epoch = frame
            if mtype == "evt":
                ops = decode_evt_batch(body)
                if not core.observe_epoch(epoch, "evt", len(ops)):
                    continue  # a stale peer's events must not touch state
                core.handle_events(ops)
            elif mtype == "req":
                if not core.observe_epoch(epoch):
                    pool.submit(refuse, rid, epoch)
                    continue
                op, payload = body
                pool.submit(answer, rid, op, payload)
            elif mtype == "sub":
                if not core.observe_epoch(epoch, "sub"):
                    # a STALE sub is counted fenced and must not rebind
                    # the push stream: a partitioned-then-healed (not yet
                    # resynced) peer's subscribe would otherwise steal
                    # the lane from the current primary and route every
                    # flip to a connection the fencing contract says not
                    # to trust
                    continue
                # version/capability handshake (version.py): the sub body
                # is the front's hello, or None from a pre-handshake
                # build (negotiates as the zero-capability 1.0 baseline,
                # no reply — it would not understand a hello frame).
                if body is None:
                    core.record_negotiation(
                        (local_proto_version()[0], 0), frozenset(), None
                    )
                    core.push = push
                    continue
                try:
                    proto, caps = negotiate(
                        local_proto_version(), advertised_capabilities(),
                        body.get("proto"), body.get("caps"),
                    )
                except NegotiationError as e:
                    # typed refusal, then DROP this connection: redialing
                    # cannot help until an operator upgrades one side.
                    # Over TCP the process stays up (only this lane
                    # dies); a socketpair child exits and the
                    # supervisor's jittered backoff paces the restarts —
                    # degraded and counted either way, never a hot loop
                    core.record_version_mismatch()
                    logger.warning(
                        "shard %d: refusing handshake: %s", core.shard_id, e
                    )
                    try:
                        send_frame(sock, send_lock, "hello", 0,
                                   {"error": f"VersionMismatch: {e}"},
                                   epoch=core.current_epoch(), key=auth_key)
                    except OSError:
                        pass
                    return
                core.record_negotiation(proto, caps, body.get("build"))
                core.push = push
                try:
                    send_frame(sock, send_lock, "hello", 0,
                               {"proto": list(proto), "caps": sorted(caps),
                                "build": BUILD_ID},
                               epoch=core.current_epoch(), key=auth_key)
                except OSError:
                    pass  # front gone; the reconnect re-handshakes
    except OSError:
        return
    finally:
        pool.shutdown(wait=False)
        rfile.close()


def serve_tcp(
    core: ShardCore, srv: socket.socket, auth_key: Optional[bytes] = None,
) -> None:
    """The worker's TCP accept loop (``--listen``): each accepted
    connection is one front lane served by :func:`serve` against the
    shared core. Returns when the listener socket is closed."""

    def lane(conn: socket.socket, peer) -> None:
        try:
            serve(core, conn, bind_push=False, auth_key=auth_key)
        except Exception:  # noqa: BLE001 — route the death, don't hide it
            logger.exception(
                "shard %d: connection from %s died", core.shard_id, peer
            )
        finally:
            try:
                conn.close()
            except OSError:
                pass

    while True:
        try:
            conn, peer = srv.accept()
        except OSError:
            return  # listener closed: shutdown
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        threading.Thread(
            target=lane, args=(conn, peer),
            name=f"shard{core.shard_id}-conn", daemon=True,
        ).start()


_LOOPBACK_HOSTS = frozenset({"", "localhost", "127.0.0.1", "::1"})


def listen_requires_auth(host: str) -> bool:
    """True when binding ``host`` exposes the framed-pickle protocol
    beyond this machine: a non-loopback listener without frame auth
    hands arbitrary-code-execution to anything that can reach the port
    (see the ipc.py trust-boundary docstring), so :func:`main` refuses
    that combination unless ``--insecure-no-auth`` is explicit."""
    return host not in _LOOPBACK_HOSTS and not host.startswith("127.")


def main(argv: Optional[List[str]] = None) -> int:
    from ..utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    parser = argparse.ArgumentParser(prog="kube-throttler-shard")
    parser.add_argument("--shard-id", type=int, required=True)
    parser.add_argument("--shards", type=int, required=True)
    parser.add_argument(
        "--ipc-fd", type=int, default=None,
        help="inherited socketpair fd (supervisor child mode)",
    )
    parser.add_argument(
        "--listen", default="",
        help="serve the framed shard protocol over TCP on host:port "
        "(port 0 = ephemeral) instead of an inherited fd — the "
        "cross-host fleet worker mode",
    )
    parser.add_argument(
        "--port-file", default="",
        help="with --listen: atomically write the bound host:port here "
        "once listening (the spawner's rendezvous, race-free even with "
        "an ephemeral port)",
    )
    parser.add_argument(
        "--auth-key-file", default="",
        help="file holding the fleet's frame-auth pre-shared key (a "
        "mounted Secret); falls back to $KT_SHARD_AUTH_KEY. The frame "
        "payload is pickle — over TCP every frame is HMAC-authenticated "
        "with this key BEFORE deserialization, so only key holders can "
        "speak to the worker. Required for a non-loopback --listen",
    )
    parser.add_argument(
        "--insecure-no-auth", action="store_true",
        help="allow a non-loopback --listen WITHOUT a frame-auth key. "
        "DANGEROUS: any peer that can reach the port gets arbitrary "
        "code execution via a crafted pickle frame — only for networks "
        "where reachability is already locked down out-of-band",
    )
    parser.add_argument(
        "--shm-ring", default="",
        help="name of the supervisor's shared-memory event ring segment "
        "(socketpair child mode); attach failure falls back to pickle "
        "evt frames on the socket and masks the evt-shm capability",
    )
    parser.add_argument(
        "--shm-doorbell-fd", type=int, default=-1,
        help="inherited read end of the ring's doorbell pipe",
    )
    parser.add_argument("--name", default="kube-throttler")
    parser.add_argument("--target-scheduler-name", default="my-scheduler")
    parser.add_argument("--data-dir", default="")
    parser.add_argument("--no-device", action="store_true")
    parser.add_argument("--ingest-batch", default="adaptive")
    parser.add_argument("--prepare-ttl", type=float, default=30.0)
    parser.add_argument("--fault-seed", type=int, default=0)
    parser.add_argument(
        "--fault-site", default="",
        help="arm one seeded fault rule (site[:mode[:after[:delay]]]) — the "
        "chaos harness's kill/err injection, e.g. shard.worker.kill:kill:25 "
        "or shm.reader.stall:delay:2:0.5",
    )
    args = parser.parse_args(argv)
    if bool(args.listen) == (args.ipc_fd is not None):
        parser.error("exactly one of --ipc-fd and --listen is required")
    auth_key = None
    if args.listen:
        from .ipc import load_auth_key

        auth_key = load_auth_key(args.auth_key_file)
        listen_host = args.listen.rpartition(":")[0]
        if auth_key is None and listen_requires_auth(listen_host):
            if not args.insecure_no_auth:
                parser.error(
                    f"--listen {args.listen}: a non-loopback listener "
                    "requires a frame-auth key (--auth-key-file or "
                    "$KT_SHARD_AUTH_KEY) — the shard protocol is pickled "
                    "Python, and without per-frame HMAC any peer that "
                    "can reach the port gets arbitrary code execution. "
                    "Pass --insecure-no-auth only if reachability is "
                    "locked down out-of-band (NetworkPolicy, private "
                    "network)"
                )

    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s %(levelname).1s shard{args.shard_id} %(name)s] %(message)s",
    )
    faults = None
    if args.fault_site:
        from ..faults.plan import FaultPlan

        parts = args.fault_site.split(":")
        site = parts[0]
        mode = parts[1] if len(parts) > 1 else "error"
        after = int(parts[2]) if len(parts) > 2 else 0
        delay = float(parts[3]) if len(parts) > 3 else 0.0
        faults = FaultPlan(seed=args.fault_seed).rule(
            site, mode=mode, after=after, times=1, delay=delay
        )
    ingest_batch = args.ingest_batch
    if ingest_batch not in ("adaptive", "off", "none", ""):
        ingest_batch = int(ingest_batch)
    core = ShardCore(
        args.shard_id,
        args.shards,
        name=args.name,
        target_scheduler=args.target_scheduler_name,
        use_device=not args.no_device,
        data_dir=args.data_dir or None,
        ingest_batch=ingest_batch,
        faults=faults,
        prepare_ttl=args.prepare_ttl,
    )
    if args.listen:
        # TCP workers have no shared-memory ring with their front —
        # never advertise the capability
        from ..version import advertised_capabilities

        os.environ["KT_PROTO_CAPS_MASK"] = ",".join(
            sorted(advertised_capabilities() - {"evt-shm"})
        )
        host, _, port = args.listen.rpartition(":")
        if auth_key is None and listen_requires_auth(host):
            logger.warning(
                "listening on %s WITHOUT frame auth (--insecure-no-auth): "
                "any peer that can reach this port can execute arbitrary "
                "code via a crafted pickle frame", args.listen,
            )
        srv = socket.create_server((host or "127.0.0.1", int(port)))
        bound_host, bound_port = srv.getsockname()[:2]
        if args.port_file:
            tmp = args.port_file + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(f"{bound_host}:{bound_port}\n")
            os.replace(tmp, args.port_file)
        print(
            f"shard {args.shard_id}/{args.shards} listening on "
            f"{bound_host}:{bound_port}",
            flush=True,
        )
        try:
            serve_tcp(core, srv, auth_key=auth_key)
        finally:
            core.stop()
            srv.close()
        return 0
    sock = socket.socket(fileno=args.ipc_fd)
    pump = None
    try:
        if args.shm_ring:
            from .shmring import ShmRingReader

            try:
                reader = ShmRingReader(
                    args.shm_ring,
                    doorbell_rfd=(
                        args.shm_doorbell_fd
                        if args.shm_doorbell_fd >= 0
                        else None
                    ),
                    faults=faults,
                    untrack=True,  # the supervisor owns the segment name
                )
            except Exception:  # noqa: BLE001 — attach fail ⇒ pickle fallback
                logger.exception(
                    "shard %d: shm ring %r attach failed — pickle fallback",
                    args.shard_id, args.shm_ring,
                )
                reader = None
            if reader is not None:

                def _ring_fatal() -> None:
                    # die as a unit: the supervisor's restart + resync
                    # with a fresh segment is the only repair for a
                    # broken ring
                    try:
                        sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

                pump = ShmEventPump(core, reader, on_fatal=_ring_fatal)
                pump_thread = threading.Thread(
                    target=pump.run,
                    name=f"shard{args.shard_id}-shm",
                    daemon=True,
                )
                pump_thread.start()
                pump.thread = pump_thread
        if pump is None:
            # no attached ring: never advertise the capability — the
            # front must keep evt batches on the socket (pickle
            # fallback)
            from ..version import advertised_capabilities

            os.environ["KT_PROTO_CAPS_MASK"] = ",".join(
                sorted(advertised_capabilities() - {"evt-shm"})
            )
        core.shm_pump = pump  # stats RPC / worker metrics sample this
        if pump is not None:
            from ..metrics import register_shm_worker_metrics

            register_shm_worker_metrics(
                core.plugin.metrics_registry, core, args.shard_id
            )
        print(f"shard {args.shard_id}/{args.shards} ready", flush=True)
        serve(core, sock)
    finally:
        if pump is not None:
            pump.stop()
            thread = getattr(pump, "thread", None)
            if thread is not None:
                thread.join(timeout=1.0)  # let a mid-peek pass finish
            pump.reader.close()
        core.stop()
        sock.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
