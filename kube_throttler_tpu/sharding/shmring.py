"""Zero-copy shared-memory event plane — SPSC ring + columnar frame codec.

The front↔worker **event** lane (``evt`` frames — ordered store-op
batches) moves through a ``multiprocessing.shared_memory`` segment
instead of pickle-over-socketpair. Per shard the supervisor maps one
single-producer/single-consumer ring; the front's ShardClient sender
thread is the only writer, the worker's pump thread the only reader.
Everything else — req/res RPCs, two-phase reserve, reshard slices,
status pushes, the hello handshake — stays on the HMAC-framed pickle
socket unchanged: the ring is the hot lane, the socket is the control
plane and the automatic fallback.

Segment layout (``ring-v1``, offsets in bytes)::

    header   64 B   <8Q>  magic, nslots, arena_bytes,
                          widx (writer), ridx (reader),
                          wraps (writer), backpressure (writer),
                          torn (reader)
    slots    nslots x 24 B  <3Q>  commit, arena offset, length
    arena    arena_bytes    frame payload bytes (ring allocator)

Seqlock-style commit protocol: the writer claims sequence ``seq``
(slot ``seq % nslots``), copies the payload into the arena, writes the
slot's offset/length, and only then stores the commit word
``seq + 1``. The reader at ``ridx`` accepts a slot only when its
commit word is exactly ``ridx + 1``; a commit word of 0 or of the
previous lap (``ridx + 1 - nslots``) means "not written yet", anything
else is a torn/corrupt commit → :class:`TornSlotError`, and the worker
routes that into its own death so the supervisor's restart + resync
repairs the shard (the same repair as a dead socket). The reader
advances ``ridx`` only after the batch is handed to the ingest
pipeline, so ``widx - ridx`` is an honest in-flight count (the front's
``drain`` gate reads it) and the writer never reclaims arena bytes a
frame might still reference.

Backpressure, never silent drop: a full ring (slot exhaustion or arena
exhaustion) makes ``push`` wait — counted in the header's
``backpressure`` word — until the deadline, then *fail the lane* (the
front marks the shard down; supervisor restart + resync repairs).
Shedding of Pod-upsert events under overload stays where it always
was, in ShardClient's bounded queue (same policy as MicroBatchIngest);
the ring itself never drops a committed frame.

Doorbell: a plain ``os.pipe`` — the writer drops one byte
(non-blocking; a full pipe means the reader already has wakeups
pending) after each commit, the reader spins briefly on the commit
word and then blocks in ``select`` on the pipe with a bounded timeout,
so a lost doorbell byte costs latency, never events.

Trust domain / why this lane is exempt from the frame-HMAC rule: the
segment is created by the supervisor and attached only by the worker
it spawned — same host, same UID, same process tree, mode 0600 under
``/dev/shm``. No byte in the ring ever came from a network peer; the
TCP transport (sharding/ipc.py) never uses it and keeps its HMAC
framing. The rare ``ROW_BLOB`` rows therefore ``pickle.loads`` bytes
the *front wrote into local memory*, which is the same trust statement
as the socketpair transport's pickle stream — the ``taint`` checker
encodes this exemption explicitly for this module only.

Frame codec (:class:`FrameEncoder`/:class:`FrameDecoder`): columns,
not pickles. A frame is ``<QQII>`` (epoch, seq, n_ints, heap_len) +
one packed ``<u32`` int stream + a byte heap. Verbs, kinds, delete
keys, pod scalar fields and whole label/annotation/request *shapes*
travel as ids into a persistent string table that grows frame-over-
frame: SPSC FIFO ordering means the reader has seen every earlier
frame, so each frame carries only the strings the reader does not
already know (steady state: a pod row is 12 ints and zero string
bytes). Shapes intern as canonical JSON renders (the snapshot-v2
columnar idiom from engine/columnar.py — ``format_quantity`` out,
``parse_quantity`` back, decoded once per shape and shared across
pods). Payloads that are not canonical pods (Throttle/ClusterThrottle/
Namespace upserts, resync prune maps) ride as embedded pickle blobs —
off the pod hot path by construction.
"""

from __future__ import annotations

import json
import os
import pickle
import select
import struct
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..api.pod import Pod, PodSpec, PodStatus
from ..engine.columnar import parse_request_shape, render_request_shape
from ..utils.lockorder import guard_attrs, make_lock

__all__ = [
    "SHM_FORMATS",
    "TornSlotError",
    "ShmRingWriter",
    "ShmRingReader",
    "FrameEncoder",
    "FrameDecoder",
    "ShmEventLane",
    "sweep_segments",
    "shm_available",
]

# The shm: wire-format registry source of truth — version.py's
# FORMAT_REGISTRY must carry one ``shm:<name>`` row per entry here
# (machine-checked by analysis/protocol.py, like snapshot versions).
SHM_FORMATS = ("ring-v1",)

_PICKLE_PROTO = 5

_MAGIC = 0x4B54_4D52_0001  # "KTMR" + layout version
_HDR = struct.Struct("<8Q")
_SLOT = struct.Struct("<3Q")
_FRAME_HDR = struct.Struct("<QQII")

_OFF_WIDX = 24
_OFF_RIDX = 32
_OFF_WRAPS = 40
_OFF_BACKPRESSURE = 48
_OFF_TORN = 56

_NONE_SID = 0xFFFFFFFF  # string id sentinel for a None field

ROW_POD = 0  # canonical pod upsert: 9 interned column ids
ROW_KEY = 1  # string payload (deletes, prune markers): 1 id
ROW_BLOB = 2  # anything else: embedded pickle blob (off the hot path)

_U64 = struct.Struct("<Q")
_U64X2 = struct.Struct("<QQ")  # slot (offset, length) pair


class TornSlotError(RuntimeError):
    """A slot's commit word is neither empty nor the expected sequence:
    the writer died mid-commit or the mapping is corrupt. The reader
    must treat the whole ring as lost (restart + resync repairs)."""


def shm_available() -> bool:
    """POSIX shared memory present on this host?"""
    try:
        from multiprocessing import shared_memory  # noqa: F401
    except ImportError:  # pragma: no cover - stdlib on every target
        return False
    return os.path.isdir("/dev/shm")


def _untrack(shm) -> None:
    # An attaching (non-creating) process must not let resource_tracker
    # adopt the segment: the tracker would unlink it when THIS process
    # exits, racing the creator's own cleanup (Python 3.10 has no
    # ``track=False``). Unregister is best-effort by design.
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


def sweep_segments(prefix: str) -> List[str]:
    """Best-effort unlink of leftover ``/dev/shm`` segments with our
    name prefix — the backstop for an unlink race (``shm.segment.unlink``)
    or a creator killed before its cleanup ran. Idempotent; missing
    names are fine."""
    removed: List[str] = []
    if not prefix or not os.path.isdir("/dev/shm"):
        return removed
    try:
        names = os.listdir("/dev/shm")
    except OSError:
        return removed
    for nm in names:
        if nm.startswith(prefix):
            try:
                os.unlink(os.path.join("/dev/shm", nm))
                removed.append(nm)
            except OSError:
                pass
    return removed


# --------------------------------------------------------------------- ring


@guard_attrs
class ShmRingWriter:
    """Producer half of the SPSC ring. One thread pushes; ``close`` may
    race from the supervisor, hence the lock. The ring-allocator state
    (`_head`, `_inflight`, `_used`) is writer-local on purpose: a worker
    restart always gets a *fresh* segment, so the writer's view of the
    arena is authoritative for its lifetime."""

    GUARDED_BY = {
        "_widx": "self._lock",
        "_head": "self._lock",
        "_used": "self._lock",
        "_inflight": "self._lock",
        "_closed": "self._lock",
        "wraps": "self._lock",
        "backpressure_waits": "self._lock",
        "frames": "self._lock",
        "unlink_failed": "self._lock",
    }

    def __init__(
        self,
        name: Optional[str] = None,
        slots: int = 1024,
        arena_bytes: int = 4 << 20,
        doorbell_wfd: Optional[int] = None,
        faults=None,
    ):
        from multiprocessing import shared_memory

        if slots < 2 or arena_bytes < 4096:
            raise ValueError("ring too small")
        size = _HDR.size + slots * _SLOT.size + arena_bytes
        self._shm = shared_memory.SharedMemory(name=name, create=True, size=size)
        self.name = self._shm.name
        self._buf = self._shm.buf
        _HDR.pack_into(self._buf, 0, _MAGIC, slots, arena_bytes, 0, 0, 0, 0, 0)
        self.nslots = slots
        self.arena_bytes = arena_bytes
        self._arena0 = _HDR.size + slots * _SLOT.size
        self.doorbell_wfd = doorbell_wfd
        if doorbell_wfd is not None:
            os.set_blocking(doorbell_wfd, False)
        self.faults = faults
        self._lock = make_lock(f"shm.ring.writer.{self.name}")
        self._widx = 0
        self._head = 0
        self._used = 0
        self._inflight: deque = deque()  # (seq, offset, length)
        self._closed = False
        self.wraps = 0
        self.backpressure_waits = 0
        self.frames = 0
        self.unlink_failed = False

    # -- stats (sampled by metrics at scrape; plain u64 reads) ------------

    def inflight(self) -> int:
        with self._lock:
            if self._closed:
                return 0
            return self._widx - _U64.unpack_from(self._buf, _OFF_RIDX)[0]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            if self._closed:
                return {
                    "depth": 0,
                    "wraps": self.wraps,
                    "backpressure": self.backpressure_waits,
                    "frames": self.frames,
                }
            ridx = _U64.unpack_from(self._buf, _OFF_RIDX)[0]
            return {
                "depth": self._widx - ridx,
                "wraps": self.wraps,
                "backpressure": self.backpressure_waits,
                "frames": self.frames,
            }

    # -- push --------------------------------------------------------------

    def _try_alloc_locked(self, n: int) -> Optional[int]:
        # reclaim everything the reader has consumed
        ridx = _U64.unpack_from(self._buf, _OFF_RIDX)[0]
        q = self._inflight
        while q and q[0][0] < ridx:
            self._used -= q.popleft()[2]
        if self._widx - ridx >= self.nslots:
            return None  # slot exhaustion
        if self._used == 0:
            if n > self.arena_bytes:
                raise ValueError("frame larger than ring arena")
            self._head = n
            return 0
        head = self._head
        tail = q[0][1]
        if head > tail:
            if self.arena_bytes - head >= n:
                self._head = head + n
                return head
            if n <= tail:  # wrap: skip the dead bytes at the end
                self.wraps += 1
                _U64.pack_into(self._buf, _OFF_WRAPS, self.wraps)
                self._head = n
                return 0
            return None
        if head < tail and tail - head >= n:
            self._head = head + n
            return head
        return None  # head == tail with bytes in flight: arena full

    def push(self, payload: bytes, timeout: float = 5.0) -> bool:
        """Commit one frame. Blocks (counted backpressure) while the
        ring is full; False once the deadline passes or the writer is
        closed — the caller must treat False as a dead lane, never as a
        droppable frame."""
        torn_commit = False
        if self.faults is not None:
            fault = self.faults.check("shm.ring.full")
            if fault is not None:
                # a saturated ring: "delay" models a slow reader the
                # backpressure wait absorbs; any other mode models a
                # stuck reader — the push fails and the lane dies
                if fault.mode == "delay":
                    with self._lock:
                        self.backpressure_waits += 1
                        if not self._closed:
                            _U64.pack_into(
                                self._buf, _OFF_BACKPRESSURE, self.backpressure_waits
                            )
                    fault.sleep()
                else:
                    return False
            fault = self.faults.check("shm.slot.torn_commit")
            if fault is not None:
                torn_commit = True
        n = len(payload)
        deadline = time.monotonic() + timeout
        waited = False
        while True:
            with self._lock:
                if self._closed:
                    return False
                off = self._try_alloc_locked(n)
                if off is not None:
                    seq = self._widx
                    a0 = self._arena0 + off
                    self._buf[a0 : a0 + n] = payload
                    base = _HDR.size + (seq % self.nslots) * _SLOT.size
                    _U64X2.pack_into(self._buf, base + 8, off, n)
                    commit = seq + 1
                    if torn_commit:
                        # payload landed but the commit word is garbage:
                        # exactly what a writer dying mid-commit leaves
                        commit = (seq + 1) | (1 << 63)
                    _U64.pack_into(self._buf, base, commit)
                    self._widx = seq + 1
                    _U64.pack_into(self._buf, _OFF_WIDX, self._widx)
                    self._inflight.append((seq, off, n))
                    self._used += n
                    self.frames += 1
                    # the doorbell (a syscall) is only for a reader that
                    # may be BLOCKED in select: with older frames still
                    # unconsumed the reader is awake (or has a wakeup
                    # byte pending) and will find this commit in its
                    # spin pass — skipping costs at most one bounded
                    # 50 ms poll slice, the documented lost-byte deal
                    ridx = _U64.unpack_from(self._buf, _OFF_RIDX)[0]
                    ring_bell = self._widx - ridx <= 1
                    break
                if not waited:
                    waited = True
                    self.backpressure_waits += 1
                    _U64.pack_into(
                        self._buf, _OFF_BACKPRESSURE, self.backpressure_waits
                    )
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.0005)  # off-lock: the reader owns the next move
        if ring_bell:
            self._ring_doorbell()
        return True

    def _ring_doorbell(self) -> None:
        if self.doorbell_wfd is None:
            return
        if self.faults is not None:
            fault = self.faults.check("shm.doorbell.lost")
            if fault is not None:
                return  # byte lost: the reader's bounded poll catches up
        try:
            os.write(self.doorbell_wfd, b"\x00")
        except (BlockingIOError, OSError):
            pass  # pipe full (reader has wakeups pending) or closing

    def close(self, unlink: bool = True) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self.doorbell_wfd is not None:
            try:
                os.close(self.doorbell_wfd)
            except OSError:
                pass
        try:
            self._shm.close()
        except BufferError:  # a stale exported view; unmap on GC instead
            pass
        if unlink:
            if self.faults is not None:
                fault = self.faults.check("shm.segment.unlink")
                if fault is not None:
                    # lost the unlink race (peer/tracker got there first,
                    # or we died before cleanup): leave the name behind —
                    # the supervisor's sweep_segments backstop removes it.
                    # Drop our tracker registration so the reclaim doesn't
                    # double-report the name at interpreter shutdown.
                    try:
                        from multiprocessing import resource_tracker

                        resource_tracker.unregister(
                            self._shm._name, "shared_memory"
                        )
                    except Exception:
                        pass
                    with self._lock:
                        self.unlink_failed = True
                    return
            try:
                self._shm.unlink()
            except OSError:
                pass


@guard_attrs
class ShmRingReader:
    """Consumer half. Exactly one pump thread calls ``peek``/``advance``;
    ``_ridx`` is therefore reader-thread-local state (mirrored into the
    header for the writer's reclaim and everyone's stats)."""

    GUARDED_BY = {
        "_closed": "self._lock",
    }

    def __init__(
        self,
        name: str,
        doorbell_rfd: Optional[int] = None,
        faults=None,
        untrack: bool = False,
    ):
        from multiprocessing import shared_memory

        self._shm = shared_memory.SharedMemory(name=name)
        if untrack:
            # a worker process attaching a supervisor-owned segment:
            # keep OUR resource tracker's hands off the creator's name
            _untrack(self._shm)
        self._buf = self._shm.buf
        magic, nslots, arena_bytes, widx, ridx, _, _, _ = _HDR.unpack_from(self._buf, 0)
        if magic != _MAGIC:
            self._shm.close()
            raise ValueError(f"not a kt event ring: magic {magic:#x}")
        self.nslots = int(nslots)
        self.arena_bytes = int(arena_bytes)
        self._arena0 = _HDR.size + self.nslots * _SLOT.size
        self._ridx = int(ridx)
        self.doorbell_rfd = doorbell_rfd
        self.faults = faults
        self.torn = 0
        self._lock = make_lock(f"shm.ring.reader.{name}")
        self._closed = False

    def depth(self) -> int:
        return int(_U64.unpack_from(self._buf, _OFF_WIDX)[0]) - self._ridx

    def _check(self):
        ridx = self._ridx
        base = _HDR.size + (ridx % self.nslots) * _SLOT.size
        try:
            commit, off, n = _SLOT.unpack_from(self._buf, base)
        except ValueError:
            # close() released the buffer under a racing peek (teardown
            # path): report empty forever, never a torn slot
            return None
        expected = ridx + 1
        if commit == expected:
            if off + n > self.arena_bytes:
                self._count_torn()
                raise TornSlotError(
                    f"slot {ridx % self.nslots}: payload [{off}:{off + n}] "
                    f"outside arena ({self.arena_bytes})"
                )
            a0 = self._arena0 + off
            return self._buf[a0 : a0 + n]
        if commit == 0 or (ridx >= self.nslots and commit == expected - self.nslots):
            return None  # slot not (re)written yet
        self._count_torn()
        raise TornSlotError(
            f"slot {ridx % self.nslots}: commit {commit:#x} != expected {expected}"
        )

    def _count_torn(self) -> None:
        self.torn += 1
        try:
            _U64.pack_into(self._buf, _OFF_TORN, self.torn)
        except (ValueError, TypeError):
            pass

    def peek(self, timeout: float = 0.2):
        """Memoryview of the next committed frame (zero-copy into the
        segment), or None on timeout. Spin briefly — an active writer
        commits within microseconds — then block on the doorbell with a
        bounded slice so a lost doorbell byte only costs latency."""
        if self.faults is not None:
            fault = self.faults.check("shm.reader.stall")
            if fault is not None:
                fault.sleep()  # slow consumer: the writer must backpressure
        deadline = time.monotonic() + timeout
        spins = 0
        while True:
            got = self._check()
            if got is not None:
                return got
            spins += 1
            if spins < 128:
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            if self.doorbell_rfd is not None:
                ready, _, _ = select.select(
                    [self.doorbell_rfd], [], [], min(remaining, 0.05)
                )
                if ready:
                    try:
                        os.read(self.doorbell_rfd, 4096)
                    except OSError:
                        pass
            else:
                time.sleep(0.0002)

    def advance(self) -> None:
        """Consume the frame ``peek`` returned. Call only after the
        batch reached the ingest pipeline: the writer reclaims arena
        bytes for every sequence below ``ridx``."""
        self._ridx += 1
        _U64.pack_into(self._buf, _OFF_RIDX, self._ridx)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self.doorbell_rfd is not None:
            try:
                os.close(self.doorbell_rfd)
            except OSError:
                pass
        try:
            self._shm.close()  # attach-side: never unlink, the creator owns the name
        except BufferError:
            pass


# --------------------------------------------------------------------- codec


class FrameEncoder:
    """Stateful columnar encoder — front side, sender-thread-only (no
    lock: strict SPSC). The string table persists across frames; ids it
    has assigned are never re-sent. ``_pins`` keeps every object whose
    ``id()`` keys a fast-path cache alive, so an id is never recycled
    under a stale cache entry."""

    # a pod OBJECT re-encoded (resync replay, repeated fan-out of the
    # same materialized object) collapses to one cached 9-sid row: the
    # string table is grow-only and frames are FIFO, so sids minted for
    # an earlier frame are always decodable later. Bounded: past the cap
    # the cache (and its pins) reset — churny fleets lose a cache, never
    # memory.
    _ROW_CACHE_CAP = 65536

    def __init__(self):
        self._ids: Dict[str, int] = {}
        self._label_by_obj: Dict[int, int] = {}  # id(dict) -> shape string id
        self._req_by_stamp: Dict[Tuple[int, int], int] = {}  # (id(arena token), rsid)
        self._row_by_obj: Dict[int, tuple] = {}  # id(pod) -> 9 interned sids
        self._pins: List[Any] = []
        self._row_pins: List[Any] = []
        self.frames = 0

    def _sid(self, s: str, newstrs: List[bytes], lens: List[int]) -> int:
        out = self._ids.get(s)
        if out is None:
            out = len(self._ids)
            self._ids[s] = out
            raw = s.encode("utf-8")
            newstrs.append(raw)
            lens.append(len(raw))
        return out

    def encode(self, ops, epoch: int, seq: int) -> bytes:
        newstrs: List[bytes] = []
        lens: List[int] = []
        rows: List[int] = []
        blobs: List[bytes] = []
        sid = self._sid

        def osid(v) -> int:
            return _NONE_SID if v is None else sid(v, newstrs, lens)

        ids_get = self._ids.get
        row_cache = self._row_by_obj
        rows_extend = rows.extend
        n_ops = 0
        for verb, kind, payload in ops:
            obj = payload
            prepickled = getattr(payload, "_kt_prepickled", False)
            if prepickled:
                obj = payload.obj
            vs = ids_get(verb)
            if vs is None:
                vs = sid(verb, newstrs, lens)
            ks = ids_get(kind)
            if ks is None:
                ks = sid(kind, newstrs, lens)
            n_ops += 1
            if (
                kind == "Pod"
                and verb != "delete"
                and type(obj) is Pod
                and obj.spec is not None
                and obj.status is not None
            ):
                row = row_cache.get(id(obj))
                if row is not None:
                    rows_extend((vs, ks, ROW_POD))
                    rows_extend(row)
                    continue
                spec = obj.spec
                row = (
                    sid(obj.name, newstrs, lens),
                    sid(obj.namespace, newstrs, lens),
                    osid(obj.uid),
                    osid(spec.scheduler_name),
                    osid(spec.node_name),
                    osid(obj.status.phase),
                    self._label_sid(obj.labels, newstrs, lens),
                    self._label_sid(obj.annotations, newstrs, lens),
                    self._req_sid(obj, spec, newstrs, lens),
                )
                if len(row_cache) >= self._ROW_CACHE_CAP:
                    row_cache.clear()
                    self._row_pins.clear()
                row_cache[id(obj)] = row
                self._row_pins.append(obj)
                rows_extend((vs, ks, ROW_POD))
                rows_extend(row)
            elif isinstance(obj, str):
                rows.extend((vs, ks, ROW_KEY, sid(obj, newstrs, lens)))
            else:
                blob = (
                    payload.pickled()
                    if prepickled
                    else pickle.dumps(obj, protocol=_PICKLE_PROTO)
                )
                rows.extend((vs, ks, ROW_BLOB, len(blobs)))
                blobs.append(blob)

        ints: List[int] = [len(lens)]
        ints.extend(lens)
        ints.append(n_ops)
        ints.extend(rows)
        ints.append(len(blobs))
        ints.extend(len(b) for b in blobs)
        heap = b"".join(newstrs) + b"".join(blobs)
        self.frames += 1
        return (
            _FRAME_HDR.pack(epoch, seq, len(ints), len(heap))
            + struct.pack(f"<{len(ints)}I", *ints)
            + heap
        )

    def _label_sid(self, d, newstrs, lens) -> int:
        if d is None:
            return _NONE_SID
        out = self._label_by_obj.get(id(d))
        if out is not None:
            return out
        rendered = json.dumps(
            [[k, v] for k, v in sorted(d.items())], separators=(",", ":")
        )
        out = self._sid(rendered, newstrs, lens)
        self._label_by_obj[id(d)] = out
        self._pins.append(d)
        return out

    def _req_sid(self, pod, spec, newstrs, lens) -> int:
        token = pod.__dict__.get("_kt_arena")
        rsid = pod.__dict__.get("_kt_req_sid")
        stamp = None
        if token is not None and rsid is not None:
            stamp = (id(token), rsid)
            out = self._req_by_stamp.get(stamp)
            if out is not None:
                return out
        rendered = json.dumps(
            render_request_shape(
                spec.containers or (), spec.init_containers or (), spec.overhead
            ),
            sort_keys=True,
            separators=(",", ":"),
        )
        out = self._sid(rendered, newstrs, lens)
        if stamp is not None:
            self._req_by_stamp[stamp] = out
            self._pins.append(token)
        return out


class FrameDecoder:
    """Stateful columnar decoder — worker side, pump-thread-only. The
    string table mirrors the encoder's; label/annotation dicts and
    container tuples decode once per shape id and are shared across
    every pod that references them (the arena's shape-sharing property,
    preserved over the wire)."""

    def __init__(self):
        self._strings: List[str] = []
        self._labels: Dict[int, dict] = {}
        self._reqs: Dict[int, tuple] = {}

    def decode(self, buf) -> Tuple[int, int, List[tuple]]:
        """``(epoch, seq, ops)`` from one frame view."""
        epoch, seq, n_ints, heap_len = _FRAME_HDR.unpack_from(buf, 0)
        ints = struct.unpack_from(f"<{n_ints}I", buf, _FRAME_HDR.size)
        heap_base = _FRAME_HDR.size + 4 * n_ints
        i = 0
        n_new = ints[i]
        i += 1
        off = heap_base
        strings = self._strings
        for k in range(n_new):
            ln = ints[i + k]
            strings.append(bytes(buf[off : off + ln]).decode("utf-8"))
            off += ln
        i += n_new
        blob_base = off

        n_ops = ints[i]
        i += 1
        ops: List[Any] = []
        blob_rows: List[Tuple[int, int]] = []  # (ops index, blob index)
        for _ in range(n_ops):
            verb = strings[ints[i]]
            kind = strings[ints[i + 1]]
            rowtype = ints[i + 2]
            i += 3
            if rowtype == ROW_POD:
                ops.append((verb, kind, self._pod(ints[i : i + 9])))
                i += 9
            elif rowtype == ROW_KEY:
                ops.append((verb, kind, strings[ints[i]]))
                i += 1
            elif rowtype == ROW_BLOB:
                blob_rows.append((len(ops), ints[i]))
                ops.append((verb, kind, None))
                i += 1
            else:
                raise TornSlotError(f"unknown row type {rowtype}")

        n_blobs = ints[i]
        i += 1
        starts = [blob_base]
        for k in range(n_blobs):
            starts.append(starts[-1] + ints[i + k])
        for op_idx, bidx in blob_rows:
            raw = bytes(buf[starts[bidx] : starts[bidx + 1]])
            verb, kind, _ = ops[op_idx]
            # local-memory bytes our own front wrote — see the module
            # docstring's trust-domain note (taint-checker exemption)
            ops[op_idx] = (verb, kind, pickle.loads(raw))
        return int(epoch), int(seq), ops

    def _str(self, sid: int):
        return None if sid == _NONE_SID else self._strings[sid]

    def _label(self, sid: int):
        if sid == _NONE_SID:
            return None
        out = self._labels.get(sid)
        if out is None:
            out = dict(json.loads(self._strings[sid]))
            self._labels[sid] = out
        return out

    def _req(self, sid: int) -> tuple:
        out = self._reqs.get(sid)
        if out is None:
            out = parse_request_shape(json.loads(self._strings[sid]))
            self._reqs[sid] = out
        return out

    def _pod(self, row) -> Pod:
        containers, init, overhead = self._req(row[8])
        return Pod(
            name=self._strings[row[0]],
            namespace=self._strings[row[1]],
            labels=self._label(row[6]),
            annotations=self._label(row[7]),
            uid=self._str(row[2]),
            spec=PodSpec(
                scheduler_name=self._str(row[3]),
                node_name=self._str(row[4]),
                containers=list(containers),
                init_containers=list(init),
                overhead=overhead,
            ),
            status=PodStatus(phase=self._str(row[5])),
        )


# ---------------------------------------------------------------- event lane


class ShmEventLane:
    """Writer + persistent encoder + frame sequencing — the object the
    supervisor hangs on a ShardClient. Sender-thread-only except
    ``close``/``stats`` (the writer's lock covers those). A failed push
    kills the lane for good: the encoder's string table may be ahead of
    the reader, so the only safe continuation is the supervisor's
    restart + resync with a fresh segment."""

    # one frame must leave slack in the arena; bigger batches split
    MAX_FRAME_FRACTION = 2

    def __init__(self, writer: ShmRingWriter):
        self.writer = writer
        self.encoder = FrameEncoder()
        self.seq = 0
        self.dead = False

    def send(self, ops, epoch: int, timeout: float = 5.0) -> bool:
        if self.dead:
            return False
        # split *before* encoding — the encoder's string table advances
        # at encode time, so an encoded frame must never be abandoned
        limit = self.writer.arena_bytes // self.MAX_FRAME_FRACTION
        if len(ops) > max(64, limit // 4096):
            mid = len(ops) // 2
            return self.send(ops[:mid], epoch, timeout) and self.send(
                ops[mid:], epoch, timeout
            )
        payload = self.encoder.encode(ops, epoch, self.seq)
        ok = self.writer.push(payload, timeout)
        if ok:
            self.seq += 1
        else:
            self.dead = True
        return ok

    def inflight(self) -> int:
        return 0 if self.dead else self.writer.inflight()

    def stats(self) -> Dict[str, int]:
        return self.writer.stats()

    def close(self) -> None:
        self.dead = True
        self.writer.close(unlink=True)
