"""Local IPC between the admission front and shard workers.

Frame protocol (both directions, over one stream socket per shard):

    [4-byte little-endian length][pickled (mtype, rid, body)]

Message types:

- ``"evt"``  front→shard, one-way: an ordered batch of store ops
  ``[(verb, kind, payload), ...]`` for the shard's ingest pipeline.
  Objects travel as their dataclass form (pickle protocol 5) — the
  supervisor spawns the workers from the same code tree, so this is the
  trusted-local analog of the replication stream's JSON event lines
  (engine/replication.py), chosen over JSON for the ~2× lower
  per-event encode+decode cost on the ingest hot path.
- ``"req"``/``"res"`` — RPC with a front-assigned request id; the
  scatter-gather calls (pre_filter, two-phase reserve, gang ops,
  stats/drain) ride this.
- ``"push"`` shard→front, one-way: status events (the shard's
  controllers wrote a Throttle/ClusterThrottle status) streaming back
  so the front's store stays the merged read view — flips first, like
  the two-lane pipeline they came from.

Overflow posture mirrors ``MicroBatchIngest``: the event queue is
bounded and sheds ONLY pod upserts (verdict-safe); a shed marks the
shard dirty so the supervisor's next resync repairs the gap. Sends to a
dead shard count as route misses and mark it dirty likewise.
"""

from __future__ import annotations

import logging
import pickle
import socket
import struct
import threading
from collections import deque
from typing import Callable, Optional, Sequence, Tuple

from ..utils.lockorder import guard_attrs, make_lock

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<I")
PICKLE_PROTO = 5

# (verb, kind, payload) — the Store.apply_events op shape
Op = Tuple[str, str, object]


class ShardUnavailable(Exception):
    """The shard's transport is down (process died / socket closed)."""


def send_frame(sock: socket.socket, send_lock, mtype: str, rid: int, body) -> None:
    payload = pickle.dumps((mtype, rid, body), protocol=PICKLE_PROTO)
    with send_lock:
        sock.sendall(_LEN.pack(len(payload)) + payload)


def read_frame(rfile) -> Optional[Tuple[str, int, object]]:
    """Read one frame from a buffered reader; None on EOF."""
    header = rfile.read(_LEN.size)
    if not header or len(header) < _LEN.size:
        return None
    (n,) = _LEN.unpack(header)
    payload = rfile.read(n)
    if len(payload) < n:
        return None
    return pickle.loads(payload)


@guard_attrs
class ShardClient:
    """Front-side handle for one shard over a stream socket.

    A sender thread drains the bounded event queue into ``evt`` frames
    (one frame per drain — the IPC analog of the store's group commit);
    a reader thread demultiplexes ``res`` frames into pending request
    slots and hands ``push`` frames to the front's applier. All three
    are decoupled from the store lock the router runs under.
    """

    MAX_QUEUE = 65536
    EVT_BATCH = 512

    GUARDED_BY = {
        "_queue": "self._qlock",
        "_pending": "self._plock",
        "_rid": "self._plock",
        "dropped": "self._qlock",
        "dirty": "self._qlock",
    }

    def __init__(
        self,
        shard_id: int,
        sock: socket.socket,
        on_push: Optional[Callable[[int, list], None]] = None,
        on_down: Optional[Callable[[int], None]] = None,
        faults=None,
        maxsize: Optional[int] = None,
    ):
        self.shard_id = shard_id
        self.sock = sock
        self.on_push = on_push
        self.on_down = on_down
        self.faults = faults
        self.maxsize = maxsize or self.MAX_QUEUE
        self._send_lock = make_lock(f"shard.client.send.{shard_id}")
        self._qlock = make_lock(f"shard.client.queue.{shard_id}")
        self._qcond = threading.Condition(self._qlock)
        self._queue: "deque[Op]" = deque()
        self._plock = make_lock(f"shard.client.pending.{shard_id}")
        self._pending = {}  # rid -> [threading.Event, response|None]
        self._rid = 0
        self._rfile = sock.makefile("rb")
        self._alive = True  # single-writer (reader thread) after init
        self._closed = False
        # single-writer stats (sender/reader threads); read by metrics
        self.events_sent = 0
        self.frames_sent = 0
        self.dropped = 0  # verdict-safe sheds (queue overflow)
        self.dirty = False  # lost events/sends — needs resync
        self._sender = threading.Thread(
            target=self._send_loop, name=f"shard{shard_id}-send", daemon=True
        )
        self._reader = threading.Thread(
            target=self._read_loop, name=f"shard{shard_id}-read", daemon=True
        )
        self._sender.start()
        self._reader.start()

    # ------------------------------------------------------------- events

    @staticmethod
    def _sheddable(op: Op) -> bool:
        verb, kind, _ = op
        return kind == "Pod" and verb != "delete"

    def enqueue_ops(self, ops: Sequence[Op]) -> None:
        """Queue ops for the shard; never blocks (verdict-safe shed)."""
        with self._qcond:
            if self._closed:
                return
            for op in ops:
                if len(self._queue) >= self.maxsize:
                    idx = next(
                        (i for i, q in enumerate(self._queue) if self._sheddable(q)),
                        None,
                    )
                    if idx is not None:
                        del self._queue[idx]
                        self.dropped += 1
                        self.dirty = True
                    elif self._sheddable(op):
                        self.dropped += 1
                        self.dirty = True
                        continue
                self._queue.append(op)
            self._qcond.notify()

    def mark_dirty(self) -> None:
        with self._qcond:
            self.dirty = True

    def clear_dirty(self) -> None:
        with self._qcond:
            self.dirty = False

    def is_dirty(self) -> bool:
        """Locked read of the needs-resync flag — ``dirty`` is GUARDED_BY
        the queue lock; the health probe reading it bare raced the sender
        marking it (lockset detector, gen-3)."""
        with self._qcond:
            return self.dirty

    def pending_events(self) -> int:
        with self._qcond:
            return len(self._queue)

    def _send_loop(self) -> None:
        # top-level routing (threads checker): ANY death of the sender —
        # transport failure or a bug — must surface as a down shard (the
        # supervisor restarts + resyncs), never as a silently growing
        # queue behind a dead thread
        try:
            while True:
                with self._qcond:
                    while not self._queue and not self._closed:
                        self._qcond.wait(0.2)
                    if self._closed and not self._queue:
                        return
                    batch = [
                        self._queue.popleft()
                        for _ in range(min(len(self._queue), self.EVT_BATCH))
                    ]
                try:
                    if self.faults is not None:
                        fault = self.faults.check("shard.ipc.send")
                        if fault is not None:
                            raise OSError(
                                f"injected IPC send failure (hit {fault.hit})"
                            )
                    send_frame(self.sock, self._send_lock, "evt", 0, batch)
                    self.events_sent += len(batch)
                    self.frames_sent += 1
                except OSError:
                    # shard gone mid-send: these events are lost to it — the
                    # supervisor's restart+resync repairs the gap
                    with self._qcond:
                        self.dropped += len(batch)
                        self.dirty = True
                    if not self._closed:
                        self._mark_down()
                    return
        except Exception:  # noqa: BLE001 — route the death, don't hide it
            logger.exception("shard %d: sender died", self.shard_id)
            if not self._closed:
                self._mark_down()

    # ---------------------------------------------------------------- RPC

    def request(self, op: str, payload=None, timeout: float = 30.0):
        """Blocking RPC; raises :class:`ShardUnavailable` on a dead shard
        or timeout, re-raises shard-side errors as RuntimeError."""
        if not self._alive:
            raise ShardUnavailable(f"shard {self.shard_id} is down")
        with self._plock:
            self._rid += 1
            rid = self._rid
            slot = [threading.Event(), None]
            self._pending[rid] = slot
        try:
            send_frame(self.sock, self._send_lock, "req", rid, (op, payload))
        except OSError:
            with self._plock:
                self._pending.pop(rid, None)
            self._mark_down()
            raise ShardUnavailable(f"shard {self.shard_id} send failed") from None
        if not slot[0].wait(timeout):
            with self._plock:
                self._pending.pop(rid, None)
            raise ShardUnavailable(
                f"shard {self.shard_id} did not answer {op} within {timeout}s"
            )
        if slot[1] is None:
            raise ShardUnavailable(f"shard {self.shard_id} died during {op}")
        ok, body = slot[1]
        if not ok:
            raise RuntimeError(f"shard {self.shard_id} {op} failed: {body}")
        return body

    def _read_loop(self) -> None:
        try:
            while True:
                frame = read_frame(self._rfile)
                if frame is None:
                    break
                mtype, rid, body = frame
                if mtype == "res":
                    with self._plock:
                        slot = self._pending.pop(rid, None)
                    if slot is not None:
                        slot[1] = body
                        slot[0].set()
                elif mtype == "push" and self.on_push is not None:
                    self.on_push(self.shard_id, body)
        except (OSError, pickle.UnpicklingError, EOFError):
            pass
        finally:
            if not self._closed:
                self._mark_down()

    # ----------------------------------------------------------- lifecycle

    @property
    def alive(self) -> bool:
        return self._alive and not self._closed

    def _mark_down(self) -> None:
        was = self._alive
        self._alive = False
        # wake every waiter: their shard will not answer
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        for slot in pending:
            slot[0].set()
        with self._qcond:
            self.dirty = True
            self._qcond.notify_all()
        if was and self.on_down is not None:
            self.on_down(self.shard_id)

    def close(self) -> None:
        self._closed = True
        with self._qcond:
            self._qcond.notify_all()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class LocalShard:
    """In-process shard handle for deterministic tests: wraps a
    :class:`worker.ShardCore` directly — same surface as
    :class:`ShardClient`, no sockets, events applied synchronously."""

    def __init__(self, shard_id: int, core, on_push=None):
        self.shard_id = shard_id
        self.core = core
        self.events_sent = 0
        self.frames_sent = 0
        self.dropped = 0
        self.dirty = False
        self.alive = True
        if on_push is not None:
            core.push = lambda items: on_push(shard_id, items)

    def enqueue_ops(self, ops: Sequence[Op]) -> None:
        if not self.alive:
            self.dropped += len(ops)
            self.dirty = True
            return
        self.core.handle_events(list(ops))
        self.events_sent += len(ops)
        self.frames_sent += 1

    def is_dirty(self) -> bool:
        return self.dirty  # synchronous single-thread handle: no lock

    def pending_events(self) -> int:
        return 0

    def mark_dirty(self) -> None:
        self.dirty = True

    def clear_dirty(self) -> None:
        self.dirty = False

    def request(self, op: str, payload=None, timeout: float = 30.0):
        if not self.alive:
            raise ShardUnavailable(f"shard {self.shard_id} is down")
        ok, body = self.core.rpc(op, payload)
        if not ok:
            raise RuntimeError(f"shard {self.shard_id} {op} failed: {body}")
        return body

    def close(self) -> None:
        self.alive = False


__all__ = [
    "Op",
    "ShardClient",
    "ShardUnavailable",
    "LocalShard",
    "send_frame",
    "read_frame",
    "PICKLE_PROTO",
]
