"""IPC between the admission front and shard workers — socketpair or TCP.

Frame protocol (both directions, over one stream socket per shard):

    [4-byte little-endian length][pickled (mtype, rid, body, epoch)]

Message types:

- ``"evt"``  front→shard, one-way: an ordered batch of store ops
  ``[(verb, kind, payload), ...]`` for the shard's ingest pipeline.
  Objects travel as their dataclass form (pickle protocol 5) — the
  supervisor spawns the workers from the same code tree, so this is the
  trusted-local analog of the replication stream's JSON event lines
  (engine/replication.py), chosen over JSON for the ~2× lower
  per-event encode+decode cost on the ingest hot path.
- ``"req"``/``"res"`` — RPC with a front-assigned request id; the
  scatter-gather calls (pre_filter, two-phase reserve, gang ops,
  stats/drain) ride this.
- ``"push"`` shard→front, one-way: status events (the shard's
  controllers wrote a Throttle/ClusterThrottle status) streaming back
  so the front's store stays the merged read view — flips first, like
  the two-lane pipeline they came from.
- ``"sub"``  front→shard, one-way: subscribe THIS connection to the
  shard's push stream. A socketpair carries exactly one connection so
  the worker binds pushes at accept; a TCP client keeps a small pool of
  connections and nominates its primary lane. The body is the front's
  HELLO — ``{"proto": [major, minor], "caps": [...], "build": ...}``
  (kube_throttler_tpu/version.py) — or ``None`` from a pre-handshake
  build, which negotiates as the zero-capability 1.0 baseline.
- ``"hello"`` shard→front: the worker's handshake answer. On agreement
  it carries the negotiated ``(proto, caps)`` plus the worker's build
  id; on an incompatible MAJOR it carries a typed refusal
  (``{"error": "VersionMismatch: ..."}``) and the worker drops the
  connection — the front surfaces :class:`VersionMismatch`, reports the
  shard degraded, counts the refusal, and redials at the backoff CAP
  (an operator fixes versions; the client must not hot-spin). Minor
  capabilities negotiate down to the intersection, so an old worker and
  a new front interoperate for the whole rolling upgrade.

Epoch fencing (PR 6 ``FencingEpoch``, end to end over the wire): every
frame carries the sender's view of the shard's fencing epoch. The front
owns the counter — it bumps it at the head of every resync (a restart,
a reconnect after a partition, a reshard retarget) — and the worker
tracks the max it has seen. A frame stamped with a LOWER epoch is a
message from the past: a partitioned-then-healed peer, or bytes that sat
in a kernel buffer across a heal. The worker drops stale ``evt`` batches
and refuses stale ``req`` frames with a :class:`FencedError` body (the
on-the-wire 409); the front drops stale ``push`` frames. Socketpair
transports never bump (epoch 0 both sides), so the fencing layer is
inert there — a dead child's socket dies with it.

Network fault sites (``net.*`` in faults/plan.py), injected HERE at the
framing layer so one seeded :class:`~..faults.plan.FaultPlan` drives
both transports identically:

- ``net.partition``       — sends raise without writing a byte
  (blackholed link). Armed per-plan-holder, so arming only one
  direction makes an ASYMMETRIC partition.
- ``net.send.torn_frame`` — a send writes only a prefix of the frame,
  then dies; the peer's ``read_frame`` must surface it as a clean EOF,
  never a partial frame.
- ``net.recv.stall``      — the receive path sleeps the rule's
  ``delay`` before the next frame (slow link / half-open socket).
- ``net.connect.refused`` — the TCP client's connect attempt is
  refused (checked in the reconnector).
- ``net.reconnect.storm`` — a just-reestablished connection dies again
  immediately (flapping link; the backoff must keep growing).

Overflow posture mirrors ``MicroBatchIngest``: the event queue is
bounded and sheds ONLY pod upserts (verdict-safe); a shed marks the
shard dirty so the supervisor's next resync repairs the gap. Sends to a
dead shard count as route misses and mark it dirty likewise.

TRUST BOUNDARY — the payload is pickle, and ``pickle.loads`` on
attacker-controlled bytes is arbitrary code execution. Over a
socketpair the peer is a child the supervisor forked from this code
tree, so the trusted-local assumption holds by construction. Over TCP
it does NOT: anything that can reach the port could feed the
deserializer. Cross-host mode therefore authenticates every frame with
a pre-shared key — ``[len][HMAC-SHA256(key, payload)][payload]`` — and
the MAC is verified BEFORE the payload is unpickled; a frame that
fails the MAC (no key, wrong key, tampered bytes) is dropped as a torn
stream and the lane dies. The worker refuses to listen on a
non-loopback address without a key (``worker.py --auth-key-file`` /
``KT_SHARD_AUTH_KEY``). The key authenticates, it does not encrypt:
frames still travel plaintext, so keep the port scoped to the fleet
(NetworkPolicy, private network) — see deploy/sharded-fleet.yaml and
docs/robustness.md "Transport security".
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import os
import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from ..utils.lockorder import guard_attrs, make_lock
from ..version import CAPABILITIES, PROTO_VERSION, local_hello

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<I")
PICKLE_PROTO = 5
# A 4-byte length from a torn/hostile stream can claim up to 4 GiB;
# nothing legitimate approaches this (evt batches cap at EVT_BATCH ops,
# reshard slices chunk well below it) — anything larger is a misaligned
# tear or garbage and must die as a torn stream, not an allocation.
MAX_FRAME = 64 * 1024 * 1024
_MAC_LEN = hashlib.sha256().digest_size  # 32

AuthKey = Optional[Union[str, bytes]]


def _as_key_bytes(key: AuthKey) -> Optional[bytes]:
    if key is None or isinstance(key, bytes):
        return key
    return key.encode("utf-8")


def load_auth_key(path: str = "", env: str = "KT_SHARD_AUTH_KEY") -> Optional[bytes]:
    """Resolve the fleet's frame-auth pre-shared key: an explicit key
    file (a mounted Secret) wins over the environment variable; either
    is stripped of surrounding whitespace. ``None`` = unauthenticated
    (loopback/socketpair only)."""
    if path:
        with open(path, "rb") as fh:
            key = fh.read().strip()
        if not key:
            raise ValueError(f"auth key file {path!r} is empty")
        return key
    val = os.environ.get(env, "").strip()
    return val.encode("utf-8") if val else None

# (verb, kind, payload) — the Store.apply_events op shape
Op = Tuple[str, str, object]


class ShardUnavailable(Exception):
    """The shard's transport is down (process died / socket closed /
    partitioned / RPC deadline exceeded)."""


class FencedError(RuntimeError):
    """The peer refused a stale-epoch frame — the wire-level 409. The
    holder of a stale epoch missed a resync/reshard/promotion while
    partitioned and must NOT be trusted until re-synced."""


class VersionMismatch(RuntimeError):
    """The peer refused the handshake: incompatible protocol MAJOR
    (version.py compatibility rules). Deliberate and terminal until one
    side is upgraded — the front degrades fail-safe and keeps redialing
    slowly; nothing crash-loops."""


# Capability-gated "evt" batch encodings. v1 (the only pre-handshake
# form) is the plain op list; "evt-columnar" peers accept the
# struct-of-arrays transpose — shared verb/kind strings collapse into
# three homogeneous columns instead of riding every row's tuple. The
# DECODER is shape-sniffing and always available; the negotiated
# capability gates the SENDER, so an old worker only ever sees v1.
_EVT_COLS_V2 = "__kt_evt_cols_v2__"


def encode_evt_batch(ops: Sequence["Op"]) -> tuple:
    return (
        _EVT_COLS_V2,
        [op[0] for op in ops],
        [op[1] for op in ops],
        [op[2] for op in ops],
    )


def decode_evt_batch(body) -> List["Op"]:
    if (
        isinstance(body, tuple)
        and len(body) == 4
        and body[0] == _EVT_COLS_V2
    ):
        return list(zip(body[1], body[2], body[3]))
    return list(body)


# pickles PrepickledPayload performed (the fan-out dedup invariant:
# one shared body fanned to N shards serializes exactly once)
PREPICKLE_SERIALIZATIONS = 0


class PrepickledPayload:
    """One event body fanned out to MANY shard queues: pickle at most once.

    The router regularly enqueues the *same* payload object to several
    shards (namespace broadcasts, pod upserts to owner + mirror sets).
    Without this wrapper each shard's sender re-pickles the identical
    object inside its own ``evt`` frame. The wrapper pickles lazily on
    first use and replays the cached bytes into every later frame via
    ``__reduce__``, so the receiving side unpickles transparently back
    to the original object — no capability gate, any peer decodes it.
    The shm event lane reads ``.obj`` for pod rows and ``pickled()``
    for blob rows. Two sender threads may race ``pickled()``; the worst
    case is a duplicate serialization, never a wrong frame.
    """

    __slots__ = ("obj", "blob")
    _kt_prepickled = True  # duck-type marker (shmring avoids the import)

    def __init__(self, obj):
        self.obj = obj
        self.blob: Optional[bytes] = None

    def pickled(self) -> bytes:
        blob = self.blob
        if blob is None:
            global PREPICKLE_SERIALIZATIONS
            PREPICKLE_SERIALIZATIONS += 1
            blob = pickle.dumps(self.obj, protocol=PICKLE_PROTO)
            self.blob = blob
        return blob

    def __reduce__(self):
        return (pickle.loads, (self.pickled(),))


def unwrap_op(op: "Op") -> "Op":
    """The in-process form of an op: prepickled wrappers unwrapped."""
    verb, kind, payload = op
    if getattr(payload, "_kt_prepickled", False):
        return (verb, kind, payload.obj)
    return op


def send_frame(
    sock: socket.socket, send_lock, mtype: str, rid: int, body,
    epoch: int = 0, faults=None, key: AuthKey = None,
) -> None:
    """Pickle and send one frame. ``faults`` arms the framing-layer
    ``net.*`` sites (same seeded plan drives socketpair and TCP).
    ``key`` prepends an HMAC-SHA256 of the payload (cross-host mode:
    the peer verifies it before unpickling a byte)."""
    payload = pickle.dumps((mtype, rid, body, epoch), protocol=PICKLE_PROTO)
    kb = _as_key_bytes(key)
    if kb is not None:
        payload = hmac.new(kb, payload, hashlib.sha256).digest() + payload
    frame = _LEN.pack(len(payload)) + payload
    if faults is not None:
        fault = faults.check("net.partition")
        if fault is not None:
            # blackholed link: nothing reaches the wire; the caller
            # handles it exactly like a peer that vanished
            raise OSError(f"injected partition (hit {fault.hit}): frame blackholed")
        fault = faults.check("net.send.torn_frame")
        if fault is not None:
            with send_lock:
                sock.sendall(frame[: max(1, len(frame) // 2)])
            raise OSError(f"injected torn frame (hit {fault.hit}): prefix only")
    with send_lock:
        sock.sendall(frame)


def read_frame(
    rfile, faults=None, key: AuthKey = None,
) -> Optional[Tuple[str, int, object, int]]:
    """Read one frame from a buffered reader; None on EOF or a torn
    (short) frame — a partial frame is never surfaced. With ``key`` the
    leading HMAC is verified before ``pickle.loads`` ever runs: a frame
    from a peer without the key (or tampered in flight) is dropped as a
    torn stream, so an unauthenticated client can never reach the
    deserializer."""
    if faults is not None:
        fault = faults.check("net.recv.stall")
        if fault is not None:
            fault.sleep()  # slow link: the peer's deadlines must fire
    header = rfile.read(_LEN.size)
    if not header or len(header) < _LEN.size:
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        # a misaligned tear (or garbage) parses as a length up to 4 GiB;
        # reading toward it would stall the lane and spike memory — the
        # framing is lost either way, so die as a torn stream
        return None
    payload = rfile.read(n)
    if len(payload) < n:
        return None
    kb = _as_key_bytes(key)
    if kb is not None:
        if n < _MAC_LEN:
            return None
        mac, payload = payload[:_MAC_LEN], payload[_MAC_LEN:]
        if not hmac.compare_digest(
            mac, hmac.new(kb, payload, hashlib.sha256).digest()
        ):
            return None  # unauthenticated peer / wrong key / tampered
    try:
        return pickle.loads(payload)
    except Exception:  # noqa: BLE001 — undecodable bytes = torn stream
        # a torn write can leave the stream mid-frame: the bytes after
        # the tear parse as a bogus length and land here. The lane is
        # unrecoverable (framing lost) — report EOF, the peer redials.
        return None


def _raise_shard_error(shard_id: int, op: str, body) -> None:
    """Map a shard-side ``(False, body)`` RPC answer to the right client
    exception: a ``FencedError:``-prefixed body is the wire 409."""
    msg = str(body)
    if msg.startswith("FencedError"):
        raise FencedError(f"shard {shard_id} {op} fenced: {msg}")
    if msg.startswith("VersionMismatch"):
        raise VersionMismatch(f"shard {shard_id} {op} refused: {msg}")
    raise RuntimeError(f"shard {shard_id} {op} failed: {msg}")


def _sheddable(op: Op) -> bool:
    verb, kind, _ = op
    return kind == "Pod" and verb != "delete"


def _apply_hello(handle, body) -> None:
    """Record a worker's ``hello`` answer on a client handle (runs on
    the handle's reader thread — the negotiation fields are that
    thread's single-writer state, read racily by metrics/health)."""
    if isinstance(body, dict) and "error" in body:
        handle.version_refused = str(body["error"])
        handle.version_mismatches += 1
        logger.warning(
            "shard %d: handshake refused: %s",
            handle.shard_id, handle.version_refused,
        )
        return
    try:
        proto = (int(body["proto"][0]), int(body["proto"][1]))
        caps = frozenset(
            c for c in body.get("caps", ()) if isinstance(c, str)
        )
    except (TypeError, KeyError, ValueError, IndexError):
        logger.warning(
            "shard %d: malformed hello %r", handle.shard_id, body
        )
        return
    handle.negotiated_proto = proto
    handle.negotiated_caps = caps
    handle.peer_build = body.get("build")
    handle.version_refused = None


@guard_attrs
class ShardClient:
    """Front-side handle for one shard over a stream socket.

    A sender thread drains the bounded event queue into ``evt`` frames
    (one frame per drain — the IPC analog of the store's group commit);
    a reader thread demultiplexes ``res`` frames into pending request
    slots and hands ``push`` frames to the front's applier. All three
    are decoupled from the store lock the router runs under.
    """

    transport = "socketpair"
    MAX_QUEUE = 65536
    EVT_BATCH = 512

    GUARDED_BY = {
        "_queue": "self._qlock",
        "_pending": "self._plock",
        "_rid": "self._plock",
        "deadline_exceeded": "self._plock",
        "dropped": "self._qlock",
        "dirty": "self._qlock",
    }
    # NOT guarded by design: shm_lane (supervisor single-writer, bound
    # once before any event flows; the lane's own lock covers close
    # racing push), _shm_active (sender-thread single-writer),
    # shm_fallback_batches (sender-thread single-writer, read by
    # metrics at scrape like events_sent).

    def __init__(
        self,
        shard_id: int,
        sock: socket.socket,
        on_push: Optional[Callable[[int, list], None]] = None,
        on_down: Optional[Callable[[int], None]] = None,
        faults=None,
        maxsize: Optional[int] = None,
        default_deadline: float = 30.0,
        deadlines: Optional[Dict[str, float]] = None,
    ):
        self.shard_id = shard_id
        self.sock = sock
        self.on_push = on_push
        self.on_down = on_down
        self.faults = faults
        self.maxsize = maxsize or self.MAX_QUEUE
        # per-op RPC deadline budget: explicit per-op entries override
        # the default; ``request(timeout=None)`` resolves through this
        self.default_deadline = float(default_deadline)
        self.deadlines: Dict[str, float] = dict(deadlines or {})
        self.epoch = 0  # socketpair transport never bumps (fencing inert)
        self._send_lock = make_lock(f"shard.client.send.{shard_id}")
        self._qlock = make_lock(f"shard.client.queue.{shard_id}")
        self._qcond = threading.Condition(self._qlock)
        self._queue: "deque[Op]" = deque()
        self._plock = make_lock(f"shard.client.pending.{shard_id}")
        self._pending = {}  # rid -> [threading.Event, response|None]
        self._rid = 0
        self._rfile = sock.makefile("rb")
        self._alive = True  # single-writer (reader thread) after init
        self._closed = False
        # single-writer stats (sender/reader threads); read by metrics
        self.events_sent = 0
        self.frames_sent = 0
        self.dropped = 0  # verdict-safe sheds (queue overflow)
        self.dirty = False  # lost events/sends — needs resync
        self.deadline_exceeded = 0  # RPCs that outran their budget
        self.reconnects = 0  # a socketpair cannot reconnect (metrics parity)
        # handshake outcome (reader-thread single-writer after the
        # worker's hello lands; None until then = 1.0 baseline, no caps)
        self.negotiated_proto: Optional[Tuple[int, int]] = None
        self.negotiated_caps: Optional[frozenset] = None
        self.peer_build: Optional[str] = None
        self.version_refused: Optional[str] = None
        self.version_mismatches = 0
        # shared-memory event lane (sharding/shmring.py): bound by the
        # supervisor right after construction when the ring spawned with
        # the worker; None ⇒ pickle frames on the socket, always
        self.shm_lane = None
        self._shm_active = False  # sender-thread: barrier completed
        self.shm_fallback_batches = 0  # evt batches pickled despite a lane
        self._sender = threading.Thread(
            target=self._send_loop, name=f"shard{shard_id}-send", daemon=True
        )
        self._reader = threading.Thread(
            target=self._read_loop, name=f"shard{shard_id}-read", daemon=True
        )
        self._sender.start()
        self._reader.start()
        # version/capability handshake: a socketpair has exactly one
        # "connection", so the hello rides a sub frame at construction
        # (the worker re-binds the same push sink — idempotent). Sent
        # without the fault plan: the handshake is not a chaos target.
        try:
            send_frame(self.sock, self._send_lock, "sub", 0, local_hello(),
                       epoch=self.epoch)
        except OSError:
            pass  # a dead-at-birth child surfaces through the reader

    # ------------------------------------------------------------- events

    def enqueue_ops(self, ops: Sequence[Op]) -> None:
        """Queue ops for the shard; never blocks (verdict-safe shed)."""
        with self._qcond:
            if self._closed:
                return
            for op in ops:
                if len(self._queue) >= self.maxsize:
                    idx = next(
                        (i for i, q in enumerate(self._queue) if _sheddable(q)),
                        None,
                    )
                    if idx is not None:
                        del self._queue[idx]
                        self.dropped += 1
                        self.dirty = True
                    elif _sheddable(op):
                        self.dropped += 1
                        self.dirty = True
                        continue
                self._queue.append(op)
            self._qcond.notify()

    def mark_dirty(self) -> None:
        with self._qcond:
            self.dirty = True

    def clear_dirty(self) -> None:
        with self._qcond:
            self.dirty = False

    def is_dirty(self) -> bool:
        """Locked read of the needs-resync flag — ``dirty`` is GUARDED_BY
        the queue lock; the health probe reading it bare raced the sender
        marking it (lockset detector, gen-3)."""
        with self._qcond:
            return self.dirty

    def pending_events(self) -> int:
        lane = self.shm_lane
        in_ring = lane.inflight() if (lane is not None and self._shm_active) else 0
        with self._qcond:
            return len(self._queue) + in_ring

    def _send_loop(self) -> None:
        # top-level routing (threads checker): ANY death of the sender —
        # transport failure or a bug — must surface as a down shard (the
        # supervisor restarts + resyncs), never as a silently growing
        # queue behind a dead thread
        try:
            while True:
                with self._qcond:
                    while not self._queue and not self._closed:
                        self._qcond.wait(0.2)
                    if self._closed and not self._queue:
                        return
                    batch = [
                        self._queue.popleft()
                        for _ in range(min(len(self._queue), self.EVT_BATCH))
                    ]
                lane = self.shm_lane
                if (
                    lane is not None
                    and not self._shm_active
                    and not lane.dead
                    and self.has_cap("evt-shm")
                ):
                    # one-time ordering barrier before cutting over to
                    # the ring: the socket is FIFO into the worker's
                    # serve loop, so a completed RPC proves every
                    # earlier socket evt frame was already ingested.
                    # After this flips, evt NEVER rides the socket again
                    # (a failed ring push kills the lane → shard down →
                    # restart + resync, same repair as a dead socket).
                    try:
                        self.request("stats")
                        self._shm_active = True
                    except Exception:  # noqa: BLE001 — stay on the socket
                        pass
                try:
                    if self.faults is not None:
                        fault = self.faults.check("shard.ipc.send")
                        if fault is not None:
                            raise OSError(
                                f"injected IPC send failure (hit {fault.hit})"
                            )
                    if lane is not None and self._shm_active:
                        if not lane.send(batch, epoch=self.epoch):
                            raise OSError(
                                "shm event lane dead (ring stalled or closed)"
                            )
                    else:
                        if lane is not None:
                            self.shm_fallback_batches += 1
                        body = (
                            encode_evt_batch(batch)
                            if self.has_cap("evt-columnar")
                            else batch
                        )
                        send_frame(self.sock, self._send_lock, "evt", 0, body,
                                   epoch=self.epoch, faults=self.faults)
                    self.events_sent += len(batch)
                    self.frames_sent += 1
                except OSError:
                    # shard gone mid-send: these events are lost to it — the
                    # supervisor's restart+resync repairs the gap
                    with self._qcond:
                        self.dropped += len(batch)
                        self.dirty = True
                    if not self._closed:
                        self._mark_down()
                    return
        except Exception:  # noqa: BLE001 — route the death, don't hide it
            logger.exception("shard %d: sender died", self.shard_id)
            if not self._closed:
                self._mark_down()

    # ---------------------------------------------------------------- RPC

    def deadline_for(self, op: str) -> float:
        return self.deadlines.get(op, self.default_deadline)

    def request(self, op: str, payload=None, timeout: Optional[float] = None):
        """Blocking RPC; raises :class:`ShardUnavailable` on a dead shard
        or an exceeded deadline, :class:`FencedError` on a stale-epoch
        refusal, re-raises other shard-side errors as RuntimeError.
        ``timeout=None`` resolves through the per-op deadline budget."""
        if timeout is None:
            timeout = self.deadline_for(op)
        if not self._alive:
            raise ShardUnavailable(f"shard {self.shard_id} is down")
        with self._plock:
            self._rid += 1
            rid = self._rid
            slot = [threading.Event(), None]
            self._pending[rid] = slot
        try:
            send_frame(self.sock, self._send_lock, "req", rid, (op, payload),
                       epoch=self.epoch, faults=self.faults)
        except OSError:
            with self._plock:
                self._pending.pop(rid, None)
            self._mark_down()
            raise ShardUnavailable(f"shard {self.shard_id} send failed") from None
        if not slot[0].wait(timeout):
            with self._plock:
                self._pending.pop(rid, None)
                self.deadline_exceeded += 1
            raise ShardUnavailable(
                f"shard {self.shard_id} did not answer {op} within {timeout}s"
            )
        if slot[1] is None:
            raise ShardUnavailable(f"shard {self.shard_id} died during {op}")
        ok, body = slot[1]
        if not ok:
            _raise_shard_error(self.shard_id, op, body)
        return body

    def _read_loop(self) -> None:
        try:
            while True:
                frame = read_frame(self._rfile, self.faults)
                if frame is None:
                    break
                mtype, rid, body, _epoch = frame
                if mtype == "res":
                    with self._plock:
                        slot = self._pending.pop(rid, None)
                    if slot is not None:
                        slot[1] = body
                        slot[0].set()
                elif mtype == "push" and self.on_push is not None:
                    self.on_push(self.shard_id, body)
                elif mtype == "hello":
                    _apply_hello(self, body)
        except (OSError, pickle.UnpicklingError, EOFError):
            pass
        finally:
            if not self._closed:
                self._mark_down()

    # ----------------------------------------------------------- lifecycle

    @property
    def alive(self) -> bool:
        return self._alive and not self._closed

    def has_cap(self, name: str) -> bool:
        """True iff the handshake negotiated this minor capability.
        False before the worker's hello lands — pre-handshake traffic
        uses the v1 baseline encodings by construction."""
        caps = self.negotiated_caps
        return caps is not None and name in caps

    def _mark_down(self) -> None:
        was = self._alive
        self._alive = False
        # wake every waiter: their shard will not answer
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        for slot in pending:
            slot[0].set()
        with self._qcond:
            self.dirty = True
            self._qcond.notify_all()
        if was and self.on_down is not None:
            self.on_down(self.shard_id)

    def close(self) -> None:
        self._closed = True
        with self._qcond:
            self._qcond.notify_all()
        lane = self.shm_lane
        if lane is not None:
            lane.close()  # unlinks the segment — the creator owns the name
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class _Conn:
    """One established TCP connection in a :class:`TcpShardClient` pool:
    socket + its send lock + the reader thread bound to it.

    Concurrency contract (no GUARDED_BY table — every field is
    effectively immutable after the maintainer publishes the lane):
    ``idx``/``sock``/``send_lock`` are assigned once at construction;
    ``reader`` is bound exactly once by the maintainer thread before the
    _Conn is stored into ``_conns[idx]`` under ``_clock``, and that
    publication is the happens-before edge every other thread reads
    through. Frame WRITES on ``sock`` serialize under ``send_lock``;
    frame READS belong to the single reader thread alone."""

    def __init__(self, shard_id: int, idx: int, sock: socket.socket):
        self.idx = idx
        self.sock = sock
        self.send_lock = make_lock(f"shard.tcp.send.{shard_id}.{idx}")
        self.reader: Optional[threading.Thread] = None

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


@guard_attrs
class TcpShardClient:
    """Front-side handle for one shard over TCP — the cross-host fleet
    transport. Same surface as :class:`ShardClient`, plus:

    - a small **connection pool**: lane 0 is the primary (carries the
      ordered ``evt`` stream and subscribes to the shard's ``push``
      stream via a ``sub`` frame); extra lanes are parallel RPC lanes so
      a slow scatter call cannot head-of-line-block its neighbors.
    - a **reconnector** with jittered-exponential backoff (the PR 1
      ``Backoff``): connection loss does NOT kill the handle. While the
      primary lane is down the client reports ``alive=False`` — the
      front degrades to fail-safe verdicts, exactly like a dead child —
      and on re-establishment it fires ``on_up`` so the supervisor runs
      the PR 9 resync (which first bumps the fencing epoch).
    - **per-op deadlines** (``deadline_for``) and **epoch stamping** on
      every outgoing frame; stale ``push`` frames from a
      healed-but-not-yet-resynced worker are dropped, stale-epoch RPC
      refusals surface as :class:`FencedError`.

    The bounded send queue keeps the PR 1 watch-queue discipline: store
    dispatch NEVER blocks on the network — overflow sheds pod upserts
    (verdict-safe) and marks the shard dirty for the next resync.
    """

    transport = "tcp"
    MAX_QUEUE = 65536
    EVT_BATCH = 512

    GUARDED_BY = {
        "_queue": "self._qlock",
        "_pending": "self._plock",
        "_rid": "self._plock",
        "_rr": "self._plock",
        "deadline_exceeded": "self._plock",
        "dropped": "self._qlock",
        "dirty": "self._qlock",
        "_conns": "self._clock",
        "reconnects": "self._clock",
        "partition_seconds": "self._clock",
        "_down_since": "self._clock",
        # state-machine flags of the reconnector: every WRITE happens
        # under _ccond (the Condition over _clock — holding either
        # satisfies the guard). _alive is deliberately read lock-free by
        # the alive property (waived): a stale read degrades exactly one
        # admission to the fail-safe verdict, which is the transport's
        # contract for a down shard anyway.
        "_ever_up": "self._clock",
        "_alive": "self._clock",
    }
    # NOT guarded by design: events_sent/frames_sent (sender-thread
    # single-writer), fenced_pushes (reader-thread single-writer), epoch
    # (supervisor single-writer, stamped racily onto outgoing frames —
    # a frame stamped one bump early is refused and retried post-resync).

    def __init__(
        self,
        shard_id: int,
        host: str,
        port: int,
        on_push: Optional[Callable[[int, list], None]] = None,
        on_down: Optional[Callable[[int], None]] = None,
        on_up: Optional[Callable[[int], None]] = None,
        faults=None,
        maxsize: Optional[int] = None,
        pool_size: int = 2,
        default_deadline: float = 30.0,
        deadlines: Optional[Dict[str, float]] = None,
        connect_timeout: float = 5.0,
        backoff_base: float = 0.05,
        backoff_cap: float = 2.0,
        auth_key: AuthKey = None,
    ):
        from ..client.transport import Backoff  # PR 1 jittered exponential

        self.shard_id = shard_id
        self.host = host
        self.port = int(port)
        self.on_push = on_push
        self.on_down = on_down
        self.on_up = on_up
        self.faults = faults
        # cross-host frame auth (HMAC per frame, see module docstring);
        # None = unauthenticated — loopback/test rigs only
        self.auth_key = _as_key_bytes(auth_key)
        self.maxsize = maxsize or self.MAX_QUEUE
        self.pool_size = max(1, int(pool_size))
        self.default_deadline = float(default_deadline)
        self.deadlines: Dict[str, float] = dict(deadlines or {})
        self.connect_timeout = connect_timeout
        # the fencing epoch this front believes the shard is at.
        # Single-writer: only bump_epoch (the resync path) advances it;
        # sender/request threads read the int (atomic in CPython)
        self.epoch = 1
        self._backoff = Backoff(base=backoff_base, cap=backoff_cap)
        self._qlock = make_lock(f"shard.tcp.queue.{shard_id}")
        self._qcond = threading.Condition(self._qlock)
        self._queue: "deque[Op]" = deque()
        self._plock = make_lock(f"shard.tcp.pending.{shard_id}")
        self._pending = {}  # rid -> [threading.Event, response|None, conn]
        self._rid = 0
        self._rr = 0  # round-robin cursor over live RPC lanes
        self._clock = make_lock(f"shard.tcp.conns.{shard_id}")
        self._ccond = threading.Condition(self._clock)
        self._conns: List[Optional[_Conn]] = [None] * self.pool_size
        self._alive = False  # primary lane state; flips in _set_primary
        self._ever_up = False
        self._closed = False
        # single-writer stats; read by metrics at scrape
        self.events_sent = 0
        self.frames_sent = 0
        self.dropped = 0
        self.dirty = False
        self.deadline_exceeded = 0
        self.reconnects = 0  # primary-lane re-establishments after a drop
        self.partition_seconds = 0.0  # cumulative primary-lane downtime
        self.fenced_pushes = 0  # stale-epoch pushes dropped (reader thread)
        # handshake outcome (reader-thread single-writer, like
        # fenced_pushes): None until the worker's hello lands = the
        # zero-capability 1.0 baseline. version_refused holds the
        # worker's typed refusal while the majors disagree — the
        # reconnector slows to the backoff CAP and request() fails fast
        # with VersionMismatch instead of burning its deadline.
        self.negotiated_proto: Optional[Tuple[int, int]] = None
        self.negotiated_caps: Optional[frozenset] = None
        self.peer_build: Optional[str] = None
        self.version_refused: Optional[str] = None
        self.version_mismatches = 0
        self._refusal_delay = max(1.0, float(backoff_cap))
        self._down_since: Optional[float] = time.monotonic()
        self._sender = threading.Thread(
            target=self._send_loop, name=f"shard{shard_id}-tcp-send", daemon=True
        )
        self._maintainer = threading.Thread(
            target=self._maintain_loop, name=f"shard{shard_id}-tcp-conn", daemon=True
        )
        self._sender.start()
        self._maintainer.start()

    # ------------------------------------------------------------ connection

    def _open_conn(self, idx: int) -> _Conn:
        """Dial one lane (NOT under any lock — connect blocks). Raises
        OSError on failure; installs + returns the live conn."""
        if self.faults is not None:
            fault = self.faults.check("net.connect.refused")
            if fault is not None:
                raise ConnectionRefusedError(
                    f"injected connect refusal (hit {fault.hit})"
                )
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout
        )
        try:
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(self.shard_id, idx, sock)
            if idx == 0:
                # nominate this lane as the push stream (and teach the
                # worker our current epoch before any RPC rides it).
                # The body is our HELLO: the worker answers with the
                # negotiated version/caps (or a VersionMismatch refusal)
                # on a "hello" frame. Faults apply here too: under
                # net.partition the sub frame blackholes like any other
                # send, so a partitioned client stays DOWN in backoff
                # instead of flapping up-then-down once per establishment
                send_frame(sock, conn.send_lock, "sub", 0, local_hello(),
                           epoch=self.epoch, faults=self.faults,
                           key=self.auth_key)
            if self.faults is not None:
                fault = self.faults.check("net.reconnect.storm")
                if fault is not None:
                    raise OSError(
                        f"injected reconnect storm (hit {fault.hit}): "
                        "fresh connection killed"
                    )
        except BaseException:
            sock.close()
            raise
        reader = threading.Thread(
            target=self._read_conn, args=(conn,),
            name=f"shard{self.shard_id}-tcp-read{idx}", daemon=True,
        )
        conn.reader = reader
        with self._ccond:
            self._conns[idx] = conn
        reader.start()
        return conn

    def _maintain_loop(self) -> None:
        # top-level routing (threads checker): the reconnector IS the
        # heal path — if it died, a transient partition would be
        # permanent while the front reports degraded forever
        try:
            while True:
                with self._ccond:
                    if self._closed:
                        return
                    missing = [
                        i for i in range(self.pool_size) if self._conns[i] is None
                    ]
                    if not missing:
                        self._ccond.wait(0.2)
                        continue
                primary_was_down = 0 in missing
                opened_primary = False
                failed = False
                for idx in missing:
                    if self._closed:
                        return
                    try:
                        self._open_conn(idx)
                        if idx == 0:
                            opened_primary = True
                    except OSError:
                        failed = True
                if opened_primary and primary_was_down:
                    self._backoff.reset()
                    self._set_primary_up()
                if failed and not self._closed:
                    delay = self._backoff.next()
                    with self._ccond:
                        if not self._closed:
                            self._ccond.wait(delay)
                elif self.version_refused is not None and not self._closed:
                    # the worker refused our major and dropped the lane:
                    # redialing faster cannot help (an operator upgrades
                    # one side), so pace at the cap — degraded, counted,
                    # never a crash loop
                    with self._ccond:
                        if not self._closed:
                            self._ccond.wait(self._refusal_delay)
        except Exception:  # noqa: BLE001 — route the death, don't hide it
            logger.exception("shard %d: tcp reconnector died", self.shard_id)

    def _set_primary_up(self) -> None:
        reconnected = False
        with self._ccond:
            if self._ever_up:
                self.reconnects += 1
                reconnected = True
            if self._down_since is not None:
                self.partition_seconds += time.monotonic() - self._down_since
                self._down_since = None
            self._ever_up = True
            self._alive = True
            self._ccond.notify_all()
        with self._qcond:
            self._qcond.notify_all()  # sender: the evt lane is back
        logger.info(
            "shard %d: tcp primary lane %s (%s:%d)",
            self.shard_id, "reconnected" if reconnected else "connected",
            self.host, self.port,
        )
        if reconnected and self.on_up is not None:
            # the supervisor's heal path: bump the fencing epoch, then
            # resync (replay + prune + re-push — no lost flips)
            self.on_up(self.shard_id)

    def _conn_dead(self, conn: _Conn) -> None:
        """Tear down one lane; lane 0 dying marks the shard down."""
        conn.close()
        with self._ccond:
            if self._conns[conn.idx] is not conn:
                return  # already replaced
            self._conns[conn.idx] = None
            primary = conn.idx == 0
            if primary:
                was = self._alive
                self._alive = False
                if self._down_since is None:
                    self._down_since = time.monotonic()
            self._ccond.notify_all()
        # fail only the RPCs that were in flight on THIS lane
        stale = []
        with self._plock:
            for rid, slot in list(self._pending.items()):
                if slot[2] is conn:
                    stale.append(self._pending.pop(rid))
        for slot in stale:
            slot[0].set()
        if primary:
            with self._qcond:
                self.dirty = True
                self._qcond.notify_all()
            if was and not self._closed and self.on_down is not None:
                self.on_down(self.shard_id)

    def _primary(self) -> Optional[_Conn]:
        with self._ccond:
            return self._conns[0]

    def _pick_conn(self) -> Optional[_Conn]:
        with self._ccond:
            live = [c for c in self._conns if c is not None]
        if not live:
            return None
        with self._plock:
            self._rr += 1
            return live[self._rr % len(live)]

    # ------------------------------------------------------------- events

    def enqueue_ops(self, ops: Sequence[Op]) -> None:
        """Queue ops for the shard; never blocks — store dispatch must
        not wait on the network (verdict-safe shed on overflow)."""
        with self._qcond:
            if self._closed:
                return
            for op in ops:
                if len(self._queue) >= self.maxsize:
                    idx = next(
                        (i for i, q in enumerate(self._queue) if _sheddable(q)),
                        None,
                    )
                    if idx is not None:
                        del self._queue[idx]
                        self.dropped += 1
                        self.dirty = True
                    elif _sheddable(op):
                        self.dropped += 1
                        self.dirty = True
                        continue
                self._queue.append(op)
            self._qcond.notify()

    def _send_loop(self) -> None:
        # top-level routing (threads checker): sender death = down shard.
        # Unlike ShardClient this handle SURVIVES link loss, so an
        # unexpected sender error cannot just log-and-exit — events would
        # queue/shed forever behind a dead thread while health read
        # merely "degraded" and even a resync would re-enqueue into the
        # same dead queue. Tear down the primary lane instead (on_down
        # fires, the front degrades fail-safe, the reconnect's resync
        # repairs the gap) and keep the sender alive.
        while True:
            try:
                self._drain_until_closed()
                return  # clean exit: closed and drained
            except Exception:  # noqa: BLE001 — route the death, don't hide it
                logger.exception("shard %d: tcp sender error", self.shard_id)
                with self._qcond:
                    if self._closed:
                        return
                    self.dirty = True
                conn = self._primary()
                if conn is not None:
                    self._conn_dead(conn)
                time.sleep(0.05)  # a persistent bug must not spin-degrade

    def _drain_until_closed(self) -> None:
        while True:
            with self._qcond:
                while not self._queue and not self._closed:
                    self._qcond.wait(0.2)
                if self._closed and not self._queue:
                    return
            conn = self._primary()
            if conn is None:
                if self._closed:
                    return
                # partitioned: hold the (bounded) queue; the shed +
                # dirty + resync-on-heal path repairs any overflow
                with self._ccond:
                    if self._conns[0] is None and not self._closed:
                        self._ccond.wait(0.2)
                continue
            with self._qcond:
                batch = [
                    self._queue.popleft()
                    for _ in range(min(len(self._queue), self.EVT_BATCH))
                ]
            if not batch:
                continue
            try:
                if self.faults is not None:
                    fault = self.faults.check("shard.ipc.send")
                    if fault is not None:
                        raise OSError(
                            f"injected IPC send failure (hit {fault.hit})"
                        )
                body = (
                    encode_evt_batch(batch)
                    if self.has_cap("evt-columnar")
                    else batch
                )
                send_frame(conn.sock, conn.send_lock, "evt", 0, body,
                           epoch=self.epoch, faults=self.faults,
                           key=self.auth_key)
                self.events_sent += len(batch)
                self.frames_sent += 1
            except OSError:
                # link gone mid-send: these events are lost — the
                # reconnect's resync (replay + prune) repairs the gap
                with self._qcond:
                    self.dropped += len(batch)
                    self.dirty = True
                self._conn_dead(conn)

    # ---------------------------------------------------------------- RPC

    def deadline_for(self, op: str) -> float:
        return self.deadlines.get(op, self.default_deadline)

    def request(self, op: str, payload=None, timeout: Optional[float] = None):
        """Blocking RPC with a per-op deadline; raises
        :class:`ShardUnavailable` when the link is down or the deadline
        passes, :class:`FencedError` on a stale-epoch refusal."""
        if timeout is None:
            timeout = self.deadline_for(op)
        refused = self.version_refused
        if refused is not None:
            raise VersionMismatch(
                f"shard {self.shard_id} refused the handshake: {refused}"
            )
        if not self.alive:
            raise ShardUnavailable(
                f"shard {self.shard_id} is unreachable ({self.host}:{self.port})"
            )
        conn = self._pick_conn()
        if conn is None:
            raise ShardUnavailable(
                f"shard {self.shard_id} has no live connection"
            )
        with self._plock:
            self._rid += 1
            rid = self._rid
            slot = [threading.Event(), None, conn]
            self._pending[rid] = slot
        try:
            send_frame(conn.sock, conn.send_lock, "req", rid, (op, payload),
                       epoch=self.epoch, faults=self.faults,
                       key=self.auth_key)
        except OSError:
            with self._plock:
                self._pending.pop(rid, None)
            self._conn_dead(conn)
            raise ShardUnavailable(
                f"shard {self.shard_id} send failed ({self.host}:{self.port})"
            ) from None
        if not slot[0].wait(timeout):
            with self._plock:
                self._pending.pop(rid, None)
                self.deadline_exceeded += 1
            raise ShardUnavailable(
                f"shard {self.shard_id} did not answer {op} within {timeout}s"
            )
        if slot[1] is None:
            raise ShardUnavailable(
                f"shard {self.shard_id} connection died during {op}"
            )
        ok, body = slot[1]
        if not ok:
            _raise_shard_error(self.shard_id, op, body)
        return body

    def _read_conn(self, conn: _Conn) -> None:
        rfile = conn.sock.makefile("rb")
        try:
            while True:
                frame = read_frame(rfile, self.faults, key=self.auth_key)
                if frame is None:
                    break
                mtype, rid, body, epoch = frame
                if mtype == "res":
                    with self._plock:
                        slot = self._pending.pop(rid, None)
                    if slot is not None:
                        slot[1] = body
                        slot[0].set()
                elif mtype == "push":
                    if epoch < self.epoch:
                        # a healed worker replaying its pre-partition view:
                        # fenced — the resync re-push will carry the truth
                        self.fenced_pushes += 1
                    elif self.on_push is not None:
                        self.on_push(self.shard_id, body)
                elif mtype == "hello":
                    _apply_hello(self, body)
        except (OSError, pickle.UnpicklingError, EOFError):
            pass
        except Exception:  # noqa: BLE001 — route the death, don't hide it
            logger.exception("shard %d: tcp reader died", self.shard_id)
        finally:
            try:
                rfile.close()
            except OSError:
                pass
            self._conn_dead(conn)

    # ----------------------------------------------------------- lifecycle

    @property
    def alive(self) -> bool:
        return self._alive and not self._closed

    def has_cap(self, name: str) -> bool:
        """True iff the handshake negotiated this minor capability.
        False before the worker's hello lands — pre-handshake traffic
        uses the v1 baseline encodings by construction."""
        caps = self.negotiated_caps
        return caps is not None and name in caps

    def bump_epoch(self) -> int:
        """Advance the fencing epoch (resync head): frames stamped with
        the previous epoch — from a partitioned peer or a stale kernel
        buffer — are refused from here on."""
        self.epoch += 1
        return self.epoch

    def is_dirty(self) -> bool:
        with self._qcond:
            return self.dirty

    def mark_dirty(self) -> None:
        with self._qcond:
            self.dirty = True

    def clear_dirty(self) -> None:
        with self._qcond:
            self.dirty = False

    def pending_events(self) -> int:
        with self._qcond:
            return len(self._queue)

    def outage_seconds(self) -> float:
        """Cumulative primary-lane downtime, including the current
        outage if one is in progress (the partition_seconds metric)."""
        with self._ccond:
            total = self.partition_seconds
            if self._down_since is not None:
                total += time.monotonic() - self._down_since
            return total

    def close(self) -> None:
        self._closed = True
        with self._qcond:
            self._qcond.notify_all()
        with self._ccond:
            conns = [c for c in self._conns if c is not None]
            self._conns = [None] * self.pool_size
            self._alive = False
            self._ccond.notify_all()
        for conn in conns:
            conn.close()
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        for slot in pending:
            slot[0].set()
        self._maintainer.join(timeout=2.0)
        self._sender.join(timeout=2.0)


class LocalShard:
    """In-process shard handle for deterministic tests: wraps a
    :class:`worker.ShardCore` directly — same surface as
    :class:`ShardClient`, no sockets, events applied synchronously."""

    transport = "local"

    def __init__(self, shard_id: int, core, on_push=None):
        self.shard_id = shard_id
        self.core = core
        self.events_sent = 0
        self.frames_sent = 0
        self.dropped = 0
        self.dirty = False
        self.alive = True
        self.epoch = 0
        self.deadline_exceeded = 0
        self.reconnects = 0
        # in-process "handshake": trivially the local build's identity
        self.negotiated_proto = PROTO_VERSION
        self.negotiated_caps = CAPABILITIES
        self.peer_build = None
        self.version_refused = None
        self.version_mismatches = 0
        if on_push is not None:
            core.push = lambda items: on_push(shard_id, items)

    def enqueue_ops(self, ops: Sequence[Op]) -> None:
        if not self.alive:
            self.dropped += len(ops)
            self.dirty = True
            return
        self.core.handle_events([unwrap_op(op) for op in ops])
        self.events_sent += len(ops)
        self.frames_sent += 1

    def is_dirty(self) -> bool:
        return self.dirty  # synchronous single-thread handle: no lock

    def pending_events(self) -> int:
        return 0

    def mark_dirty(self) -> None:
        self.dirty = True

    def clear_dirty(self) -> None:
        self.dirty = False

    def deadline_for(self, op: str) -> float:
        return 30.0

    def has_cap(self, name: str) -> bool:
        return name in self.negotiated_caps

    def request(self, op: str, payload=None, timeout: Optional[float] = None):
        if not self.alive:
            raise ShardUnavailable(f"shard {self.shard_id} is down")
        ok, body = self.core.rpc(op, payload)
        if not ok:
            _raise_shard_error(self.shard_id, op, body)
        return body

    def close(self) -> None:
        self.alive = False


__all__ = [
    "Op",
    "ShardClient",
    "TcpShardClient",
    "ShardUnavailable",
    "FencedError",
    "VersionMismatch",
    "LocalShard",
    "send_frame",
    "read_frame",
    "encode_evt_batch",
    "decode_evt_batch",
    "PrepickledPayload",
    "unwrap_op",
    "PICKLE_PROTO",
]
