"""Consistent-hash ring over the Throttle/ClusterThrottle keyspace.

Two properties matter for the scatter-gather front:

- **Stability**: ownership must be a pure function of (route key,
  shard count) — identical across processes and runs, so the front, the
  workers, and a restarted supervisor all agree without coordination.
  Python's builtin ``hash`` is salted per process; we hash with
  blake2b instead.
- **Selector affinity**: the per-event cost the sharding exists to
  divide is proportional to the number of *matching* throttles on each
  shard. Throttles with byte-identical selectors match exactly the same
  pods, so hashing the ROUTE KEY of a throttle as its canonical
  selector fingerprint (instead of its object key) co-locates them —
  a pod event then lands on the few shards owning its selector classes
  rather than on every shard that drew one of its 20 throttles. The
  partition is still a consistent hash of the keyspace: the fingerprint
  is a deterministic function of the stored object, and ownership by
  object key is recorded by the front (``AdmissionFront.owner_of``).

Virtual nodes smooth the partition (~128 points per shard keeps the
max/mean shard load under ~1.2 for uniform keys).
"""

from __future__ import annotations

import bisect
import hashlib
import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple, Union

from ..api.types import ClusterThrottle, Throttle

__all__ = [
    "stable_hash64",
    "selector_fingerprint",
    "route_key_for",
    "HashRing",
    "RangeMove",
    "ReshardPlan",
    "plan_reshard",
    "TransitionRouting",
]


def stable_hash64(key: str) -> int:
    """Process-stable 64-bit hash (blake2b; builtin hash is salted)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


def _selector_term_dict(term, cluster: bool) -> dict:
    from ..api.serialization import label_selector_to_dict

    out = {"podSelector": label_selector_to_dict(term.pod_selector)}
    if cluster:
        out["namespaceSelector"] = label_selector_to_dict(term.namespace_selector)
    return out


def selector_fingerprint(thr: Union[Throttle, ClusterThrottle]) -> str:
    """Canonical, order-stable serialization of a throttle's selector.

    Throttles additionally fold in their namespace (a Throttle only ever
    matches pods of its own namespace, so same-selector throttles in
    different namespaces share no matching work and need not co-locate).
    """
    cluster = isinstance(thr, ClusterThrottle)
    terms = [
        _selector_term_dict(t, cluster) for t in thr.spec.selector.selector_terms
    ]
    scope = "c" if cluster else f"t:{thr.namespace}"
    return f"{scope}|" + json.dumps(terms, sort_keys=True, separators=(",", ":"))


def route_key_for(kind: str, obj) -> str:
    """The ring key an object shards by.

    - Throttle / ClusterThrottle: selector fingerprint (affinity above);
    - gang groups (``kind="Gang"``, obj = group key string): the group
      id — a gang's ledger lives on exactly one shard;
    - anything else keyed by a plain string: that string.
    """
    if kind in ("Throttle", "ClusterThrottle"):
        return selector_fingerprint(obj)
    if kind == "Gang":
        return f"gang|{obj}"
    return f"{kind}|{obj}"


class HashRing:
    """Immutable consistent-hash ring: ``shard_of(route_key) -> shard id``.

    Analyzer note (PR 10): every field is written once in ``__init__``
    and only read afterwards — immutability IS the concurrency
    discipline here, so there is deliberately no ``GUARDED_BY`` table
    and no lock. Do not add mutating methods; rebuild a new ring for a
    new shard count (shard-count rebalancing is restart + resync by
    design, see ROADMAP item 1)."""

    def __init__(self, n_shards: int, vnodes: int = 128):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = int(n_shards)
        self.vnodes = int(vnodes)
        points: List[Tuple[int, int]] = []
        for shard in range(self.n_shards):
            for v in range(self.vnodes):
                points.append((stable_hash64(f"shard-{shard}-vnode-{v}"), shard))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._shards = [s for _, s in points]

    def shard_of(self, route_key: str) -> int:
        if self.n_shards == 1:
            return 0
        h = stable_hash64(route_key)
        i = bisect.bisect_right(self._hashes, h)
        if i == len(self._hashes):
            i = 0
        return self._shards[i]

    def shard_of_object(self, kind: str, obj) -> int:
        return self.shard_of(route_key_for(kind, obj))

    def spread(self, keys) -> List[int]:
        """Shard load histogram for a key sample (diagnostics/tests)."""
        counts = [0] * self.n_shards
        for k in keys:
            counts[self.shard_of(k)] += 1
        return counts

    def owner_of_hash(self, h: int) -> int:
        """Shard owning a raw 64-bit ring position (resharding plumbing:
        the plan and the transition router reason in hash space, not key
        space, so a range statement covers keys that do not exist yet)."""
        if self.n_shards == 1:
            return 0
        i = bisect.bisect_right(self._hashes, int(h))
        if i == len(self._hashes):
            i = 0
        return self._shards[i]

    def boundaries(self) -> List[int]:
        """The sorted vnode positions (plan_reshard merges old+new)."""
        return list(self._hashes)


# --------------------------------------------------------------------------
# live resharding: retarget plans + the dual-ring transition router
# --------------------------------------------------------------------------

_HASH_SPACE = 1 << 64


@dataclass(frozen=True)
class RangeMove:
    """One moving keyspace range: the half-open hash interval
    ``[lo, hi)`` whose owner changes from ``src`` to ``dst`` when the
    ring retargets. ``hi == 2**64`` closes the top of the circle (the
    wrap segment is split at 0 so every move is a plain interval)."""

    index: int  # position in the plan (the coordinator's range id)
    lo: int
    hi: int
    src: int
    dst: int

    def covers(self, h: int) -> bool:
        return self.lo <= h < self.hi


@dataclass(frozen=True)
class ReshardPlan:
    """The minimal transfer set between two rings: only intervals whose
    owner differs appear (a key outside every move never transfers), and
    the plan is a pure function of the two ring parameter tuples — any
    two processes that agree on (n_old, n_new, vnodes) agree on the plan
    byte for byte (tests/test_reshard.py pins this)."""

    old_shards: int
    new_shards: int
    moves: Tuple[RangeMove, ...]

    def move_for_hash(self, h: int) -> Optional[RangeMove]:
        lows = [m.lo for m in self.moves]
        i = bisect.bisect_right(lows, h) - 1
        if i >= 0 and self.moves[i].covers(h):
            return self.moves[i]
        return None

    def moves_from(self, src: int) -> List[RangeMove]:
        return [m for m in self.moves if m.src == src]


def plan_reshard(old: HashRing, new: HashRing) -> ReshardPlan:
    """Compute the split/merge plan between two rings. Walk the merged
    boundary set: between consecutive boundaries ownership is constant
    under BOTH rings, so each elementary interval is wholly moving or
    wholly staying; adjacent moving intervals with the same (src, dst)
    coalesce into one :class:`RangeMove`."""
    cuts = sorted(set(old.boundaries()) | set(new.boundaries()) | {0, _HASH_SPACE})
    raw: List[Tuple[int, int, int, int]] = []  # (lo, hi, src, dst)
    for lo, hi in zip(cuts, cuts[1:]):
        if lo >= hi:
            continue
        src = old.owner_of_hash(lo)
        dst = new.owner_of_hash(lo)
        if src == dst:
            continue
        if raw and raw[-1][1] == lo and raw[-1][2] == src and raw[-1][3] == dst:
            raw[-1] = (raw[-1][0], hi, src, dst)
        else:
            raw.append((lo, hi, src, dst))
    moves = tuple(
        RangeMove(index=i, lo=lo, hi=hi, src=src, dst=dst)
        for i, (lo, hi, src, dst) in enumerate(raw)
    )
    return ReshardPlan(
        old_shards=old.n_shards, new_shards=new.n_shards, moves=moves
    )


class TransitionRouting:
    """Dual-ring routing during a live reshard: every key has exactly ONE
    authoritative owner at every instant (the zero-owner-never invariant
    the retarget tests sweep) — the old ring's owner until the covering
    range cuts over, the new ring's after. ``mirror_of`` names the
    destination while its range is warming (streaming + double-routing),
    so the front can mirror events without consulting the destination's
    verdicts.

    State transitions per range: ``pending`` → ``mirroring`` → ``cut``
    (success) or back to ``pending`` (abort-back-to-source). Mutation
    happens only under the front's route lock; readers race-free snapshot
    via the plain dict (CPython dict reads are atomic; a torn read is at
    worst one-event-late routing, repaired by the cutover's fence)."""

    PENDING = "pending"
    MIRRORING = "mirroring"
    CUT = "cut"

    def __init__(self, old_ring: HashRing, new_ring: HashRing,
                 plan: Optional[ReshardPlan] = None):
        self.old_ring = old_ring
        self.new_ring = new_ring
        self.plan = plan if plan is not None else plan_reshard(old_ring, new_ring)
        self.state: Dict[int, str] = {m.index: self.PENDING for m in self.plan.moves}

    def set_state(self, index: int, state: str) -> None:
        self.state[index] = state

    def owner_of_hash(self, h: int) -> int:
        move = self.plan.move_for_hash(h)
        if move is None:
            return self.new_ring.owner_of_hash(h)  # == old owner by plan
        return move.dst if self.state.get(move.index) == self.CUT else move.src

    def mirror_of_hash(self, h: int) -> Optional[RangeMove]:
        move = self.plan.move_for_hash(h)
        if move is not None and self.state.get(move.index) == self.MIRRORING:
            return move
        return None

    def owner_of(self, route_key: str) -> int:
        return self.owner_of_hash(stable_hash64(route_key))

    def complete(self) -> bool:
        return all(s == self.CUT for s in self.state.values())
