"""Consistent-hash ring over the Throttle/ClusterThrottle keyspace.

Two properties matter for the scatter-gather front:

- **Stability**: ownership must be a pure function of (route key,
  shard count) — identical across processes and runs, so the front, the
  workers, and a restarted supervisor all agree without coordination.
  Python's builtin ``hash`` is salted per process; we hash with
  blake2b instead.
- **Selector affinity**: the per-event cost the sharding exists to
  divide is proportional to the number of *matching* throttles on each
  shard. Throttles with byte-identical selectors match exactly the same
  pods, so hashing the ROUTE KEY of a throttle as its canonical
  selector fingerprint (instead of its object key) co-locates them —
  a pod event then lands on the few shards owning its selector classes
  rather than on every shard that drew one of its 20 throttles. The
  partition is still a consistent hash of the keyspace: the fingerprint
  is a deterministic function of the stored object, and ownership by
  object key is recorded by the front (``AdmissionFront.owner_of``).

Virtual nodes smooth the partition (~128 points per shard keeps the
max/mean shard load under ~1.2 for uniform keys).
"""

from __future__ import annotations

import bisect
import hashlib
import json
from typing import List, Tuple, Union

from ..api.types import ClusterThrottle, Throttle

__all__ = ["stable_hash64", "selector_fingerprint", "route_key_for", "HashRing"]


def stable_hash64(key: str) -> int:
    """Process-stable 64-bit hash (blake2b; builtin hash is salted)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


def _selector_term_dict(term, cluster: bool) -> dict:
    from ..api.serialization import label_selector_to_dict

    out = {"podSelector": label_selector_to_dict(term.pod_selector)}
    if cluster:
        out["namespaceSelector"] = label_selector_to_dict(term.namespace_selector)
    return out


def selector_fingerprint(thr: Union[Throttle, ClusterThrottle]) -> str:
    """Canonical, order-stable serialization of a throttle's selector.

    Throttles additionally fold in their namespace (a Throttle only ever
    matches pods of its own namespace, so same-selector throttles in
    different namespaces share no matching work and need not co-locate).
    """
    cluster = isinstance(thr, ClusterThrottle)
    terms = [
        _selector_term_dict(t, cluster) for t in thr.spec.selector.selector_terms
    ]
    scope = "c" if cluster else f"t:{thr.namespace}"
    return f"{scope}|" + json.dumps(terms, sort_keys=True, separators=(",", ":"))


def route_key_for(kind: str, obj) -> str:
    """The ring key an object shards by.

    - Throttle / ClusterThrottle: selector fingerprint (affinity above);
    - gang groups (``kind="Gang"``, obj = group key string): the group
      id — a gang's ledger lives on exactly one shard;
    - anything else keyed by a plain string: that string.
    """
    if kind in ("Throttle", "ClusterThrottle"):
        return selector_fingerprint(obj)
    if kind == "Gang":
        return f"gang|{obj}"
    return f"{kind}|{obj}"


class HashRing:
    """Immutable consistent-hash ring: ``shard_of(route_key) -> shard id``.

    Analyzer note (PR 10): every field is written once in ``__init__``
    and only read afterwards — immutability IS the concurrency
    discipline here, so there is deliberately no ``GUARDED_BY`` table
    and no lock. Do not add mutating methods; rebuild a new ring for a
    new shard count (shard-count rebalancing is restart + resync by
    design, see ROADMAP item 1)."""

    def __init__(self, n_shards: int, vnodes: int = 128):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = int(n_shards)
        self.vnodes = int(vnodes)
        points: List[Tuple[int, int]] = []
        for shard in range(self.n_shards):
            for v in range(self.vnodes):
                points.append((stable_hash64(f"shard-{shard}-vnode-{v}"), shard))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._shards = [s for _, s in points]

    def shard_of(self, route_key: str) -> int:
        if self.n_shards == 1:
            return 0
        h = stable_hash64(route_key)
        i = bisect.bisect_right(self._hashes, h)
        if i == len(self._hashes):
            i = 0
        return self._shards[i]

    def shard_of_object(self, kind: str, obj) -> int:
        return self.shard_of(route_key_for(kind, obj))

    def spread(self, keys) -> List[int]:
        """Shard load histogram for a key sample (diagnostics/tests)."""
        counts = [0] * self.n_shards
        for k in keys:
            counts[self.shard_of(k)] += 1
        return counts
