"""Live elastic resharding: the fenced two-phase keyspace handoff.

Changing shard count used to be restart + full resync (ROADMAP 3b) —
the one operation a fleet serving heavy traffic cannot afford. This
coordinator retargets the consistent-hash ring LIVE, one moving range at
a time, with the overlap discipline of "keep serving from the old owner
while the new owner warms, cut over only at a fence":

1. **Prepare + stream.** The front turns double-routing ON for the range
   (``AdmissionFront.begin_range`` — every event for a covered key now
   applies at the source AND mirrors to the destination, and reserves
   fan out to both). The source then stages its slice — store objects,
   reservation-ledger entries, gang records, published statuses — and
   streams it in prefix-sha-verified chunks (the PR 6 StandbyReplicator
   chunk contract, re-pointed over the framed-pickle IPC; the
   coordinator relays source→destination because workers share no
   socket). Order matters: mirror-on happens BEFORE the prepare flush,
   so no event can fall between the snapshot and the mirror stream.

2. **Warm-up.** The destination applies the slice and keeps absorbing
   mirrored events; its controllers compute verdicts and flips, but its
   status pushes are SUPPRESSED (advisory) — the front consults only the
   authoritative owner for checks while the range is in flight.

3. **Fenced cutover, per range.** The source fences the range (the
   PR 6 ``FencingEpoch`` discipline, range-scoped: post-fence
   authoritative writes for the range are refused and counted), the
   front atomically re-points every covered key's owner under one
   route-lock hold, and the destination ``reshard_activate``s —
   re-enqueueing every moved key on the PRIORITY lane so every flip it
   computed during warm-up re-publishes flips-first through the
   two-lane path. Nothing the source never committed is lost. The
   source then retires its slice (fence lifted with it).

Failure is first-class (``reshard.*`` sites, faults/plan.py):

- ``reshard.handoff.torn`` — the chunk stream tears or corrupts; the
  sink's hash check refuses the chunk and the range aborts back to the
  source (authority never moved).
- ``reshard.dest.crash`` — the destination dies mid-warm-up; the
  coordinator aborts the range and retries once the supervisor's
  monitor restarts the worker.
- ``reshard.fence.race`` — the fence step loses a race (a concurrent
  epoch superseded the handoff); the source unfences and the range
  aborts.
- ``reshard.front.crash`` — the coordinator dies between prepare and
  cutover; NOBODY cleans up in-band, and the shard-side two-phase
  reapers TTL the orphaned handoff on both ends (source lifts its
  fence, destination drops the imported slice including every imported
  reservation) — zero orphan reservations by the same clock that reaps
  two-phase reserves.

A failure AFTER the cutover is NOT aborted: the destination owns the
range from that instant, so a destination death there is the ordinary
kill-a-shard case (supervisor restart + resync from the front's merged
store), and a failed source retire leaves an inert fenced zombie slice
that the source's handoff reaper unstages.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

from .ipc import ShardUnavailable
from .ring import HashRing, RangeMove, ReshardPlan, TransitionRouting, plan_reshard

logger = logging.getLogger(__name__)

__all__ = [
    "ReshardAborted",
    "ReshardTimeout",
    "CoordinatorCrash",
    "ReshardCoordinator",
]


class ReshardAborted(Exception):
    """One range's handoff aborted back to the source (retryable)."""


class ReshardTimeout(Exception):
    """The rescale deadline passed with ranges still pending. The
    transition router stays installed — routing remains correct (cut
    ranges serve from their destinations, pending ones from their
    sources) — but the fleet is not at its target shape."""


class CoordinatorCrash(Exception):
    """Simulated coordinator death (``reshard.front.crash`` in a mode
    other than ``kill``): propagates WITHOUT cleanup so tests can drive
    the shard-side TTL reapers against the orphaned handoff."""


class ReshardCoordinator:
    """Drives one ring retarget over an :class:`AdmissionFront`."""

    def __init__(self, front, faults=None, chunk_timeout: float = 30.0):
        self.front = front
        self.faults = faults if faults is not None else front.faults
        self.metrics = getattr(front, "reshard_metrics", None)
        self.chunk_timeout = chunk_timeout
        self._seq = 0
        # single-writer progress counters (stats/tests)
        self.handoffs_done = 0
        self.handoffs_aborted = 0
        self.bytes_streamed = 0
        self.events_streamed = 0

    # ------------------------------------------------------------ plumbing

    def _request(self, sid: int, op: str, payload, timeout: Optional[float] = None):
        handle = self.front.shards.get(sid)
        if handle is None or not handle.alive:
            raise ShardUnavailable(f"shard {sid} is down")
        return handle.request(op, payload, timeout=timeout or self.chunk_timeout)

    def _wait_queue_empty(self, sid: int, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            handle = self.front.shards.get(sid)
            if handle is None or not handle.alive:
                raise ShardUnavailable(f"shard {sid} went down mid-handoff")
            if handle.pending_events() == 0:
                return
            time.sleep(0.005)
        raise ShardUnavailable(
            f"shard {sid} event queue never drained in {timeout}s"
        )

    def _check_fault(self, site: str) -> None:
        if self.faults is None:
            return
        fault = self.faults.check(site)
        if fault is None:
            return
        fault.sleep()
        if fault.mode == "kill":
            fault.kill()
        if site == "reshard.front.crash":
            raise CoordinatorCrash(f"injected coordinator death (hit {fault.hit})")
        raise fault.make_error()

    # ------------------------------------------------------------- the work

    def rescale(
        self,
        new_ring: HashRing,
        deadline_s: float = 180.0,
        retry_backoff: float = 0.5,
    ) -> Dict:
        """Retarget the front's ring to ``new_ring``, range by range.
        Aborted ranges are retried until the deadline; the target ring is
        adopted only once EVERY range has cut over, so a partial failure
        never leaves a hybrid steady state."""
        old_ring = self.front.ring
        plan: ReshardPlan = plan_reshard(old_ring, new_ring)
        transition = TransitionRouting(old_ring, new_ring, plan)
        self.front.begin_reshard(transition)
        report: Dict = {
            "from_shards": old_ring.n_shards,
            "to_shards": new_ring.n_shards,
            "moves": len(plan.moves),
            "aborts": 0,
            "retries": 0,
            "keys_cut": 0,
            "bytes": 0,
            "events": 0,
        }
        if not plan.moves:
            self.front.finish_reshard(new_ring, new_ring.n_shards)
            return report
        # one handoff per (src, dst) pair: a retarget produces O(vnodes)
        # elementary moves, but the slice stream and the fence are
        # per-PAIR concerns — grouping turns ~100 streams into ≤ a few,
        # while the front still mirrors/cuts each range individually
        groups: Dict[Tuple[int, int], List[RangeMove]] = {}
        for move in plan.moves:
            groups.setdefault((move.src, move.dst), []).append(move)
        report["groups"] = len(groups)
        pending: List[Tuple[int, int]] = sorted(groups)
        deadline = time.monotonic() + deadline_s
        while pending:
            src, dst = pending.pop(0)
            moves = groups[(src, dst)]
            try:
                report["keys_cut"] += self._handoff_group(src, dst, moves)
                self.handoffs_done += 1
            except CoordinatorCrash:
                raise
            except Exception as e:  # noqa: BLE001 — abort + retry is the contract
                self.handoffs_aborted += 1
                report["aborts"] += 1
                logger.warning(
                    "reshard: handoff shard %d→%d (%d ranges) aborted back "
                    "to source: %s", src, dst, len(moves), e,
                )
                if self.metrics is not None:
                    self.metrics["aborts"].inc({})
                if time.monotonic() > deadline:
                    raise ReshardTimeout(
                        f"handoff {src}->{dst} still pending at deadline "
                        f"(last error: {e})"
                    ) from e
                report["retries"] += 1
                pending.append((src, dst))
                time.sleep(retry_backoff)
        report["bytes"] = self.bytes_streamed
        report["events"] = self.events_streamed
        self.front.finish_reshard(new_ring, new_ring.n_shards)
        logger.info(
            "reshard complete: %d→%d shards, %d ranges, %d keys re-pointed "
            "(%d aborts retried)",
            report["from_shards"], report["to_shards"], report["moves"],
            report["keys_cut"], report["aborts"],
        )
        return report

    def _handoff_group(self, src: int, dst: int,
                       moves: List[RangeMove]) -> int:
        """One (src, dst) handoff end to end — every moving range between
        the pair rides one slice stream and one fence. Pre-cutover
        failures abort back to the source (and raise); post-cutover
        failures are repaired through the ordinary shard-death machinery
        (see module docstring)."""
        self._seq += 1
        handoff = f"reshard-{self._seq}-s{src}d{dst}"
        ranges = [(m.lo, m.hi) for m in moves]
        cut = False
        try:
            # 1. mirror ON first — no event may fall between the staged
            # snapshot and the mirror stream
            for move in moves:
                self.front.begin_range(move)
            # the prepare RPC rides the req channel, which can overtake
            # evt frames still queued front-side: wait for the source's
            # queue to drain so every pre-mirror event is on the socket
            # AHEAD of the prepare (FIFO) and lands in the export —
            # everything after the drain is mirrored by construction
            self._wait_queue_empty(src, timeout=60.0)
            prep = self._request(
                src, "reshard_prepare",
                {"handoff": handoff, "ranges": ranges}, timeout=120.0,
            )
            # 2. relay the verified chunk stream source → destination
            offset, sha = 0, ""
            while True:
                chunk = self._request(
                    src, "reshard_chunk",
                    {"handoff": handoff, "offset": offset, "sha": sha},
                )
                res = self._request(
                    dst, "reshard_import",
                    {"handoff": handoff, "ranges": ranges, "chunk": chunk},
                    timeout=120.0,
                )
                self.bytes_streamed += len(chunk["data"])
                if self.metrics is not None:
                    self.metrics["bytes"].inc({}, float(len(chunk["data"])))
                offset, sha = chunk["endOffset"], chunk["endSha"]
                if res.get("done"):
                    self.events_streamed += int(res.get("objects", 0))
                    if self.metrics is not None:
                        self.metrics["events"].inc(
                            {}, float(res.get("objects", 0))
                        )
                    break
            # 3. fenced cutover
            self._check_fault("reshard.front.crash")
            t_fence = time.monotonic()
            self._request(
                src, "reshard_fence",
                {"handoff": handoff, "ranges": ranges, "epoch": self._seq},
            )
            self._check_fault("reshard.fence.race")
            keys_cut = 0
            for move in moves:
                keys_cut += self.front.cutover_range(move)
            cut = True
            self._request(dst, "reshard_activate", {"handoff": handoff})
            self._request(src, "reshard_retire", {"handoff": handoff})
            if self.metrics is not None:
                self.metrics["cutover"].observe({}, time.monotonic() - t_fence)
            logger.info(
                "reshard: handoff shard %d→%d cut over (%d ranges, %d keys, "
                "%d slice bytes)", src, dst, len(moves), keys_cut,
                int(prep.get("bytes", 0)),
            )
            return keys_cut
        except CoordinatorCrash:
            raise  # no cleanup — the shard-side TTL reapers own this path
        except Exception:
            if cut:
                # the destination owns the ranges now: repair forward, not
                # backward (restart+resync is the shard-death machinery)
                logger.exception(
                    "reshard: post-cutover step failed for handoff %d→%d — "
                    "relying on supervisor restart+resync", src, dst,
                )
                self._post_cutover_repair(src, dst, handoff)
                return 0
            self._abort_group(src, dst, moves, handoff)
            raise

    def _abort_group(self, src: int, dst: int, moves: List[RangeMove],
                     handoff: str) -> None:
        """Pre-cutover abort: stop mirroring FIRST (no new mirrored event
        may trail the destination's cleanup), flush what is in flight,
        then roll both sides back. Every step is best-effort — a side
        that cannot answer will TTL-reap the handoff itself."""
        for move in moves:
            self.front.abort_range(move)
        for sid, drain in ((dst, True), (src, False)):
            try:
                if drain:
                    self._request(sid, "drain", {"timeout": 5.0}, timeout=30.0)
                self._request(sid, "reshard_abort", {"handoff": handoff})
            except Exception:  # noqa: BLE001 — reaper covers a dark side
                logger.warning(
                    "reshard: abort of %s on shard %d failed (TTL reaper "
                    "will finish it)", handoff, sid,
                )

    def _post_cutover_repair(self, src: int, dst: int, handoff: str) -> None:
        try:
            self._request(src, "reshard_retire", {"handoff": handoff})
        except Exception:  # noqa: BLE001 — the source reaper unstages it
            pass
        try:
            self.front.resync_shard(dst)
        except Exception:  # noqa: BLE001 — monitor-driven resync follows
            pass
