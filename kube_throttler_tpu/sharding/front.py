"""The thin admission front over N shared-nothing keyspace shards.

The front owns three things and no controllers:

1. **The merged object view** — a plain :class:`Store` holding every
   object. Specs/pods flow IN through it (any mutation is routed); the
   shards' controllers stream status writes BACK into it (flips first,
   from their two-lane pipelines), so the HTTP surface and the bench
   read one coherent view.
2. **The routing index** — a :class:`SelectorIndex` per kind, the same
   incremental match structure the shards run, used only to answer
   "which shards' throttles can this pod match". Ownership is the
   consistent-hash ring over selector-affinity route keys (ring.py);
   the front records the owner per throttle key.
3. **Scatter-gather admission** — ``pre_filter`` fans out to the
   matching shards and AND-merges shard-local verdicts: a pod may match
   throttles in several shards, and any-shard-throttled ⇒ unschedulable,
   so the merge needs no cross-shard transaction. Reservations DO span
   shards, so ``reserve`` is two-phase: prepare on every matching shard,
   commit/abort from the front; a prepared transaction orphaned by a
   front crash is reaped shard-side (worker.ShardCore.reap_stale_txns).
   Gang groups hash by group id — the group's authoritative ledger
   record lives on exactly one shard — while member reservations ride
   the same two-phase fan-out.

Routing rules (Router, a store batch listener):

- Namespace events broadcast to every shard (rare, verdict-critical);
- Throttle/ClusterThrottle SPEC changes route to the owner shard
  (status-only writes are the shards' own echoes and are not routed);
  an owner change (selector edit) migrates the object and replays its
  matching pods to the new owner;
- Pod events route to the union of shards owning a matching throttle —
  plus a DELETE to shards the pod just stopped mattering to, so no
  shard ever aggregates a stale pod. Pods matching nothing live only in
  the front's store.

Degraded mode: a dead shard makes the front FAIL-SAFE — pods that match
its keyspace report unschedulable (reason ``shard[unavailable]=...``),
health reports degraded, and every event meant for it marks the shard
dirty; the supervisor's restart triggers a full resync (replay + prune)
after which the shard's controllers recompute and re-push every status,
so no flip is lost.
"""

from __future__ import annotations

import logging
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from ..api.pod import Namespace, Pod, accel_class_of
from ..engine.index import SelectorIndex
from ..engine.store import Event, EventType, NotFoundError, Store, key_of
from ..health import Health
from ..metrics import Registry
from ..plugin.framework import Status, StatusCode
from ..utils.lockorder import guard_attrs, make_lock
from ..utils.tracing import PhaseTracer, vlog
from .ipc import ShardUnavailable
from .ring import (
    HashRing,
    RangeMove,
    TransitionRouting,
    route_key_for,
    stable_hash64,
)

logger = logging.getLogger(__name__)

_KINDS = ("Throttle", "ClusterThrottle")


@guard_attrs
class AdmissionFront:
    """Scatter-gather admission front over ``n_shards`` workers.

    Implements the plugin surface the HTTP server and the scheduler
    speak (``pre_filter`` / ``pre_filter_batch`` / ``reserve`` /
    ``unreserve`` / gang ops / ``health``), so ``cli.py --shards N``
    drops it in where the single-process ``KubeThrottler`` goes.
    """

    # routing maps move only under the route lock (the Router runs under
    # the store lock and takes it; readers take it alone)
    GUARDED_BY = {
        "_owner": "self._route_lock",
        "_pod_routes": "self._route_lock",
        "_route_hash": "self._route_lock",
        "_mirror": "self._route_lock",
        "_transition": "self._route_lock",
        "_gang_routes": "self._txn_lock",
        "_txn_seq": "self._txn_lock",
        "route_misses": "self._route_lock",
        "two_phase_aborts": "self._txn_lock",
        "_epochs": "self._route_lock",
        "_global_epoch": "self._route_lock",
    }

    def __init__(
        self,
        n_shards: int,
        store: Optional[Store] = None,
        metrics_registry: Optional[Registry] = None,
        event_recorder=None,
        faults=None,
        name: str = "kube-throttler",
        rpc_deadline: float = 30.0,
        rpc_deadlines: Optional[Dict[str, float]] = None,
    ):
        self.n_shards = int(n_shards)
        self.name = name
        # per-op RPC deadline budget (--shard-rpc-deadline): every
        # scatter resolves its timeout through deadline_for(op). The
        # batch triage op keeps a wide floor — one device pass over a
        # full shard population legitimately outlives a point RPC
        self.rpc_deadline = float(rpc_deadline)
        self.rpc_deadlines: Dict[str, float] = {
            "pre_filter_batch": max(120.0, self.rpc_deadline),
        }
        self.rpc_deadlines.update(rpc_deadlines or {})
        self.ring = HashRing(self.n_shards)
        self.store = store if store is not None else Store()
        self.metrics_registry = metrics_registry or Registry()
        self.tracer = PhaseTracer(self.metrics_registry)
        self.event_recorder = event_recorder
        self.faults = faults
        self.device_manager = None  # server.py compatibility (host-side front)
        self.shards: Dict[int, object] = {}  # shard_id -> ShardClient/LocalShard
        self._route_lock = make_lock("shard.front.route")
        self._txn_lock = make_lock("shard.front.txn")
        # (kind, key) -> owning shard id
        self._owner: Dict[Tuple[str, str], int] = {}
        # (kind, key) -> ring position of its route key (reshard range
        # membership without re-fingerprinting the object)
        self._route_hash: Dict[Tuple[str, str], int] = {}
        # live resharding: (kind, key) -> (mirror shard, range index)
        # while the covering range is warming; the dual-ring router for
        # keys first seen mid-transition
        self._mirror: Dict[Tuple[str, str], Tuple[int, int]] = {}
        self._transition: Optional[TransitionRouting] = None
        # pod key -> frozenset of shard ids the pod was last routed to
        self._pod_routes: Dict[str, FrozenSet[int]] = {}
        # gang group key -> shard ids holding a prepared reserve
        self._gang_routes: Dict[str, Tuple[int, ...]] = {}
        self._txn_seq = 0
        self.route_misses = 0  # events destined for a down shard
        self.two_phase_aborts = 0  # single-writer per call path; approximate
        # front-side verdict epochs (the scatter-tier mirror of the
        # engine's col_epoch plane, engine/verdictcache.py): one counter
        # per routed throttle key, bumped by every event that can change
        # that key's verdict — spec routes, status echoes/pushes, and the
        # two-phase reservation ops (which mutate shard state without a
        # throttle event). Namespace/reshard/resync mutations bump the
        # global counter. Entries are never popped, even on delete: a
        # re-created key restarting at zero could replay an old epoch sum
        # and falsely validate a pre-delete cache entry (ABA)
        self._epochs: Dict[Tuple[str, str], int] = {}
        self._global_epoch = 0
        # routing index: one SelectorIndex per kind, front-side only. With
        # the columnar merged store the indexes share its intern pool and
        # retain NO pod objects (resolved through the arena below) — this
        # is what kills the front-side copy of the pod population, so
        # full-scale RSS no longer multiplies with shard count
        _arena = getattr(self.store, "pod_arena", None)
        _interner = _arena.pool if _arena is not None else None
        self.index: Dict[str, SelectorIndex] = {
            "Throttle": SelectorIndex("throttle", interner=_interner),
            "ClusterThrottle": SelectorIndex("clusterthrottle", interner=_interner),
        }
        if _arena is not None:
            for idx in self.index.values():
                idx.pod_resolver = self.store.materialize_pod
        # interned-verdict cache over the scatter path: a hit skips the
        # whole fan-out (RPC round trips, not just a plane walk). Only
        # available with the columnar store — the request-shape id that
        # keys it lives in the arena's intern pool
        self.verdict_cache = None
        if _arena is not None and os.environ.get("KT_VERDICT_CACHE", "1") != "0":
            from ..engine.verdictcache import VerdictCache

            try:
                capacity = int(os.environ.get("KT_VERDICT_CACHE_SIZE", "65536"))
            except ValueError:
                capacity = 65536  # malformed override must not kill serving
            self.verdict_cache = VerdictCache(capacity=capacity)
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, self.n_shards), thread_name_prefix="front-scatter"
        )
        # typed read surface (server.py parity with the plugin)
        from ..client import Clientset, InformerBundle, Listers, SharedInformerFactory

        self.clientset = Clientset(self.store)
        self.informer_factory = SharedInformerFactory(self.store, resync_period=0.0)
        self.core_informer_factory = SharedInformerFactory(
            self.store, resync_period=0.0
        )
        self.informers = InformerBundle(
            self.informer_factory, self.core_informer_factory
        )
        self.listers = Listers.from_factories(
            self.informer_factory, self.core_informer_factory
        )
        self.informer_factory.start()
        self.core_informer_factory.start()
        # metrics (families registered in metrics.METRIC_NAMES)
        from ..metrics import register_shard_metrics

        m = register_shard_metrics(self.metrics_registry, self)
        self._m_scatter = m["scatter"]
        self._m_aborts = m["aborts"]
        self._m_misses = m["misses"]
        from ..metrics import register_net_metrics

        # kube_throttler_net_* families: transport health per shard,
        # sampled from the handles at scrape (TCP fleets; zeros locally)
        self.net_metrics = register_net_metrics(self.metrics_registry, self)
        from ..metrics import register_reshard_metrics

        # kube_throttler_reshard_* families: the gauge samples
        # reshard_state() at scrape; the coordinator drives the counters
        # and the cutover histogram through this dict
        self.reshard_metrics = register_reshard_metrics(self.metrics_registry, self)
        from ..metrics import register_build_metrics

        # kube_throttler_build_info + version-mismatch counter: this
        # build's identity plus the per-shard negotiated proto/caps,
        # sampled from the handles at scrape (rolling-upgrade telemetry)
        register_build_metrics(self.metrics_registry, role="front", front=self)
        from ..metrics import register_shm_metrics

        # kube_throttler_shm_* families: zero-copy event-ring health per
        # shard, sampled from each handle's lane at scrape (zeros when
        # the fleet runs plain pickle)
        register_shm_metrics(self.metrics_registry, self)
        self.health = Health()
        self.health.register("shards", self._shards_health)
        # the Router: batch listener + per-event handlers on the store
        # (dispatch order: handlers registered here run for every event;
        # batch-applied events reach _on_batch once, then the per-event
        # handlers skip while in_batch_dispatch is set)
        self.store.add_batch_listener(self)
        for kind in ("Pod", "Namespace", "Throttle", "ClusterThrottle"):
            self.store.add_event_handler(kind, self._on_event, replay=False)

    # ----------------------------------------------------------- shard admin

    def attach_shard(self, shard_id: int, handle, resync: bool = False) -> None:
        """Register (or replace, after a restart) a shard handle. With
        ``resync`` the shard is replayed its full keyspace slice first."""
        self.shards[shard_id] = handle
        # a (re)attached shard serves from replayed state: cached verdicts
        # computed against its predecessor must not validate
        self._bump_global_epoch()
        if resync:
            self.resync_shard(shard_id)

    def owner_of(self, kind: str, key: str) -> Optional[int]:
        """The shard owning a throttle key (None = not yet routed)."""
        with self._route_lock:
            return self._owner.get((kind, key))

    def _alive(self, shard_id: int):
        handle = self.shards.get(shard_id)
        return handle if handle is not None and handle.alive else None

    def _shards_health(self):
        detail = {}
        down = 0
        for sid in range(self.n_shards):
            handle = self.shards.get(sid)
            state = "ok"
            refused = (
                getattr(handle, "version_refused", None)
                if handle is not None else None
            )
            if refused:
                # the worker refused our protocol MAJOR (version.py): a
                # deliberate, typed condition an operator fixes by
                # upgrading one side — named here so /healthz says WHY
                # the shard is dark instead of looking like a partition
                down += 1
                state = f"version-mismatch: {refused}"
            elif handle is None or not handle.alive:
                down += 1
                state = "down"
                if handle is not None and getattr(handle, "transport", "") == "tcp":
                    # connection lost ≠ process died: the TCP client is
                    # reconnecting on its own — the supervisor must NOT
                    # spuriously restart a partitioned remote worker
                    state = "disconnected"
            elif handle.is_dirty():
                state = "degraded"
            detail[f"shard-{sid}"] = state
        if down == self.n_shards and self.n_shards > 0:
            return "down", detail
        if down or any(v == "degraded" for v in detail.values()):
            return "degraded", detail
        return "ok", detail

    # ------------------------------------------------- verdict-cache epochs

    def _bump_key_epoch(self, kind: str, key: str) -> None:
        with self._route_lock:
            self._epochs[(kind, key)] = self._epochs.get((kind, key), 0) + 1

    def _bump_global_epoch(self) -> None:
        with self._route_lock:
            self._global_epoch += 1

    def _bump_pod_epochs(self, pod: Pod) -> None:
        """Bump every throttle key the pod matches — the reservation ops
        change shard-side reserved amounts without any throttle event
        flowing through the Router, so they invalidate here explicitly."""
        with self._route_lock:
            for kind in _KINDS:
                for key in self.index[kind].affected_throttle_keys_for(pod):
                    self._epochs[(kind, key)] = self._epochs.get((kind, key), 0) + 1

    def _verdict_fingerprint(self, pod: Pod):
        """(cache key, epoch sum) for a pod, or None when uncacheable
        (no arena, or a live reshard is re-pointing owners). The key is
        (request-shape id, accel class, matched throttle keys); the sum
        covers exactly those keys plus the global counter, so monotonic
        bumps make equality prove nothing relevant changed (the same
        argument as DeviceStateManager.verdict_fingerprint)."""
        arena = getattr(self.store, "pod_arena", None)
        if arena is None:
            return None
        if pod.__dict__.get("_kt_arena") is arena.token:
            sid = pod.__dict__["_kt_req_sid"]
        else:
            sid = arena.request_shape_id(pod.spec)
        accel = accel_class_of(pod)
        matched: List[Tuple[str, str]] = []
        with self._route_lock:
            if self._transition is not None:
                return None
            esum = self._global_epoch
            for kind in _KINDS:
                for key in sorted(self.index[kind].affected_throttle_keys_for(pod)):
                    matched.append((kind, key))
                    esum += self._epochs.get((kind, key), 0)
        return (sid, accel, tuple(matched)), esum

    @staticmethod
    def _front_cacheable(status: Status) -> bool:
        """ERROR verdicts, fail-safe shard-down verdicts, and exceeds
        verdicts (which emit a Warning event per call — a hit would
        swallow the emission) never enter the cache."""
        if status.code is StatusCode.ERROR:
            return False
        return not any(
            "[pod-requests-exceeds-threshold]" in r
            or r.startswith("shard[unavailable]")
            for r in status.reasons
        )

    # ------------------------------------------------------ routing (Router)

    def on_batch(self, events: List[Event]) -> None:
        """Store batch listener: route the whole ordered batch, one
        per-shard buffer flush at the end."""
        buffers: Dict[int, list] = {}
        for event in events:
            self._route_event(event, buffers)
        self._flush_buffers(buffers)

    def _on_event(self, event: Event) -> None:
        if self.store.in_batch_dispatch:
            return  # already routed by on_batch
        buffers: Dict[int, list] = {}
        self._route_event(event, buffers)
        self._flush_buffers(buffers)

    def _flush_buffers(self, buffers: Dict[int, list]) -> None:
        if len(buffers) > 1:
            self._dedup_fanout(buffers)
        for sid, ops in buffers.items():
            handle = self._alive(sid)
            if handle is None:
                with self._route_lock:
                    self.route_misses += len(ops)
                self._m_misses.inc({}, float(len(ops)))
                handle = self.shards.get(sid)
                if handle is not None:
                    handle.mark_dirty()
                continue
            handle.enqueue_ops(ops)

    @staticmethod
    def _dedup_fanout(buffers: Dict[int, list]) -> None:
        """Fan-out dedup: an op payload routed to N shards used to be
        pickled N times, once per shard batch. Wrap any payload object
        that lands in two or more shard buffers in one shared
        :class:`~.ipc.PrepickledPayload` so the pickle fallback
        serializes it ONCE and splices the cached bytes into every
        shard's frame (``__reduce__`` replays them; the shm encoder
        just unwraps ``.obj`` and pays nothing)."""
        from .ipc import PrepickledPayload

        seen_in: Dict[int, set] = {}
        first: Dict[int, object] = {}
        for sid, ops in buffers.items():
            for op in ops:
                payload = op[2]
                if isinstance(payload, str) or getattr(
                    payload, "_kt_prepickled", False
                ):
                    continue
                seen_in.setdefault(id(payload), set()).add(sid)
                first[id(payload)] = payload
        shared = {
            pid: PrepickledPayload(first[pid])
            for pid, sids in seen_in.items()
            if len(sids) >= 2
        }
        if not shared:
            return
        for ops in buffers.values():
            for i, op in enumerate(ops):
                wrapped = shared.get(id(op[2]))
                if wrapped is not None:
                    ops[i] = (op[0], op[1], wrapped)

    def _route_event(self, event: Event, buffers: Dict[int, list]) -> None:
        kind = event.kind
        if kind == "Namespace":
            self._route_namespace(event, buffers)
        elif kind in _KINDS:
            self._route_throttle(event, buffers)
        elif kind == "Pod":
            self._route_pod(event, buffers)

    def _route_namespace(self, event: Event, buffers) -> None:
        ns: Namespace = event.obj
        # namespace changes alter selector matching (and the unknown-ns
        # ERROR verdict) for arbitrary pods: global invalidation
        self._bump_global_epoch()
        if event.type is EventType.DELETED:
            for idx in self.index.values():
                idx.remove_namespace(ns.name)
            op = ("delete", "Namespace", ns.name)
        else:
            for idx in self.index.values():
                idx.upsert_namespace(ns)
            op = ("upsert", "Namespace", ns)
        for sid in range(self.n_shards):
            buffers.setdefault(sid, []).append(op)

    def _route_throttle(self, event: Event, buffers) -> None:
        kind, thr = event.kind, event.obj
        # ownership/index key is thr.key (what affected_throttle_keys_for
        # answers: "ns/name", or "/name" for ClusterThrottle); store ops
        # use the store key (no leading slash)
        key = thr.key
        store_key = key_of(kind, thr)
        idx = self.index[kind]
        # EVERY throttle event — spec route, delete, or a shard's status
        # echo/push streaming back — can change this key's verdict
        # (status flips carry the active/insufficient transitions), so
        # every path through here bumps its epoch
        self._bump_key_epoch(kind, key)
        if event.type is EventType.DELETED:
            with self._route_lock:
                owner = self._owner.pop((kind, key), None)
                self._route_hash.pop((kind, key), None)
                mirror = self._mirror.pop((kind, key), None)
            idx.remove_throttle(key)
            if owner is not None:
                buffers.setdefault(owner, []).append(("delete", kind, store_key))
            if mirror is not None:
                buffers.setdefault(mirror[0], []).append(("delete", kind, store_key))
            return
        spec_changed = (
            event.type is EventType.ADDED
            or event.old_obj is None
            or event.old_obj.spec != thr.spec
        )
        if not spec_changed:
            # a status write — either this shard's own echo streaming back
            # or a local write; the owner computes statuses, don't route
            idx.refresh_throttle_object(thr)
            return
        h = stable_hash64(route_key_for(kind, thr))
        with self._route_lock:
            tr = self._transition
            if tr is None:
                owner, move = self.ring.owner_of_hash(h), None
            else:
                owner, move = tr.owner_of_hash(h), tr.mirror_of_hash(h)
            prev = self._owner.get((kind, key))
            self._owner[(kind, key)] = owner
            self._route_hash[(kind, key)] = h
            if move is not None:
                self._mirror[(kind, key)] = (move.dst, move.index)
            else:
                self._mirror.pop((kind, key), None)
        idx.upsert_throttle(thr)
        if prev is not None and prev != owner:
            # selector edit moved the key: migrate object + matching pods
            buffers.setdefault(prev, []).append(("delete", kind, store_key))
        targets = [owner] if move is None else [owner, move.dst]
        for sid in targets:
            buffers.setdefault(sid, []).append(("upsert", kind, thr))
        # the (new) owner — and a warming mirror — must hold every pod this
        # throttle matches; send the ones not already routed there (set-
        # difference via the route map keeps this O(matched), no full scan)
        matched = idx.matched_pod_keys(key)
        if matched:
            pods_needed: Dict[str, List[int]] = {}
            with self._route_lock:
                for pkey in matched:
                    routes = self._pod_routes.get(pkey, frozenset())
                    missing = [sid for sid in targets if sid not in routes]
                    if missing:
                        self._pod_routes[pkey] = routes | set(missing)
                        pods_needed[pkey] = missing
            for pkey, sids in pods_needed.items():
                ns, _, pname = pkey.partition("/")
                try:
                    pod = self.store.get_pod(ns, pname)
                except NotFoundError:
                    continue
                for sid in sids:
                    buffers.setdefault(sid, []).append(("upsert", "Pod", pod))

    def _pod_target_shards(self, pod: Pod) -> Set[int]:
        """Shards owning at least one throttle (of either kind) whose
        selector matches the pod — the AUTHORITATIVE scatter set for
        verdicts. During a live reshard a warming mirror is deliberately
        absent here: its verdicts are advisory until the range cuts over."""
        targets: Set[int] = set()
        with self._route_lock:
            for kind in _KINDS:
                for key in self.index[kind].affected_throttle_keys_for(pod):
                    owner = self._owner.get((kind, key))
                    if owner is not None:
                        targets.add(owner)
        return targets

    def _pod_mirror_shards(self, pod: Pod) -> Set[int]:
        """Warming destinations holding a mirrored copy of a matching
        throttle — the double-route extension for events and the reserve
        fan-out (a reservation made only on the source during warm-up
        would be missing from the destination at cutover)."""
        mirrors: Set[int] = set()
        with self._route_lock:
            if not self._mirror:
                return mirrors
            for kind in _KINDS:
                for key in self.index[kind].affected_throttle_keys_for(pod):
                    m = self._mirror.get((kind, key))
                    if m is not None:
                        mirrors.add(m[0])
        return mirrors

    def _route_pod(self, event: Event, buffers) -> None:
        pod: Pod = event.obj
        for idx in self.index.values():
            if event.type is EventType.DELETED:
                idx.remove_pod(pod.key)
            else:
                idx.upsert_pod(pod)
        if event.type is EventType.DELETED:
            with self._route_lock:
                routes = self._pod_routes.pop(pod.key, frozenset())
            for sid in routes:
                buffers.setdefault(sid, []).append(("delete", "Pod", pod.key))
            return
        new_set = frozenset(
            self._pod_target_shards(pod) | self._pod_mirror_shards(pod)
        )
        with self._route_lock:
            old_set = self._pod_routes.get(pod.key, frozenset())
            if new_set:
                self._pod_routes[pod.key] = new_set
            else:
                self._pod_routes.pop(pod.key, None)
        for sid in new_set:
            buffers.setdefault(sid, []).append(("upsert", "Pod", pod))
        for sid in old_set - new_set:
            # the pod stopped matching anything on sid: a delete keeps that
            # shard's store/aggregates clean (equivalent to updating it —
            # a non-matching pod contributes nothing — but O(1) forever)
            buffers.setdefault(sid, []).append(("delete", "Pod", pod.key))

    # ------------------------------------------------------- status upstream

    def apply_status_push(self, shard_id: int, items) -> None:
        """Shard → front status stream: replace ONLY the status of the
        front's stored object (status-subresource semantics) so an echo
        in flight can never revert a newer routed spec. Keys the front no
        longer holds (concurrent delete) are skipped per key. The
        resulting MODIFIED events are spec-unchanged by construction, so
        the Router does not route them back (no echo loop)."""
        thrs = [obj for kind, obj in items if kind == "Throttle"]
        cthrs = [obj for kind, obj in items if kind == "ClusterThrottle"]
        if thrs:
            self.store.update_throttle_statuses(thrs)
        if cthrs:
            self.store.update_cluster_throttle_statuses(cthrs)

    # ----------------------------------------------------------- scatter RPC

    def deadline_for(self, op: str) -> float:
        """The per-op RPC deadline budget for a scatter call."""
        return self.rpc_deadlines.get(op, self.rpc_deadline)

    def _scatter(
        self, targets: Sequence[int], op: str, payload,
        timeout: Optional[float] = None,
    ):
        """Fan an RPC out to ``targets``; returns {shard_id: result}.
        Shard failures surface as the exception object in the map.
        ``timeout=None`` resolves through the per-op deadline budget."""
        if timeout is None:
            timeout = self.deadline_for(op)
        t0 = time.monotonic()
        targets = list(targets)

        def call(sid: int):
            handle = self._alive(sid)
            if handle is None:
                return ShardUnavailable(f"shard {sid} is down")
            try:
                return handle.request(op, payload, timeout=timeout)
            except Exception as e:  # noqa: BLE001 — merged by the caller
                return e
        if len(targets) == 1:
            out = {targets[0]: call(targets[0])}
        else:
            futs = {sid: self._pool.submit(call, sid) for sid in targets}
            out = {sid: f.result() for sid, f in futs.items()}
        self._m_scatter.observe_key((op,), time.monotonic() - t0)
        return out

    # ------------------------------------------------------------ pre_filter

    def pre_filter(self, pod: Pod) -> Status:
        with self.tracer.trace("prefilter"):
            return self._pre_filter(pod)

    def _pre_filter(self, pod: Pod) -> Status:
        # the single-process ClusterThrottle check errors on a pod whose
        # Namespace object is unknown (clusterthrottle_controller.go:273-
        # 276) before anything else can answer — replicate it centrally
        # so a pod matching zero shards still gets the identical verdict
        if self.store.get_namespace(pod.namespace) is None:
            return Status(
                StatusCode.ERROR,
                (str(NotFoundError(f"namespace {pod.namespace!r} not found")),),
            )
        targets = sorted(self._pod_target_shards(pod))
        if not targets:
            vlog(5, "pod %s is not throttled by any throttle/clusterthrottle (0 shards)", pod.key)
            return Status(StatusCode.SUCCESS)
        # interned-verdict probe: a hit skips the whole scatter. Gated on
        # every target shard being alive and clean — a cached SUCCESS must
        # not outlive the fail-safe discipline (shard death bumps no
        # epoch), and a dirty shard's answers are stale until resync
        cache = self.verdict_cache
        fp = None
        if cache is not None:
            for s in targets:
                handle = self._alive(s)
                if handle is None or handle.is_dirty():
                    break
            else:
                fp = self._verdict_fingerprint(pod)
        if fp is not None:
            hit = cache.get(fp[0], fp[1])
            if hit is not None:
                return hit
        results = self._scatter(targets, "pre_filter", pod)
        down = sorted(
            sid for sid, r in results.items() if isinstance(r, ShardUnavailable)
        )
        if down:
            # FAIL-SAFE degradation: this pod's keyspace is dark — report
            # unschedulable rather than fabricate an admission
            return Status(
                StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE,
                tuple(f"shard[unavailable]=shard-{sid}" for sid in down),
            )
        errors: List[str] = []
        merged = {
            "throttle": {"active": set(), "insufficient": set(), "exceeds": set()},
            "clusterthrottle": {
                "active": set(), "insufficient": set(), "exceeds": set()
            },
        }
        for sid, r in sorted(results.items()):
            if isinstance(r, Exception):
                errors.append(str(r))
                continue
            for kind, cats in r.items():
                if "error" in cats:
                    errors.append(cats["error"])
                    continue
                for cat, keys in cats.items():
                    merged[kind][cat].update(keys)
        if errors:
            return Status(StatusCode.ERROR, tuple(sorted(set(errors))))
        status = self._compose_status(pod, merged)
        if (
            fp is not None
            and self._front_cacheable(status)
            # validate-after-compute: a mutation that raced the scatter
            # bumped an epoch, so the re-read sum differs and the insert
            # is suppressed instead of poisoning the cache
            and self._verdict_fingerprint(pod) == fp
        ):
            cache.put(fp[0], fp[1], status)
        return status

    def _compose_status(self, pod: Pod, merged) -> Status:
        """Reason composition in the exact plugin.go:182-214 order, from
        the AND-merged shard verdicts. Name lists are sorted — the
        single-process ordering is index-column order, which no longer
        exists across shards; verdict equivalence is pinned on sorted
        name sets (tools/harness.normalized_reasons)."""
        thr, clthr = merged["throttle"], merged["clusterthrottle"]
        if not any(thr.values()) and not any(clthr.values()):
            vlog(5, "pod %s is not throttled by any throttle/clusterthrottle", pod.key)
            return Status(StatusCode.SUCCESS)
        reasons: List[str] = []
        if clthr["exceeds"]:
            reasons.append(
                "clusterthrottle[pod-requests-exceeds-threshold]="
                + ",".join(sorted(clthr["exceeds"]))
            )
        if thr["exceeds"]:
            reasons.append(
                "throttle[pod-requests-exceeds-threshold]="
                + ",".join(sorted(thr["exceeds"]))
            )
        if (clthr["exceeds"] or thr["exceeds"]) and self.event_recorder is not None:
            names = sorted(clthr["exceeds"]) + sorted(thr["exceeds"])
            self.event_recorder.eventf(
                pod.key,
                "Warning",
                "ResourceRequestsExceedsThrottleThreshold",
                self.name,
                "It won't be scheduled unless decreasing resource requests or "
                "increasing ClusterThrottle/Throttle threshold because its "
                f"resource requests exceeds their thresholds: {','.join(names)}",
            )
        if clthr["active"]:
            reasons.append(
                "clusterthrottle[active]=" + ",".join(sorted(clthr["active"]))
            )
        if thr["active"]:
            reasons.append("throttle[active]=" + ",".join(sorted(thr["active"])))
        if clthr["insufficient"]:
            reasons.append(
                "clusterthrottle[insufficient]="
                + ",".join(sorted(clthr["insufficient"]))
            )
        if thr["insufficient"]:
            reasons.append(
                "throttle[insufficient]=" + ",".join(sorted(thr["insufficient"]))
            )
        vlog(2, "pod %s is unschedulable: %s", pod.key, "; ".join(reasons))
        return Status(StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE, tuple(reasons))

    def pre_filter_batch(self) -> dict:
        """Bulk triage, sharded: every shard classifies its own pods in
        one local device pass; the front ANDs verdicts per pod across the
        shards that carry it and fills in the pods no shard holds (they
        match nothing ⇒ schedulable, unless their namespace is unknown —
        the identical never-schedulable routing the single-process merge
        applies)."""
        with self.tracer.trace("prefilter_batch"):
            alive = [s for s in range(self.n_shards) if self._alive(s) is not None]
            results = self._scatter(alive, "pre_filter_batch", None)
            # during a live reshard the AND-merge must consult only each
            # pod's AUTHORITATIVE owners: a warming mirror's verdict is
            # advisory (it may lag the source), and a dead mirror must not
            # fail-safe pods whose owners are healthy
            owner_filter: Optional[Dict[str, Set[int]]] = None
            with self._route_lock:
                transition_active = self._transition is not None
            if transition_active:
                owner_filter = {
                    pod.key: self._pod_target_shards(pod)
                    for pod in self.store.list_pods()
                }
            schedulable: Dict[str, bool] = {}
            errors: Set[str] = set()
            for sid in sorted(results):
                r = results[sid]
                if isinstance(r, Exception):
                    continue  # its routed pods are handled as down below
                for key, ok in r["schedulable"].items():
                    if (
                        owner_filter is not None
                        and sid not in owner_filter.get(key, frozenset())
                    ):
                        continue
                    schedulable[key] = schedulable.get(key, True) and bool(ok)
                errors.update(r["errors"])
            # pods routed to a shard that answered nothing are dark: fail
            # safe, like the per-pod surface
            dead = {
                sid
                for sid in range(self.n_shards)
                if sid not in results or isinstance(results.get(sid), Exception)
            }
            if dead:
                if owner_filter is not None:
                    for pkey, sids in owner_filter.items():
                        if sids & dead:
                            schedulable[pkey] = False
                else:
                    with self._route_lock:
                        routes = dict(self._pod_routes)
                    for pkey, sids in routes.items():
                        if sids & dead:
                            schedulable[pkey] = False
            known_ns = {ns.name for ns in self.store.list_namespaces()}
            for pod in self.store.list_pods():
                if pod.key not in schedulable and pod.key not in errors:
                    schedulable[pod.key] = True
            bad = [k for k in schedulable if k.partition("/")[0] not in known_ns]
            for key in bad:
                del schedulable[key]
                errors.add(key)
            return {"schedulable": schedulable, "errors": sorted(errors)}

    # --------------------------------------------------- two-phase reserve

    def _next_txn(self) -> str:
        with self._txn_lock:
            self._txn_seq += 1
            return f"front-txn-{self._txn_seq}"

    def reserve(self, pod: Pod, node: str = "") -> Status:
        """Two-phase reserve: prepare on every matching shard, commit (or
        abort) from the front. Any prepare failure aborts the prepared
        subset — no cross-shard transaction, no partial reserve."""
        with self.tracer.trace("reserve"):
            # mirrors ride the two-phase fan-out: a reserve prepared only
            # on the source during a handoff would be missing from the
            # destination at cutover (a lost reservation, not an orphan)
            targets = sorted(
                self._pod_target_shards(pod) | self._pod_mirror_shards(pod)
            )
            if not targets:
                return Status(StatusCode.SUCCESS)
            txn = self._next_txn()
            results = self._scatter(targets, "reserve_prepare", {"txn": txn, "pod": pod})
            failed = {sid: r for sid, r in results.items() if isinstance(r, Exception)}
            if failed:
                # abort EVERY target, not just the ones that answered ok:
                # a prepare that TIMED OUT may still have landed (the
                # deadline is the front's clock, not the shard's) — only
                # an abort addressed to all of them guarantees zero
                # orphans now rather than after the shard's TTL reaper.
                # Shards that never saw the prepare no-op the abort
                self._scatter(targets, "txn_abort", {"txn": txn})
                with self._txn_lock:
                    self.two_phase_aborts += 1
                self._m_aborts.inc({})
                # the abort rolled prepared shards back, but bump anyway:
                # invalidating a still-valid entry costs one recompute;
                # missing a real change costs a wrong verdict
                self._bump_pod_epochs(pod)
                return Status(
                    StatusCode.ERROR,
                    tuple(
                        f"Failed to reserve pod={pod.key} on shard {sid}: {e}"
                        for sid, e in sorted(failed.items())
                    ),
                )
            self._scatter(targets, "txn_commit", {"txn": txn})
            self._bump_pod_epochs(pod)
            return Status(StatusCode.SUCCESS)

    def unreserve(self, pod: Pod, node: str = "") -> None:
        with self.tracer.trace("unreserve"):
            targets = sorted(
                self._pod_target_shards(pod) | self._pod_mirror_shards(pod)
            )
            results = self._scatter(targets, "unreserve", pod)
            for sid, r in results.items():
                if isinstance(r, Exception):
                    logger.warning("unreserve of %s on shard %d failed: %s",
                                   pod.key, sid, r)
            self._bump_pod_epochs(pod)

    # -------------------------------------------------------- gang admission

    def _gang_targets(self, group_key: str, pods: Sequence[Pod]) -> List[int]:
        """Shards touched by a gang: every member-matching shard PLUS the
        group's hash owner — the one shard whose ledger holds the
        authoritative group record (journal GANG stamps, TTL clock)."""
        targets: Set[int] = set()
        for pod in pods:
            targets |= self._pod_target_shards(pod)
            targets |= self._pod_mirror_shards(pod)
        targets.add(self.gang_owner(group_key))
        mirror = self._gang_mirror(group_key)
        if mirror is not None:
            targets.add(mirror)
        return sorted(targets)

    def gang_owner(self, group_key: str) -> int:
        h = stable_hash64(route_key_for("Gang", group_key))
        with self._route_lock:
            tr = self._transition
        if tr is not None:
            return tr.owner_of_hash(h)
        return self.ring.owner_of_hash(h)

    def _gang_mirror(self, group_key: str) -> Optional[int]:
        h = stable_hash64(route_key_for("Gang", group_key))
        with self._route_lock:
            tr = self._transition
        if tr is not None:
            move = tr.mirror_of_hash(h)
            if move is not None:
                return move.dst
        return None

    def pre_filter_gang(self, group_key: str, pods: Sequence[Pod]) -> Status:
        """Group feasibility scatter-gather. Feasibility partitions by
        throttle (a group fits iff it fits under every matched throttle),
        so shard-local gang checks AND-merge exactly like pre_filter."""
        with self.tracer.trace("prefilter_gang"):
            if not pods:
                return Status(StatusCode.SUCCESS)
            targets = [
                sid for sid in sorted(set().union(
                    *(self._pod_target_shards(p) for p in pods)
                ))
            ]
            if not targets:
                return Status(StatusCode.SUCCESS)
            results = self._scatter(
                targets, "gang_check", {"group": group_key, "pods": list(pods)}
            )
            reasons: List[str] = []
            errors: List[str] = []
            for sid in sorted(results):
                r = results[sid]
                if isinstance(r, ShardUnavailable):
                    reasons.append(f"shard[unavailable]=shard-{sid}")
                elif isinstance(r, Exception):
                    errors.append(str(r))
                elif r["code"] == StatusCode.ERROR.value:
                    errors.extend(r["reasons"])
                elif r["code"] != StatusCode.SUCCESS.value:
                    reasons.extend(r["reasons"])
            if errors:
                return Status(StatusCode.ERROR, tuple(sorted(set(errors))))
            if reasons:
                return Status(
                    StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE,
                    tuple(sorted(set(reasons))),
                )
            return Status(StatusCode.SUCCESS)

    def reserve_gang(self, group_key: str, pods: Sequence[Pod]) -> Status:
        """Two-phase gang reserve: each target shard performs its local
        all-or-nothing ``reserve_gang`` (its own ledger rolls back its own
        members on local failure); the front aborts every prepared shard
        if ANY prepare fails, so the group is reserved everywhere or
        nowhere."""
        with self.tracer.trace("reserve_gang"):
            targets = self._gang_targets(group_key, pods)
            owner = self.gang_owner(group_key)
            txn = self._next_txn()
            results = {}
            for sid in targets:
                r = self._scatter(
                    [sid], "gang_prepare",
                    {
                        "txn": txn, "group": group_key, "pods": list(pods),
                        "owner": sid == owner,
                    },
                )
                results.update(r)
            failed = {sid: r for sid, r in results.items() if isinstance(r, Exception)}
            if failed:
                # same zero-orphan discipline as reserve(): a timed-out
                # gang_prepare may have landed — abort ALL targets
                self._scatter(targets, "txn_abort", {"txn": txn})
                with self._txn_lock:
                    self.two_phase_aborts += 1
                self._m_aborts.inc({})
                for p in pods:
                    self._bump_pod_epochs(p)
                return Status(
                    StatusCode.ERROR,
                    tuple(
                        f"gang {group_key}: prepare failed on shard {sid}: {e}"
                        for sid, e in sorted(failed.items())
                    ),
                )
            self._scatter(targets, "txn_commit", {"txn": txn})
            with self._txn_lock:
                self._gang_routes[group_key] = tuple(targets)
            for p in pods:
                self._bump_pod_epochs(p)
            return Status(StatusCode.SUCCESS)

    def unreserve_gang(self, group_key: str) -> None:
        with self.tracer.trace("unreserve_gang"):
            with self._txn_lock:
                targets = self._gang_routes.pop(group_key, None)
            if targets is None:
                targets = [
                    sid for sid in range(self.n_shards)
                    if self._alive(sid) is not None
                ]
            self._scatter(list(targets), "gang_rollback", {"group": group_key})
            # the rolled-back members are unknown here (the ledger lives
            # shard-side): global invalidation
            self._bump_global_epoch()

    # ------------------------------------------------------ live resharding
    # (driven by sharding/reshard.ReshardCoordinator; every mutation of
    # the routing maps happens under the route lock, so a cutover is
    # atomic with respect to the Router and the scatter target builders)

    def begin_reshard(self, transition: TransitionRouting) -> None:
        """Install the dual-ring transition router. From here until
        ``finish_reshard``/``cancel_reshard``, new keys route through it
        (old-ring owner until the covering range cuts over)."""
        with self._route_lock:
            self._transition = transition
            # every reshard phase bumps the global verdict epoch inline
            # (already under the route lock): cached verdicts predate the
            # ownership moves and must not validate across them
            self._global_epoch += 1

    def begin_range(self, move: RangeMove) -> int:
        """Turn double-routing ON for one moving range: every owned key
        whose route hash the range covers gains a mirror entry, and keys
        first seen from now on mirror via the transition router. Returns
        the number of keys mirrored."""
        n = 0
        with self._route_lock:
            if self._transition is not None:
                self._transition.set_state(move.index, TransitionRouting.MIRRORING)
            for (kind, key), h in self._route_hash.items():
                if move.covers(h):
                    self._mirror[(kind, key)] = (move.dst, move.index)
                    n += 1
        return n

    def cutover_range(self, move: RangeMove) -> int:
        """The atomic per-range cutover: re-point every mirrored key's
        owner at the destination and drop its mirror entry, all under one
        route-lock hold — no event, check, or reserve can observe a
        half-cut range. Returns keys re-pointed."""
        n = 0
        with self._route_lock:
            if self._transition is not None:
                self._transition.set_state(move.index, TransitionRouting.CUT)
            for (kind, key), (dst, ridx) in list(self._mirror.items()):
                if ridx == move.index:
                    self._owner[(kind, key)] = dst
                    del self._mirror[(kind, key)]
                    n += 1
            self._global_epoch += 1
        return n

    def abort_range(self, move: RangeMove) -> int:
        """Abort-back-to-source: drop the range's mirror entries (owners
        were never re-pointed) and return the range to ``pending`` so a
        later attempt can re-stream it."""
        n = 0
        with self._route_lock:
            if self._transition is not None:
                self._transition.set_state(move.index, TransitionRouting.PENDING)
            for (kind, key), (_dst, ridx) in list(self._mirror.items()):
                if ridx == move.index:
                    del self._mirror[(kind, key)]
                    n += 1
        return n

    def finish_reshard(self, new_ring: HashRing, n_shards: int) -> None:
        """Adopt the target ring as THE ring (every range cut over) and
        drop the transition router."""
        with self._route_lock:
            self.ring = new_ring
            self._transition = None
            self._mirror.clear()
            self._global_epoch += 1
        self.n_shards = int(n_shards)

    def cancel_reshard(self) -> None:
        """Abandon a reshard whose every range was aborted: the old ring
        stays authoritative (owners were never re-pointed)."""
        with self._route_lock:
            self._transition = None
            self._mirror.clear()
            self._global_epoch += 1

    def reshard_state(self) -> Optional[Dict[str, object]]:
        with self._route_lock:
            tr = self._transition
            mirrored = len(self._mirror)
        if tr is None:
            return None
        states = list(tr.state.values())
        return {
            "moves": len(states),
            "pending": states.count(TransitionRouting.PENDING),
            "mirroring": states.count(TransitionRouting.MIRRORING),
            "cut": states.count(TransitionRouting.CUT),
            "mirrored_keys": mirrored,
            "target_shards": tr.new_ring.n_shards,
        }

    # ------------------------------------------------------- resync / drain

    def resync_shard(self, shard_id: int) -> int:
        """Replay a (restarted) shard's full keyspace slice: namespaces,
        owned throttles, their matching pods, then a prune of everything
        the replay did not name. Returns ops sent. The shard's controllers
        recompute every status from the replayed state and push the
        results back — flips the dead worker never published re-derive."""
        handle = self.shards.get(shard_id)
        if handle is None:
            return 0
        bump = getattr(handle, "bump_epoch", None)
        if bump is not None:
            # fence the past before replaying the present: frames from
            # before the heal (a partitioned peer's view, bytes parked in
            # a kernel buffer) must be refused once this resync lands
            bump()
        # store.atomic(): snapshot reads and the enqueue must be ATOMIC
        # w.r.t. dispatch — mutations dispatch (and _flush_buffers
        # enqueues) under the store lock, so holding it here means no
        # live event can land in the shard queue between this snapshot's
        # reads and its enqueue. Without it, an event routed while we
        # iterate sits BEFORE the (older) snapshot in the queue and the
        # worker keeps the stale object forever.
        with self.store.atomic():
            n = self._resync_locked(shard_id, handle)
        # the healed shard recomputes everything from the replay; cached
        # verdicts from before the heal must not validate
        self._bump_global_epoch()
        return n

    def _resync_locked(self, shard_id: int, handle) -> int:
        ops: List[tuple] = []
        want: Dict[str, List[str]] = {
            "Namespace": [], "Throttle": [], "ClusterThrottle": [], "Pod": [],
        }
        for ns in self.store.list_namespaces():
            ops.append(("upsert", "Namespace", ns))
            want["Namespace"].append(ns.name)
        with self._route_lock:
            owned = [
                (kind, key) for (kind, key), sid in self._owner.items()
                if sid == shard_id
            ]
            pod_keys = [
                pkey for pkey, sids in self._pod_routes.items() if shard_id in sids
            ]
        for kind, key in owned:
            try:
                if kind == "Throttle":
                    ns, _, nm = key.partition("/")
                    obj = self.store.get_throttle(ns, nm)
                else:
                    obj = self.store.get_cluster_throttle(key.lstrip("/"))
            except NotFoundError:
                continue
            ops.append(("upsert", kind, obj))
            # the prune set compares STORE keys on the shard
            want[kind].append(key_of(kind, obj))
        for pkey in pod_keys:
            ns, _, nm = pkey.partition("/")
            try:
                pod = self.store.get_pod(ns, nm)
            except NotFoundError:
                continue
            ops.append(("upsert", "Pod", pod))
            want["Pod"].append(pkey)
        from .worker import RESYNC_PRUNE

        ops.append((RESYNC_PRUNE, "", want))
        handle.enqueue_ops(ops)
        handle.clear_dirty()
        logger.info("resynced shard %d: %d ops", shard_id, len(ops))
        return len(ops)

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until every alive shard has applied everything routed to
        it and its workqueues are empty (the bench's applied-not-submitted
        accounting point)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            busy = False
            for sid in range(self.n_shards):
                handle = self._alive(sid)
                if handle is None:
                    continue
                if handle.pending_events() > 0:
                    busy = True
                    continue
                try:
                    d = handle.request("drain", {"timeout": 2.0}, timeout=30.0)
                except (ShardUnavailable, RuntimeError):
                    continue
                if d["queue"] > 0 or any(v > 0 for v in d["workqueues"].values()):
                    busy = True
            if not busy:
                return True
            time.sleep(0.02)
        return False

    def stats(self) -> dict:
        """Front + per-shard aggregate (the bench and /readyz detail)."""
        shards = {}
        for sid in range(self.n_shards):
            handle = self._alive(sid)
            if handle is None:
                shards[sid] = {"alive": False}
                continue
            try:
                s = handle.request("stats", None, timeout=10.0)
            except (ShardUnavailable, RuntimeError) as e:
                shards[sid] = {"alive": False, "error": str(e)}
                continue
            s["alive"] = True
            s["events_sent"] = handle.events_sent
            s["dropped_at_front"] = handle.dropped
            s["transport"] = getattr(handle, "transport", "socketpair")
            s["reconnects"] = getattr(handle, "reconnects", 0)
            s["rpc_deadline_exceeded"] = getattr(handle, "deadline_exceeded", 0)
            shards[sid] = s
        with self._route_lock:
            misses = self.route_misses
            routed_pods = len(self._pod_routes)
            owned = len(self._owner)
        with self._txn_lock:
            aborts = self.two_phase_aborts
        return {
            "shards": shards,
            "route_misses": misses,
            "routed_pods": routed_pods,
            "owned_throttles": owned,
            "two_phase_aborts": aborts,
            "reshard": self.reshard_state(),
        }

    # ------------------------------------------------------------- lifecycle

    def full_tick_sharded(self, n_devices=None, shape=None) -> dict:
        raise RuntimeError(
            "full_tick_sharded is a single-process device surface; the "
            "multiprocess front serves pre_filter_batch instead"
        )

    def run_pending_once(self) -> int:
        """Drain helper parity with the plugin (tests): waits for shard
        queues/workqueues instead of running local controllers."""
        self.drain(timeout=30.0)
        return 0

    def start(self) -> None:  # the workers already run their controllers
        return None

    def stop(self) -> None:
        for handle in self.shards.values():
            try:
                handle.close()
            except Exception:  # noqa: BLE001
                pass
        self._pool.shutdown(wait=False)
        self.informer_factory.shutdown()
        self.core_informer_factory.shutdown()
