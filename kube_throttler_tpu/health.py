"""Per-component health state machine behind ``/readyz``.

kube-scheduler's healthz is a flat 200/500; a degraded throttler is more
nuanced — the device breaker being open is a latency regression, not
unreadiness (the host oracle serves); a reflector stuck in backoff is
stale-but-serving; a journal that skipped corrupt lines recovered lossily.
Operators need those distinctions without grepping logs, and probes need a
single verdict.

Components register a probe returning ``(state, detail)`` where state is
one of ``ok`` / ``degraded`` / ``down``:

- ``ok``       — fully functional;
- ``degraded`` — serving with reduced fidelity/latency (open breaker,
  reflector retrying, lossy journal recovery); /readyz stays 200 so the
  pod is NOT yanked from rotation while it can still answer;
- ``down``     — the component cannot serve (reflector never synced:
  admission verdicts would be fabricated from an empty cache); /readyz
  returns 503.

The aggregate verdict is the worst component state. Probes run at request
time on the serving thread — they must be cheap reads of existing state,
never RPCs.

``snapshot()`` also RECORDS state transitions: each time a component's
probed state differs from its last probed state, ``(component, old, new)``
is appended to a bounded transition log. The log is the scenario hunt's
coverage signal (scenarios/hunt/coverage.py) — a fault schedule that
drives a component through a transition nobody has seen before is, by
definition, new behavior worth keeping — and a cheap debugging timeline
("when did the reflector first degrade?") for everyone else.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple, Union

from .utils.lockorder import guard_attrs, make_lock

# probe return: "ok" | ("ok", {...detail}) — detail optional
ProbeResult = Union[str, Tuple[str, dict]]
Probe = Callable[[], ProbeResult]

_SEVERITY = {"ok": 0, "degraded": 1, "down": 2}
STATES = tuple(_SEVERITY)


@guard_attrs
class Health:
    """Registry of component probes + aggregate snapshot."""

    GUARDED_BY = {
        "_probes": "self._lock",
        "_last_states": "self._lock",
        "_transitions": "self._lock",
    }

    # bounded transition log: old entries are dropped FIFO so a flapping
    # component cannot grow the process unboundedly
    MAX_TRANSITIONS = 1000

    def __init__(self) -> None:
        self._lock = make_lock("health")
        self._probes: Dict[str, Probe] = {}
        self._last_states: Dict[str, str] = {}
        self._transitions: List[Tuple[str, str, str]] = []

    def register(self, component: str, probe: Probe) -> None:
        """Register (or replace) a component probe."""
        with self._lock:
            self._probes[component] = probe

    def unregister(self, component: str) -> None:
        with self._lock:
            self._probes.pop(component, None)

    def snapshot(self) -> dict:
        """Run every probe; returns ``{"state": worst, "components":
        {name: {"state": ..., ...detail}}}``. A probe that raises marks its
        component ``down`` (a broken health check is not evidence of
        health) rather than failing the endpoint."""
        with self._lock:
            probes = list(self._probes.items())
        components: Dict[str, dict] = {}
        worst = "ok"
        for name, probe in probes:
            try:
                result = probe()
            except Exception as e:  # noqa: BLE001 — probe bugs must not 500 /readyz
                state, detail = "down", {"error": f"{e.__class__.__name__}: {e}"}
            else:
                if isinstance(result, tuple):
                    state, detail = result
                else:
                    state, detail = result, {}
                if state not in _SEVERITY:
                    state, detail = "down", {"error": f"bad probe state {state!r}"}
            components[name] = {"state": state, **(detail or {})}
            if _SEVERITY[state] > _SEVERITY[worst]:
                worst = state
        with self._lock:
            for name, comp in components.items():
                prev = self._last_states.get(name)
                cur = comp["state"]
                if prev is not None and prev != cur:
                    self._transitions.append((name, prev, cur))
                self._last_states[name] = cur
            if len(self._transitions) > self.MAX_TRANSITIONS:
                del self._transitions[: -self.MAX_TRANSITIONS]
        return {"state": worst, "components": components}

    def transitions(self) -> List[Tuple[str, str, str]]:
        """Observed ``(component, old_state, new_state)`` transitions, in
        observation order. Transitions are only recorded at ``snapshot()``
        time — a consumer that wants a fine-grained timeline samples
        snapshots at its own cadence (the scenario engine samples on the
        replayer's tick)."""
        with self._lock:
            return list(self._transitions)

    def reset_transitions(self) -> None:
        """Drop the transition log and the last-seen states (a new
        measurement epoch: the next snapshot seeds fresh baselines)."""
        with self._lock:
            self._transitions.clear()
            self._last_states.clear()


__all__ = ["Health", "STATES"]
