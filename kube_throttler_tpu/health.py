"""Per-component health state machine behind ``/readyz``.

kube-scheduler's healthz is a flat 200/500; a degraded throttler is more
nuanced — the device breaker being open is a latency regression, not
unreadiness (the host oracle serves); a reflector stuck in backoff is
stale-but-serving; a journal that skipped corrupt lines recovered lossily.
Operators need those distinctions without grepping logs, and probes need a
single verdict.

Components register a probe returning ``(state, detail)`` where state is
one of ``ok`` / ``degraded`` / ``down``:

- ``ok``       — fully functional;
- ``degraded`` — serving with reduced fidelity/latency (open breaker,
  reflector retrying, lossy journal recovery); /readyz stays 200 so the
  pod is NOT yanked from rotation while it can still answer;
- ``down``     — the component cannot serve (reflector never synced:
  admission verdicts would be fabricated from an empty cache); /readyz
  returns 503.

The aggregate verdict is the worst component state. Probes run at request
time on the serving thread — they must be cheap reads of existing state,
never RPCs.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple, Union

from .utils.lockorder import guard_attrs, make_lock

# probe return: "ok" | ("ok", {...detail}) — detail optional
ProbeResult = Union[str, Tuple[str, dict]]
Probe = Callable[[], ProbeResult]

_SEVERITY = {"ok": 0, "degraded": 1, "down": 2}
STATES = tuple(_SEVERITY)


@guard_attrs
class Health:
    """Registry of component probes + aggregate snapshot."""

    GUARDED_BY = {"_probes": "self._lock"}

    def __init__(self) -> None:
        self._lock = make_lock("health")
        self._probes: Dict[str, Probe] = {}

    def register(self, component: str, probe: Probe) -> None:
        """Register (or replace) a component probe."""
        with self._lock:
            self._probes[component] = probe

    def unregister(self, component: str) -> None:
        with self._lock:
            self._probes.pop(component, None)

    def snapshot(self) -> dict:
        """Run every probe; returns ``{"state": worst, "components":
        {name: {"state": ..., ...detail}}}``. A probe that raises marks its
        component ``down`` (a broken health check is not evidence of
        health) rather than failing the endpoint."""
        with self._lock:
            probes = list(self._probes.items())
        components: Dict[str, dict] = {}
        worst = "ok"
        for name, probe in probes:
            try:
                result = probe()
            except Exception as e:  # noqa: BLE001 — probe bugs must not 500 /readyz
                state, detail = "down", {"error": f"{e.__class__.__name__}: {e}"}
            else:
                if isinstance(result, tuple):
                    state, detail = result
                else:
                    state, detail = result, {}
                if state not in _SEVERITY:
                    state, detail = "down", {"error": f"bad probe state {state!r}"}
            components[name] = {"state": state, **(detail or {})}
            if _SEVERITY[state] > _SEVERITY[worst]:
                worst = state
        return {"state": worst, "components": components}


__all__ = ["Health", "STATES"]
