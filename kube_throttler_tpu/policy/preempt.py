"""PreemptionCoordinator: journaled, gang-atomic victim eviction.

The scheduler calls :meth:`preempt_for_gang` when ``pre_filter_gang``
rejected a group for capacity (scheduler.py ``_schedule_gang``). One cycle:

1. **Policy gate** — the active :class:`~..policy.spec.PolicySpec` must
   enable preemption, the preemptor's priority must be positive, and the
   group must be outside its cooldown window (the anti-thrash floor the
   preemption-storm scenario gates on).
2. **Deficits** — ``compute_gang_deficits``: the exact per-(kind,
   throttle, dim) capacity shortfalls, accel-class-resolved. None ⇒ the
   group can never fit (a member alone exceeds a threshold) — no victim
   set helps, nothing is evicted.
3. **Candidates** — running (count-in, non-finished) pods matched to a
   deficit throttle whose priority sits at least ``min_priority_gap``
   below the preemptor's, grouped into eviction units (a gang member
   drags its whole gang — no half-evicted gangs by construction), ranked
   (weight asc, priority asc, age desc).
4. **Selection** — the batched kernel (ops/victim_select.py) when a
   device manager is wired (``KT_PREEMPT_DEVICE=0`` forces the host
   path), else the sequential oracle; both walk the identical ranked
   arrays, so the choice is a performance knob, never a semantic one.
   If even the full eligible set cannot cover the deficits, NOTHING is
   evicted (counted ``infeasible``): partial eviction would churn victims
   without admitting the group.
5. **Eviction** — journal ``PREEMPT begin`` (victim keys + serialized
   objects: the crash-rollback payload), roll back victim gangs' ledger
   records, then delete each victim pod through the store
   (delete-then-requeue: the DELETED events free node occupancy, drop
   used sums, and the flip-candidate promotion publishes the freed-
   capacity flips through the priority lane first), then ``PREEMPT
   commit``. A crash between begin and commit rolls back to ZERO
   evictions at recovery (engine/journal.py ``rollback_uncommitted_
   preempts`` re-creates the victims from the begin line), mirroring the
   GANG contract; a live mid-eviction exception restores the already-
   deleted victims and stamps ``rollback``. The SIGKILL instant is
   ``crash.preempt.partial_evict`` (tools/crashtest.py).

The coordinator also tracks admission ages (the rank's age axis) and the
evicted-then-readmitted churn counter — both gated on preemption being
enabled so a policy-less daemon pays one cached-flag check per pod event
and retains ZERO per-pod state (the PR 11 memory posture).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.pod import Pod, accel_class_of, pod_group_of, priority_of
from ..engine.store import EventType
from ..faults.plan import maybe_crash
from ..utils.lockorder import guard_attrs, make_lock
from ..utils.tracing import vlog
from .spec import PolicyEngine
from .victims import (
    EvictionUnit,
    build_selection_problem,
    compute_gang_deficits,
    rank_eviction_units,
    sequential_victim_select,
)

logger = logging.getLogger(__name__)

# re-check the cached preemption-enabled flag at most every N pod events
# (plus on every policy generation bump) — time-window activation flips
# are observed within one stride without paying an active() per event
_ENABLED_PROBE_STRIDE = 1024


def _next_pow2(n: int, lo: int = 8) -> int:
    v = lo
    while v < n:
        v <<= 1
    return v


@guard_attrs
class PreemptionCoordinator:
    """One per plugin. Thread-safety: the maps below move under the
    coordinator lock, taken only for short map operations — NEVER across
    store calls (store dispatch re-enters :meth:`on_pod_event`, which
    takes the same lock). Counters are single-writer ints read by
    metrics/tests (the ledger stance)."""

    GUARDED_BY = {
        "_admitted_at": "self._lock",
        "_recent_evictions": "self._lock",
        "_last_attempt": "self._lock",
    }

    READMIT_WINDOW_S = 60.0

    def __init__(
        self,
        policy: PolicyEngine,
        kind_controllers: Sequence[Tuple[str, object]],
        store=None,
        gang_ledger=None,
        journal=None,
        faults=None,
        evict_fn: Optional[Callable[[Pod], None]] = None,
        device_manager=None,
    ):
        self.policy = policy
        self.kind_controllers = tuple(kind_controllers)
        self.store = store
        self.gang_ledger = gang_ledger
        # late-bound by the CLI in standalone mode, like the gang ledger's
        self.journal = journal
        self.faults = faults
        self.device_manager = device_manager
        self._evict_fn = evict_fn
        self._lock = make_lock("policy.preempt")
        self._admitted_at: Dict[str, float] = {}  # pod key → monotonic bind time
        self._recent_evictions: Dict[str, float] = {}  # pod key → eviction time
        self._last_attempt: Dict[str, float] = {}  # group key → last cycle time
        self._seq = 0  # preempt-id counter (single-writer: scheduler thread)
        # cached policy gate for the hot pod-event path
        self._enabled_cache = (None, 0, False)  # (generation, countdown, enabled)
        # single-writer counters (metrics/tests read these)
        self.cycles_total = 0
        self.victims_total = 0
        self.infeasible_total = 0
        self.disabled_total = 0
        self.cooldown_skipped_total = 0
        self.rolled_back_total = 0
        self.readmitted_total = 0
        # select-latency histogram (metrics.register_preempt_metrics)
        self.select_hist = None

    # -- pod-event tracking (ages + readmit churn) -------------------------

    def _tracking_enabled(self) -> bool:
        gen = self.policy.generation
        cached_gen, countdown, enabled = self._enabled_cache
        if cached_gen == gen and countdown > 0:
            self._enabled_cache = (cached_gen, countdown - 1, enabled)
            return enabled
        enabled = self.policy.active().preemption_enabled
        self._enabled_cache = (gen, _ENABLED_PROBE_STRIDE, enabled)
        if not enabled:
            # a policy swap back to disabled must not strand per-pod state
            with self._lock:
                if self._admitted_at:
                    self._admitted_at.clear()
        return enabled

    def on_pod_event(self, event) -> None:
        """Store Pod-event hook (runs under the store lock — keep tiny).
        Records admission (bind) times for the rank's age axis and counts
        evicted-then-readmitted churn; both only while the active policy
        enables preemption, so a policy-less daemon retains zero per-pod
        state here."""
        if not self._tracking_enabled():
            return
        pod = event.obj
        now = time.monotonic()
        with self._lock:
            if event.type == EventType.DELETED:
                self._admitted_at.pop(pod.key, None)
                return
            if pod.is_scheduled() and pod.is_not_finished():
                self._admitted_at.setdefault(pod.key, now)
            ts = self._recent_evictions.get(pod.key)
            if ts is not None and event.type == EventType.ADDED:
                self._recent_evictions.pop(pod.key, None)
                if now - ts <= self.READMIT_WINDOW_S:
                    self.readmitted_total += 1

    # -- candidate gathering -----------------------------------------------

    def _gather_units(
        self,
        deficits,
        member_keys: set,
        preemptor_priority: int,
        spec,
    ) -> List[EvictionUnit]:
        units: Dict[str, EvictionUnit] = {}
        seen: set = set()  # (pod_key, kind, throttle_key) contrib dedupe
        now = time.monotonic()
        with self._lock:
            admitted_at = dict(self._admitted_at)
        ctr_by_kind = dict(self.kind_controllers)
        for kind, tkey in sorted({(k, t) for (k, t, _dim) in deficits}):
            ctr = ctr_by_kind[kind]
            try:
                thr = ctr.throttle_by_key(tkey)
            except Exception:
                continue  # deleted under us: its deficit keys stay unmet
            running, _ = ctr.affected_pods(thr)
            for pod in running:
                if pod.key in member_keys:
                    continue
                prio = priority_of(pod)
                if prio + spec.min_priority_gap > preemptor_priority:
                    continue
                group = pod_group_of(pod)
                unit_key = f"gang:{group.key}" if group is not None else pod.key
                unit = units.get(unit_key)
                if unit is None:
                    unit = EvictionUnit(
                        unit_key=unit_key,
                        pods=(),
                        priority=prio,
                        weight=spec.weight_for(accel_class_of(pod)),
                        age_s=-1.0,
                        gang_key=group.key if group is not None else None,
                    )
                    units[unit_key] = unit
                if pod.key not in {p.key for p in unit.pods}:
                    unit.pods = unit.pods + (pod,)
                    unit.priority = max(unit.priority, prio)
                    unit.weight = max(
                        unit.weight, spec.weight_for(accel_class_of(pod))
                    )
                    bound = admitted_at.get(pod.key)
                    age = float("inf") if bound is None else now - bound
                    # a unit ranks as its OLDEST member (age desc)
                    unit.age_s = age if unit.age_s < 0 else max(unit.age_s, age)
                if (pod.key, kind, tkey) not in seen:
                    seen.add((pod.key, kind, tkey))
                    unit.add_pod_contrib(kind, tkey, pod)
        for unit in units.values():
            if unit.age_s < 0:
                unit.age_s = float("inf")
        return rank_eviction_units(units.values())

    # -- selection ----------------------------------------------------------

    def _select(self, deficit: np.ndarray, contrib: np.ndarray, max_victims: int):
        """Kernel when a device manager is wired (padded shapes so tick
        bursts never recompile), host oracle otherwise — identical ranked
        arrays, pinned-equal semantics."""
        use_device = (
            self.device_manager is not None
            and os.environ.get("KT_PREEMPT_DEVICE", "1") != "0"
        )
        if use_device and deficit.size:
            from ..ops.victim_select import victim_select

            n, m = contrib.shape
            np_pad = _next_pow2(max(n, 1))
            mp_pad = _next_pow2(max(m, 1), lo=4)
            contrib_p = np.zeros((np_pad, mp_pad), dtype=np.int64)
            contrib_p[:n, :m] = contrib
            deficit_p = np.zeros(mp_pad, dtype=np.int64)
            deficit_p[:m] = deficit
            try:
                selected, ok, remaining = victim_select(
                    contrib_p, deficit_p, max_victims=max_victims
                )
                sel = np.asarray(selected)[:n]
                return bool(np.asarray(ok)), list(np.nonzero(sel)[0])
            except Exception:
                logger.exception(
                    "victim-select dispatch failed; serving host oracle"
                )
        ok, selected, _remaining = sequential_victim_select(
            deficit, contrib, max_victims=max_victims
        )
        return ok, selected

    # -- the cycle -----------------------------------------------------------

    def preempt_for_gang(
        self, group_key: str, members: Sequence[Pod], mono: Optional[float] = None
    ) -> Dict:
        """One preemption cycle for a capacity-rejected group. Returns a
        report dict; ``report["evicted"]`` > 0 means victims were removed
        and the scheduler should simply park — the deletes fire requeue
        hints and the next cycle admits the group."""
        report = {"evicted": 0, "victims": [], "reason": ""}
        spec = self.policy.active()
        preemptor_priority = max((priority_of(m) for m in members), default=0)
        if not spec.preemption_enabled or preemptor_priority <= 0:
            self.disabled_total += 1
            report["reason"] = "disabled"
            return report
        now = time.monotonic() if mono is None else mono
        with self._lock:
            last = self._last_attempt.get(group_key)
            if (
                last is not None
                and spec.preempt_cooldown_s > 0
                and now - last < spec.preempt_cooldown_s
            ):
                in_cooldown = True
            else:
                in_cooldown = False
                self._last_attempt[group_key] = now
        if in_cooldown:
            self.cooldown_skipped_total += 1
            report["reason"] = "cooldown"
            return report

        t0 = time.monotonic()
        try:
            deficits = compute_gang_deficits(members, self.kind_controllers)
            if deficits is None:
                self.infeasible_total += 1
                report["reason"] = "member-exceeds-threshold"
                return report
            if not deficits:
                report["reason"] = "no-capacity-deficit"
                return report
            member_keys = {m.key for m in members}
            units = self._gather_units(
                deficits, member_keys, preemptor_priority, spec
            )
            if not units:
                self.infeasible_total += 1
                report["reason"] = "no-eligible-victims"
                return report
            _dims, deficit, contrib = build_selection_problem(deficits, units)
            ok, selected = self._select(
                deficit, contrib, spec.max_victims_per_cycle
            )
            if not ok:
                # evicting everything eligible still would not admit the
                # group: evict NOTHING (churn without admission is the
                # thrash the storm scenario gates against)
                self.infeasible_total += 1
                report["reason"] = "insufficient-victims"
                return report
            victims = [units[i] for i in selected]
        finally:
            if self.select_hist is not None:
                self.select_hist.observe_key((), time.monotonic() - t0)

        evicted = self._execute_eviction(group_key, victims, now)
        report["evicted"] = len(evicted)
        report["victims"] = evicted
        report["reason"] = "evicted" if evicted else "eviction-failed"
        return report

    # -- eviction ------------------------------------------------------------

    def _expand_gang_pods(self, unit: EvictionUnit) -> List[Pod]:
        """Whole-gang expansion at eviction time: every running member of
        the victim's gang, not just the ones matched to deficit throttles
        — half-evicted gangs are the exact stranded-capacity shape gang
        admission exists to prevent."""
        if unit.gang_key is None or self.store is None:
            return list(unit.pods)
        namespace = unit.gang_key.partition("/")[0]
        out: Dict[str, Pod] = {p.key: p for p in unit.pods}
        for pod in self.store.list_pods(namespace):
            g = pod_group_of(pod)
            if (
                g is not None
                and g.key == unit.gang_key
                and pod.is_scheduled()
                and pod.is_not_finished()
            ):
                out.setdefault(pod.key, pod)
        return list(out.values())

    def _evict(self, pod: Pod) -> None:
        if self._evict_fn is not None:
            self._evict_fn(pod)
        elif self.store is not None:
            self.store.delete_pod(pod.namespace, pod.name)
        else:
            raise RuntimeError("preemption coordinator has no eviction path")

    def execute_eviction(
        self, preempt_id: str, victim_pods: Sequence[Pod], gang_keys: Sequence[str] = ()
    ) -> List[str]:
        """The journaled eviction sequence, exposed for the crash harness:
        PREEMPT begin (victims + serialized objects) → gang-ledger
        rollbacks → per-victim delete (``crash.preempt.partial_evict``
        fires per delete) → PREEMPT commit. A live exception mid-sequence
        restores the already-deleted victims and stamps rollback — zero
        evictions either way, the GANG contract's mirror."""
        from ..api.serialization import object_to_dict

        victim_pods = list(victim_pods)
        keys = [p.key for p in victim_pods]
        if self.journal is not None:
            self.journal.append_preempt(
                "begin",
                preempt_id,
                victims=keys,
                objects=[object_to_dict(p) for p in victim_pods],
            )
        if self.gang_ledger is not None:
            for gk in gang_keys:
                try:
                    self.gang_ledger.rollback_group(gk, "preempted")
                except Exception:  # pragma: no cover — ledger rollback is total
                    logger.exception("gang %s: preemption rollback failed", gk)
        deleted: List[Pod] = []
        try:
            for pod in victim_pods:
                # the mid-eviction SIGKILL instant the crash matrix drives:
                # some victims deleted, the commit line never lands
                maybe_crash(self.faults, "crash.preempt.partial_evict")
                self._evict(pod)
                deleted.append(pod)
        except Exception:
            logger.exception(
                "preempt %s: eviction failed after %d/%d victim(s); restoring",
                preempt_id, len(deleted), len(victim_pods),
            )
            for pod in deleted:
                try:
                    if self.store is not None:
                        self.store.create_pod(pod)
                except Exception:  # pragma: no cover — restore is best effort
                    logger.exception("preempt %s: restore of %s failed", preempt_id, pod.key)
            if self.journal is not None:
                self.journal.append_preempt("rollback", preempt_id)
            self.rolled_back_total += 1
            return []
        if self.journal is not None:
            self.journal.append_preempt("commit", preempt_id)
        return keys

    def _execute_eviction(self, group_key: str, victims, now: float) -> List[str]:
        self._seq += 1
        preempt_id = f"{group_key}#{self._seq}"
        victim_pods: List[Pod] = []
        gang_keys: List[str] = []
        for unit in victims:
            if unit.gang_key is not None:
                gang_keys.append(unit.gang_key)
                victim_pods.extend(self._expand_gang_pods(unit))
            else:
                victim_pods.extend(unit.pods)
        with self._lock:
            for pod in victim_pods:
                self._recent_evictions[pod.key] = now
            # bound the churn map: entries outside the window carry no signal
            if len(self._recent_evictions) > 4096:
                floor = now - self.READMIT_WINDOW_S
                self._recent_evictions = {
                    k: t for k, t in self._recent_evictions.items() if t >= floor
                }
        evicted = self.execute_eviction(preempt_id, victim_pods, gang_keys)
        if evicted:
            self.cycles_total += 1
            self.victims_total += len(evicted)
            vlog(
                2,
                "preempt %s: evicted %d victim(s) (%d gang(s)) for group %s",
                preempt_id, len(evicted), len(gang_keys), group_key,
            )
        return evicted
