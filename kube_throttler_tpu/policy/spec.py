"""Policy-as-data: per-accelerator-class value weights + preemption knobs.

The scheduling-policy layer Gavel ("Heterogeneity-Aware Cluster Scheduling
Policies for Deep Learning Workloads", PAPERS.md) argues for: which work a
cluster should protect is *data*, not code. A :class:`PolicySpec` carries

- **per-accel-class value weights** (the effective-throughput / value-
  function weights of "Value Function Based Performance Optimization of
  Deep Learning Workloads"): a class with a HIGHER weight is more valuable
  per occupied slot — its throttles' flips are promoted first through the
  workqueue's ``(-priority, seq)`` hi lane, and its pods are evicted LAST
  by victim selection (rank ascends by weight);
- **preemption knobs**: enable flag, per-cycle victim cap, per-group
  cooldown (the anti-thrash floor the preemption-storm scenario gates),
  and the priority gap a victim must sit below the preemptor by;
- **rank-aware placement** toggle ("Rank-Aware Resource Scheduling for
  Tightly-Coupled MPI Workloads"): topology-contiguity scoring in the
  scheduler's tentative gang placement.

Hot swap rides the SAME machinery as temporaryThresholdOverrides
(api/types.py): each spec has RFC3339 ``begin``/``end`` activation
boundaries (empty = open-ended, both inclusive — literally
``TemporaryThresholdOverride.is_active``), the FIRST active spec wins
whole-replacement (no per-field merge ambiguity), and
:meth:`PolicyEngine.set_specs` swaps the whole list atomically at runtime.
With no spec active (or none configured) the engine serves the built-in
default: weights 1.0, preemption off — every consumer degrades to the
pre-policy behavior byte-for-byte.
"""

from __future__ import annotations

from dataclasses import dataclass
from datetime import datetime
from typing import Dict, Iterable, Optional, Sequence, Tuple

from ..api.types import TemporaryThresholdOverride
from ..utils.clock import Clock, RealClock
from ..utils.lockorder import guard_attrs, make_lock

# hi-lane promotion priorities are ints; weights are small floats — one
# fixed scale maps them losslessly for any weight expressed in hundredths
PROMOTION_PRIORITY_SCALE = 100


@dataclass(frozen=True)
class ClassWeight:
    """One accelerator class's value weight (first-wins within a spec,
    like the override merge). ``weight`` is relative: only order matters
    to victim ranking; magnitude feeds the flip promotion priority."""

    accel_class: str = ""
    weight: float = 1.0


@dataclass(frozen=True)
class PolicySpec:
    """One policy window. ``begin``/``end`` are RFC3339 activation
    boundaries with temporaryThresholdOverrides semantics (empty =
    open-ended; active iff begin ≤ now ∧ (end == "" ∨ now ≤ end))."""

    name: str = "default"
    begin: str = ""
    end: str = ""
    class_weights: Tuple[ClassWeight, ...] = ()
    default_weight: float = 1.0
    preemption_enabled: bool = False
    max_victims_per_cycle: int = 32
    preempt_cooldown_s: float = 0.0
    min_priority_gap: int = 1
    rank_aware_placement: bool = True

    def is_active(self, now: datetime) -> bool:
        """The exact temporaryThresholdOverrides window predicate —
        delegated, not reimplemented, so the two mechanisms can never
        drift (both boundaries inclusive, RFC3339ParseError on bad
        input)."""
        return TemporaryThresholdOverride(begin=self.begin, end=self.end).is_active(
            now
        )

    def weight_for(self, accel_class: Optional[str]) -> float:
        """First class_weights entry naming ``accel_class`` (first wins,
        like the override merge), else the default weight. Pods with no
        class use the default too."""
        if accel_class:
            for entry in self.class_weights:
                if entry.accel_class == accel_class:
                    return float(entry.weight)
        return float(self.default_weight)

    def promotion_priority(self, accel_classes: Iterable[str]) -> int:
        """Hi-lane priority for a throttle declaring ``accel_classes``:
        the max class weight above the default, scaled to an int. A
        throttle with no class entries (or classes the policy does not
        weight above default) promotes at 0 — the original FIFO lane."""
        best = 0.0
        for cls in accel_classes:
            w = self.weight_for(cls) - float(self.default_weight)
            if w > best:
                best = w
        return int(round(best * PROMOTION_PRIORITY_SCALE))


DEFAULT_POLICY = PolicySpec()


@guard_attrs
class PolicyEngine:
    """The hot-swappable policy holder every consumer reads through.

    Consumers (victim selection, the controllers' flip promotion, the
    scheduler's placement scoring) call :meth:`active` per decision — the
    spec list is tiny and the is_active probes are string-empty checks in
    the common case, so there is no caching layer to invalidate on a
    swap. ``generation`` bumps per :meth:`set_specs` for observability."""

    GUARDED_BY = {"_specs": "self._lock", "generation": "self._lock"}

    def __init__(
        self,
        specs: Sequence[PolicySpec] = (),
        clock: Optional[Clock] = None,
    ):
        self._lock = make_lock("policy.engine")
        self._specs: Tuple[PolicySpec, ...] = tuple(specs)
        self._clock = clock or RealClock()
        self.generation = 0

    def set_specs(self, specs: Sequence[PolicySpec]) -> int:
        """Atomically replace the whole spec list (the hot swap). Returns
        the new generation."""
        with self._lock:
            self._specs = tuple(specs)
            self.generation += 1
            return self.generation

    def specs(self) -> Tuple[PolicySpec, ...]:
        with self._lock:
            return self._specs

    def active(self, now: Optional[datetime] = None) -> PolicySpec:
        """The FIRST active spec (first-wins whole-replacement, the
        override discipline), else the built-in default. A spec whose
        boundary fails to parse is skipped — a config typo must not
        disable policy resolution for the specs after it."""
        now = now or self._clock.now()
        for spec in self.specs():
            try:
                if spec.is_active(now):
                    return spec
            except ValueError:
                continue
        return DEFAULT_POLICY


# -- config decoding (plugin args / hot-swap payloads) -----------------------


def policy_spec_from_dict(d: Dict) -> PolicySpec:
    """Decode one camelCase policy entry (the plugin-args / hot-swap wire
    form). Unknown keys are rejected — a policy written by a newer schema
    must fail loudly, not silently drop a knob."""
    d = dict(d)
    weights = []
    for w in d.pop("classWeights", ()) or ():
        w = dict(w)
        cls = str(w.pop("accelClass", "") or "")
        weight = float(w.pop("weight", 1.0))
        if w:
            raise ValueError(f"unknown classWeights keys: {sorted(w)}")
        if not cls:
            raise ValueError("classWeights entries need a non-empty accelClass")
        if weight < 0:
            raise ValueError(f"classWeights weight must be >= 0: {weight!r}")
        weights.append(ClassWeight(accel_class=cls, weight=weight))
    spec = PolicySpec(
        name=str(d.pop("name", "default") or "default"),
        begin=str(d.pop("begin", "") or ""),
        end=str(d.pop("end", "") or ""),
        class_weights=tuple(weights),
        default_weight=float(d.pop("defaultWeight", 1.0)),
        preemption_enabled=bool(d.pop("preemptionEnabled", False)),
        max_victims_per_cycle=int(d.pop("maxVictimsPerCycle", 32)),
        preempt_cooldown_s=float(d.pop("preemptCooldownSeconds", 0.0)),
        min_priority_gap=int(d.pop("minPriorityGap", 1)),
        rank_aware_placement=bool(d.pop("rankAwarePlacement", True)),
    )
    if d:
        raise ValueError(f"unknown policy keys: {sorted(d)}")
    if spec.max_victims_per_cycle <= 0:
        raise ValueError(
            f"maxVictimsPerCycle must be positive: {spec.max_victims_per_cycle!r}"
        )
    if spec.preempt_cooldown_s < 0:
        raise ValueError(
            f"preemptCooldownSeconds must be >= 0: {spec.preempt_cooldown_s!r}"
        )
    if spec.min_priority_gap < 0:
        raise ValueError(f"minPriorityGap must be >= 0: {spec.min_priority_gap!r}")
    if spec.default_weight < 0:
        raise ValueError(f"defaultWeight must be >= 0: {spec.default_weight!r}")
    return spec


def policy_specs_from_config(raw) -> Tuple[PolicySpec, ...]:
    """Decode the plugin-args ``policies`` list (or a single dict)."""
    if raw is None:
        return ()
    if isinstance(raw, dict):
        raw = [raw]
    return tuple(policy_spec_from_dict(d) for d in raw)
