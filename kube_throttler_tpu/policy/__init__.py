"""Preemption & policy engine (docs/policy.md).

``spec``    — PolicySpec / PolicyEngine: policy-as-data value weights and
              preemption knobs, hot-swappable with the same RFC3339
              activation-window + first-wins machinery as
              temporaryThresholdOverrides.
``victims`` — deficit derivation + eviction-unit ranking + the
              ``sequential_victim_select`` host oracle the batched kernel
              (ops/victim_select.py) is pinned to.
``preempt`` — PreemptionCoordinator: journaled (PREEMPT begin/commit/
              rollback), gang-atomic victim eviction driven by the
              scheduler when a high-priority group cannot fit.
"""

from .spec import (  # noqa: F401
    ClassWeight,
    PolicyEngine,
    PolicySpec,
    policy_spec_from_dict,
    policy_specs_from_config,
)
from .victims import (  # noqa: F401
    EvictionUnit,
    build_selection_problem,
    compute_gang_deficits,
    rank_eviction_units,
    sequential_victim_select,
)
from .preempt import PreemptionCoordinator  # noqa: F401
