"""Victim selection: deficits, eviction units, and the sequential oracle.

Semantics are DERIVED from the admission inequality, not invented. A gang
that ``pre_filter_gang`` rejected for capacity is blocked on every
(throttle, dimension) where

    used + reserved + group_total > threshold          (the overflow form)

so the capacity that must be freed — the **deficit** — is exactly
``used + reserved + group_total - threshold`` on each such pair
(:func:`compute_gang_deficits`; thresholds are the accel-class-resolved
effective thresholds, the same resolution order as the gang kernel). A
pod's eviction frees its contribution to ``used`` on every throttle it
matches, so victim selection is: walk candidates in rank order and keep
the ones that still reduce an unmet deficit, until every deficit is met.

**Rank order** (policy weight asc, priority asc, age desc): cheapest work
first — lowest value-weight class, then lowest priority, then the OLDEST
among ties (it has had its run; a deterministic tie-break on the unit key
closes the order totally). :func:`rank_eviction_units` implements it.

**Eviction units**: a victim that belongs to a gang drags its WHOLE gang —
admitting half-evicted gangs would recreate exactly the stranded-capacity
problem gang admission exists to prevent — so candidates are grouped into
units (single pod, or every running member of one gang) and selection
operates on units.

:func:`sequential_victim_select` is the per-candidate ORACLE: a plain
Python greedy walk over the flattened deficit vector. The batched kernel
(ops/victim_select.py) computes the SAME walk as one ``lax.scan`` dispatch
over the ranked contribution matrix; the seeded equivalence sweep and the
hypothesis twin (tests/test_policy.py, tests/test_victim_property.py) pin
kernel ≡ oracle on both the verdict and the selected set.

All quantities are integer milli-units (``_milli_ceil`` — conservative
ceiling for sub-milli fractions, identical on both paths) so kernel and
oracle do exact integer arithmetic on identical arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from fractions import Fraction
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.pod import Pod, accel_class_of
from ..api.types import (
    ResourceAmount,
    effective_threshold,
    resource_amount_of_pod,
)

# deficit / contribution key: (kind, throttle_key, dim) where dim is the
# reserved name "pod" (count) or a resource name (milli-units)
DimKey = Tuple[str, str, str]
COUNT_DIM = "pod"


def _milli_ceil(value: Fraction) -> int:
    """Ceiling milli-units of a Fraction — exact for milli-precision
    quantities (the normal case), conservatively rounded UP otherwise so
    a freed sub-milli sliver is never counted as covering a deficit it
    does not."""
    value = Fraction(value)
    return -((-value.numerator * 1000) // value.denominator)


def _amount_milli(amount: ResourceAmount) -> Tuple[int, Dict[str, int]]:
    counts = amount.resource_counts or 0
    reqs = {
        rn: _milli_ceil(q) for rn, q in (amount.resource_requests or {}).items()
    }
    return counts, reqs


def compute_gang_deficits(
    members: Sequence[Pod],
    kind_controllers: Sequence[Tuple[str, object]],
) -> Optional[Dict[DimKey, int]]:
    """Per-(kind, throttle, dim) capacity that must be freed before the
    group fits: ``used + reserved + group_total - threshold`` wherever
    positive, over every throttle any member matches. Thresholds are
    accel-class-resolved (the group's class, like the gang kernel);
    request dims count only when some matched member requests them
    non-zero (the ``is_throttled_for`` gate). Returns None when the group
    is infeasible regardless of eviction — some member ALONE exceeds a
    threshold (step 1), which no victim set can fix. An empty dict means
    nothing needs freeing (the block was not capacity-shaped)."""
    accel = next((c for c in map(accel_class_of, members) if c), None)
    deficits: Dict[DimKey, int] = {}
    for kind, ctr in kind_controllers:
        # union of matched throttles with per-throttle matched members
        matched: Dict[str, Tuple[object, List[Pod]]] = {}
        for pod in members:
            for thr in ctr.affected_throttles(pod):
                entry = matched.get(thr.key)
                if entry is None:
                    matched[thr.key] = (thr, [pod])
                else:
                    entry[1].append(pod)
        for tkey, (thr, tpods) in matched.items():
            threshold = thr.spec.accel_threshold_for(accel)
            if threshold is None:
                threshold = effective_threshold(thr.spec.threshold, thr.status)
            thr_cnt, thr_req = (
                threshold.resource_counts,
                threshold.resource_requests or {},
            )
            # step-1 screen: a member alone over the threshold is
            # un-preemptable — nothing freed can admit it
            for pod in tpods:
                pa = resource_amount_of_pod(pod)
                if threshold.is_throttled(pa, False).is_throttled_for(pod):
                    return None
            used_cnt, used_req = _amount_milli(thr.status.used)
            res_cnt, res_req = _amount_milli(
                ctr.cache.reserved_resource_amount(tkey)[0]
            )
            g_cnt = len(tpods)
            g_req: Dict[str, int] = {}
            for pod in tpods:
                _, preq = _amount_milli(resource_amount_of_pod(pod))
                for rn, m in preq.items():
                    g_req[rn] = g_req.get(rn, 0) + m
            if thr_cnt is not None:
                need = used_cnt + res_cnt + g_cnt - int(thr_cnt)
                if need > 0:
                    deficits[(kind, tkey, COUNT_DIM)] = need
            for rn, tq in thr_req.items():
                g_rn = g_req.get(rn, 0)
                if g_rn <= 0:
                    continue  # no member requests it non-zero: never blocks
                need = (
                    used_req.get(rn, 0) + res_req.get(rn, 0) + g_rn
                    - _milli_ceil(tq)
                )
                if need > 0:
                    deficits[(kind, tkey, rn)] = need
    return deficits


@dataclass
class EvictionUnit:
    """One atomically-evictable candidate: a single running pod, or every
    running member of one gang (whole gangs evict together — the
    all-or-nothing contract runs both ways). ``contrib`` maps
    (kind, throttle_key) to the unit's freed amounts there."""

    unit_key: str
    pods: Tuple[Pod, ...]
    priority: int = 0
    weight: float = 1.0
    age_s: float = float("inf")  # unknown admission time ranks oldest
    gang_key: Optional[str] = None
    contrib: Dict[Tuple[str, str], Tuple[int, Dict[str, int]]] = field(
        default_factory=dict
    )

    def add_pod_contrib(self, kind: str, throttle_key: str, pod: Pod) -> None:
        cnt, req = _amount_milli(resource_amount_of_pod(pod))
        cur_cnt, cur_req = self.contrib.get((kind, throttle_key), (0, {}))
        merged = dict(cur_req)
        for rn, m in req.items():
            merged[rn] = merged.get(rn, 0) + m
        self.contrib[(kind, throttle_key)] = (cur_cnt + cnt, merged)


def rank_eviction_units(units: Sequence[EvictionUnit]) -> List[EvictionUnit]:
    """(policy weight asc, priority asc, age desc), unit-key tie-break —
    the total, deterministic victim order both selection paths walk."""
    return sorted(units, key=lambda u: (u.weight, u.priority, -u.age_s, u.unit_key))


def build_selection_problem(
    deficits: Dict[DimKey, int],
    units: Sequence[EvictionUnit],
) -> Tuple[List[DimKey], np.ndarray, np.ndarray]:
    """Flatten deficits + ranked-unit contributions into the arrays BOTH
    selection paths consume: ``(dims, deficit int64[M], contrib
    int64[N, M])``. Dims are sorted for determinism; ``units`` must
    already be in rank order (the row order IS the selection order)."""
    dims = sorted(deficits)
    deficit = np.array([deficits[d] for d in dims], dtype=np.int64)
    contrib = np.zeros((len(units), len(dims)), dtype=np.int64)
    dim_index = {d: j for j, d in enumerate(dims)}
    for i, unit in enumerate(units):
        for (kind, tkey), (cnt, req) in unit.contrib.items():
            j = dim_index.get((kind, tkey, COUNT_DIM))
            if j is not None:
                contrib[i, j] += cnt
            for rn, m in req.items():
                j = dim_index.get((kind, tkey, rn))
                if j is not None:
                    contrib[i, j] += m
    return dims, deficit, contrib


def sequential_victim_select(
    deficit: np.ndarray,
    contrib: np.ndarray,
    max_victims: int = 0,
) -> Tuple[bool, List[int], np.ndarray]:
    """The per-candidate ORACLE the batched kernel must equal: walk the
    ranked rows in order; select a row iff it contributes to some still-
    positive deficit (and the victim cap is not exhausted); subtract its
    whole contribution. Returns ``(ok, selected row indices, remaining)``
    — ``ok`` iff every deficit reached ≤ 0. ``max_victims`` ≤ 0 means
    uncapped. Pure; never mutates its inputs."""
    remaining = np.array(deficit, dtype=np.int64, copy=True)
    selected: List[int] = []
    for i in range(contrib.shape[0]):
        if np.all(remaining <= 0):
            break
        if max_victims > 0 and len(selected) >= max_victims:
            break
        row = contrib[i]
        if np.any((row > 0) & (remaining > 0)):
            remaining -= row
            selected.append(i)
    return bool(np.all(remaining <= 0)), selected, remaining
