"""Ring-rotation full sweep — the ring-attention/context-parallel pattern
mapped onto the (pods × throttles) check matrix.

In `sharded.py` the mesh is 2D and each device holds a [P/dp, T/tp] mask
tile; the cross-device traffic is two `psum`s. This module is the
alternative decomposition for when the **throttle-side state dominates
memory** (huge T×R threshold/override/used tensors, the analog of long-KV
in ring attention): a 1D ring where

- every device *permanently owns* one T/n throttle tile (thresholds,
  override schedule, reservations, used accumulators) and its mask columns
  ``mask[:, T_loc]`` — throttle state never moves;
- pod blocks ([P/n, R] requests + validity) *rotate* around the ring via
  `ppermute`, exactly like KV blocks in ring attention — hop s delivers the
  block owned by device (me − s) mod n;
- sweep 1 accumulates each tile's ``used`` from every visiting pod block
  (after n hops every tile has the full sum — a ring all-reduce that never
  materializes a global [P,T] or [T,R] tensor anywhere);
- thresholds + throttled flags are then computed tile-locally;
- sweep 2 rotates the blocks again, now carrying [P/n, 4] verdict-count
  accumulators with them; each device classifies the visiting block against
  its tile, and after n hops the counts arrive home complete.

Per-hop traffic is O(P/n · R) — independent of T — and all hops are
neighbor `ppermute`s that ride ICI. Output layout matches
``sharded_full_update`` so callers can swap decompositions freely.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jaxcompat import shard_map

from ..ops.aggregate import throttled_flags
from ..ops.check import CHECK_ACTIVE, CHECK_INSUFFICIENT, CHECK_POD_EXCEEDS, _classify
from ..ops.overrides import OverrideSchedule, calculate_thresholds
from ..ops.schema import PodBatch, ThrottleState

AXIS = "ring"


def ring_full_update(mesh: Mesh, *, on_equal: bool = False, step3_on_equal: bool = True):
    """Compile the full tick over a 1D ("ring",) mesh.

    Input layout (per-device shards in parentheses):
      pods, counted      — sharded on the ring        ([P/n], [P/n,R])
      mask               — [P, T] sharded on axis 1   ([P, T/n] columns)
      sched, reservations, thr_valid — sharded on the ring ([T/n, ...])
      now_ns             — replicated
    Outputs mirror ``sharded_full_update``: per-pod arrays ring-sharded,
    per-throttle arrays ring-sharded.
    """
    assert mesh.axis_names == (AXIS,), f"ring mesh must have a single '{AXIS}' axis"
    n = mesh.devices.size
    perm = [(i, (i + 1) % n) for i in range(n)]

    def _rotate(tree):
        return jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, AXIS, perm), tree
        )

    def _sweep(sched, pods, mask_cols, counted,
               res_cnt, res_cnt_p, res_req, res_req_p, thr_valid, now_ns):
        me = jax.lax.axis_index(AXIS)
        p_loc = pods.valid.shape[0]
        t_loc = mask_cols.shape[1]
        r = pods.req.shape[1]

        # ---- sweep 1: ring all-reduce of used into the resident tile
        used_cnt = jnp.zeros(t_loc, dtype=jnp.int64)
        used_req = jnp.zeros((t_loc, r), dtype=jnp.int64)
        contrib = jnp.zeros((t_loc, r), dtype=jnp.int32)
        blk = (pods, counted)
        for s in range(n):
            origin = (me - s) % n
            start = (origin * p_loc).astype(jnp.int32)
            m = jax.lax.dynamic_slice(mask_cols, (start, jnp.int32(0)), (p_loc, t_loc))
            bpods, bcounted = blk
            mm = m & bcounted[:, None]  # [P/n, T/n]
            used_cnt = used_cnt + jnp.sum(mm, axis=0, dtype=jnp.int64)
            mb = mm[:, :, None]
            used_req = used_req + jnp.sum(
                jnp.where(mb, bpods.req[:, None, :], 0), axis=0
            )
            contrib = contrib + jnp.sum(
                (mb & bpods.req_present[:, None, :]).astype(jnp.int32), axis=0
            )
            if s < n - 1:  # the n-th rotate would only ship blocks home
                blk = _rotate(blk)

        used_cnt_present = used_cnt > 0
        used_req_present = contrib > 0

        # ---- tile-local: thresholds at now, reconcile's throttled flags
        thr_cnt, thr_cnt_present, thr_req, thr_req_present = calculate_thresholds(
            sched, now_ns
        )
        st_cnt, st_req, st_req_flag_present = throttled_flags(
            thr_cnt, thr_cnt_present, thr_req, thr_req_present,
            used_cnt, used_cnt_present, used_req, used_req_present,
        )
        state = ThrottleState(
            valid=thr_valid,
            thr_cnt=thr_cnt, thr_cnt_present=thr_cnt_present,
            thr_req=thr_req, thr_req_present=thr_req_present,
            used_cnt=used_cnt, used_cnt_present=used_cnt_present,
            used_req=used_req, used_req_present=used_req_present,
            res_cnt=res_cnt, res_cnt_present=res_cnt_p,
            res_req=res_req, res_req_present=res_req_p,
            st_cnt_throttled=st_cnt, st_req_throttled=st_req,
            st_req_flag_present=st_req_flag_present,
        )

        # ---- sweep 2: rotate blocks with their verdict-count accumulators
        # (sweep 1 left the traveling blocks one hop short of home; start
        # from the locally-held originals instead of shipping them back)
        counts = jnp.zeros((p_loc, 4), dtype=jnp.int32)
        blk2 = (pods, counts)
        for s in range(n):
            origin = (me - s) % n
            start = (origin * p_loc).astype(jnp.int32)
            m = jax.lax.dynamic_slice(mask_cols, (start, jnp.int32(0)), (p_loc, t_loc))
            bpods, bcounts = blk2
            statuses = _classify(state, bpods, m, on_equal, step3_on_equal)  # int8[P/n,T/n]
            bcounts = bcounts + jnp.stack(
                [jnp.sum(statuses == c, axis=1, dtype=jnp.int32) for c in range(4)],
                axis=1,
            )
            blk2 = _rotate((bpods, bcounts))

        _, counts = blk2  # home, complete over all tiles
        schedulable = (
            counts[:, CHECK_ACTIVE]
            + counts[:, CHECK_INSUFFICIENT]
            + counts[:, CHECK_POD_EXCEEDS]
        ) == 0
        return counts, schedulable, used_cnt, used_req, st_cnt, st_req

    from .sharded import uniform_pods_specs, uniform_sched_specs

    ring = P(AXIS)
    sched_specs = uniform_sched_specs(ring)
    pods_specs = uniform_pods_specs(ring)

    mapped = shard_map(
        _sweep,
        mesh=mesh,
        in_specs=(
            sched_specs, pods_specs, P(None, AXIS), ring,
            ring, ring, ring, ring, ring, P(),
        ),
        out_specs=(ring, ring, ring, ring, ring, ring),
    )
    return jax.jit(mapped)


def make_ring_mesh(n_devices: int | None = None) -> Mesh:
    """1D ("ring",) mesh over the first n devices."""
    import numpy as np

    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(np.array(devices[:n]), axis_names=(AXIS,))
