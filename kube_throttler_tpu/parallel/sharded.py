"""The full update step — single-device and mesh-sharded variants.

One "step" is a complete system tick, the analog of a training step for this
framework: resolve every throttle's time-varying threshold, re-aggregate
``used`` from the pod set, recompute throttled flags, and classify every
pod × throttle admission cell — i.e. a full reconcile pass fused with a full
PreFilter sweep.

``full_update_step`` is the pure single-shard program. ``sharded_full_update``
wraps it in ``shard_map`` over a ("pods","throttles") mesh: each device owns
a [P/dp, T/tp] tile of the mask, a P/dp slice of pods, and a T/tp slice of
throttle state; the only cross-device traffic is

- ``psum`` over the **pods** axis of the used-aggregation partials
  (each pod shard contributes its masked sums for the local throttle tile);
- ``psum`` over the **throttles** axis of per-pod class counts
  (each throttle tile contributes its verdict counts for the local pods).

Both are single-hop ICI all-reduces of [T_loc,R] / [P_loc,4] tiles — no
[P,T] global tensor ever exists on any device.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jaxcompat import shard_map

from dataclasses import fields as _dc_fields

from ..ops.aggregate import aggregate_used, throttled_flags
from ..ops.check import CHECK_ACTIVE, CHECK_INSUFFICIENT, CHECK_POD_EXCEEDS, _classify
from ..ops.overrides import OverrideSchedule, calculate_thresholds
from ..ops.schema import PodBatch, ThrottleState


def uniform_sched_specs(spec) -> OverrideSchedule:
    """OverrideSchedule spec pytree with every leaf on one PartitionSpec.
    Shared by all mesh wrappers (2D dense, 2D sparse, ring) so adding a
    field to OverrideSchedule is a one-place change instead of a silent
    shard_map pytree mismatch in whichever copy was forgotten."""
    return OverrideSchedule(**{f.name: spec for f in _dc_fields(OverrideSchedule)})


def uniform_pods_specs(spec) -> PodBatch:
    """PodBatch spec pytree with every leaf on one PartitionSpec."""
    return PodBatch(**{f.name: spec for f in _dc_fields(PodBatch)})


def full_update_step(
    sched: OverrideSchedule,
    pods: PodBatch,
    mask: jnp.ndarray,  # bool[P,T]
    counted: jnp.ndarray,  # bool[P] — running pods that count into used
    res_cnt: jnp.ndarray,
    res_cnt_present: jnp.ndarray,
    res_req: jnp.ndarray,
    res_req_present: jnp.ndarray,
    thr_valid: jnp.ndarray,  # bool[T]
    now_ns: jnp.ndarray,
    *,
    on_equal: bool = False,
    step3_on_equal: bool = True,
    pod_axis: str | None = None,
    thr_axis: str | None = None,
):
    """One full tick. With ``pod_axis``/``thr_axis`` set (inside shard_map),
    partial reductions are psum-ed across the mesh.

    Returns (counts int32[P,4], schedulable bool[P],
             used_cnt int64[T], used_req int64[T,R],
             st_cnt bool[T], st_req bool[T,R]).
    """
    # 1. time-varying thresholds (local throttle tile)
    thr_cnt, thr_cnt_present, thr_req, thr_req_present = calculate_thresholds(
        sched, now_ns
    )

    # 2. used aggregation: local pod-shard partial, then sum across pod shards
    used_cnt, used_req, contrib = aggregate_used(pods, mask, counted)
    if pod_axis is not None:
        used_cnt = jax.lax.psum(used_cnt, pod_axis)
        used_req = jax.lax.psum(used_req, pod_axis)
        contrib = jax.lax.psum(contrib, pod_axis)
    used_cnt_present = used_cnt > 0
    used_req_present = contrib > 0

    # 3. status.throttled flags (reconcile's onEqual=True compare)
    st_cnt, st_req, st_req_flag_present = throttled_flags(
        thr_cnt, thr_cnt_present, thr_req, thr_req_present,
        used_cnt, used_cnt_present, used_req, used_req_present,
    )

    # 4. admission classification against the fresh state
    state = ThrottleState(
        valid=thr_valid,
        thr_cnt=thr_cnt,
        thr_cnt_present=thr_cnt_present,
        thr_req=thr_req,
        thr_req_present=thr_req_present,
        used_cnt=used_cnt,
        used_cnt_present=used_cnt_present,
        used_req=used_req,
        used_req_present=used_req_present,
        res_cnt=res_cnt,
        res_cnt_present=res_cnt_present,
        res_req=res_req,
        res_req_present=res_req_present,
        st_cnt_throttled=st_cnt,
        st_req_throttled=st_req,
        st_req_flag_present=st_req_flag_present,
    )
    statuses = _classify(state, pods, mask, on_equal, step3_on_equal)  # int8[P,T]

    # 5. per-pod verdicts: count classes over the local throttle tile, then
    # sum across throttle shards
    counts = jnp.stack(
        [jnp.sum(statuses == c, axis=1, dtype=jnp.int32) for c in range(4)], axis=1
    )
    if thr_axis is not None:
        counts = jax.lax.psum(counts, thr_axis)
    schedulable = (
        counts[:, CHECK_ACTIVE] + counts[:, CHECK_INSUFFICIENT] + counts[:, CHECK_POD_EXCEEDS]
    ) == 0

    return counts, schedulable, used_cnt, used_req, st_cnt, st_req


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal", "pod_axis", "thr_axis"))
def full_update_step_gather(
    sched: OverrideSchedule,
    pods: PodBatch,
    cols: jnp.ndarray,  # int32[P,K] matched throttle cols per pod, -1 pads
    counted: jnp.ndarray,  # bool[P]
    res_cnt: jnp.ndarray,
    res_cnt_present: jnp.ndarray,
    res_req: jnp.ndarray,
    res_req_present: jnp.ndarray,
    thr_valid: jnp.ndarray,  # bool[T]
    now_ns: jnp.ndarray,
    *,
    on_equal: bool = False,
    step3_on_equal: bool = True,
    pod_axis: str | None = None,
    thr_axis: str | None = None,
):
    """The SPARSE tick: same fused reconcile+classify as
    ``full_update_step`` but driven by the [P,K] matched-cols companion
    instead of the dense [P,T] mask — O(P·K·R) work and no [P,T] tensor
    anywhere (neither compute nor transfer). On real clusters K ≪ T, so
    this is the production serving shape on one chip AND on a mesh (see
    ``sharded_full_update_gather``).

    Sharded form (``pod_axis``/``thr_axis`` set, inside shard_map): pods
    and their cols rows are sharded over "pods"; cols carry GLOBAL col
    ids, and each "throttles"-axis shard rebases them into its local tile
    (out-of-tile slots → -1, exactly the ownership-partition trick of
    ``sharded_apply_deltas``). used partials psum over the pods axis;
    per-pod class counts psum over the throttles axis (each global col has
    exactly one owning tile, so every slot is counted once). Identical
    comm shape to the dense ``sharded_full_update`` — two single-hop ICI
    all-reduces — with O(P·K) tiles instead of O(P·T).

    used-aggregation is an exact int64 scatter-add over the flat [P·K]
    (col, contribution) pairs (padded/uncounted/out-of-tile slots route to
    an out-of-range index and drop); classification is
    ``check_pods_gather`` against the freshly derived state. Returns the
    same tuple as ``full_update_step``: (counts int32[P,4],
    schedulable bool[P], used_cnt int64[T], used_req int64[T,R],
    st_cnt bool[T], st_req bool[T,R])."""
    from ..ops.check import check_pods_gather

    T = thr_valid.shape[0]
    P_, K = cols.shape
    R = pods.req.shape[1]

    if thr_axis is not None:
        # rebase global col ids into this shard's tile; foreign slots pad
        offset = jax.lax.axis_index(thr_axis) * T
        local = (cols >= offset) & (cols < offset + T)
        cols = jnp.where(local, cols - offset, jnp.int32(-1))

    thr_cnt, thr_cnt_present, thr_req, thr_req_present = calculate_thresholds(
        sched, now_ns
    )

    slot = (cols >= 0) & (counted & pods.valid)[:, None]  # [P,K]
    tgt = jnp.where(slot, cols, T).reshape(-1)  # T = out of range ⇒ dropped
    used_cnt = jnp.zeros(T, dtype=jnp.int64).at[tgt].add(1, mode="drop")
    # R-LEADING scatter operands: the naive [P·K, R] update-row matrix
    # tile-pads R=8 → 128 lanes on TPU — a 16× expansion (8.6G at the
    # 131072-pod ladder cap), the same OOM class the gather kernels hit
    # (see ops/check.py _gather_statuses). With [R, P·K] rows scattering
    # into an [R, T] accumulator the huge P·K count rides the un-padded
    # lane dim and R the sublane dim; transposing back costs one [T,R].
    req_rows = jnp.broadcast_to(pods.req.T[:, :, None], (R, P_, K)).reshape(R, P_ * K)
    pres_rows = jnp.broadcast_to(
        pods.req_present.T[:, :, None], (R, P_, K)
    ).reshape(R, P_ * K)
    used_req = (
        jnp.zeros((R, T), dtype=jnp.int64).at[:, tgt].add(req_rows, mode="drop").T
    )
    contrib = (
        jnp.zeros((R, T), dtype=jnp.int32)
        .at[:, tgt]
        .add(pres_rows.astype(jnp.int32), mode="drop")
        .T
    )
    if pod_axis is not None:
        used_cnt = jax.lax.psum(used_cnt, pod_axis)
        used_req = jax.lax.psum(used_req, pod_axis)
        contrib = jax.lax.psum(contrib, pod_axis)
    used_cnt_present = used_cnt > 0
    used_req_present = contrib > 0

    st_cnt, st_req, st_req_flag_present = throttled_flags(
        thr_cnt, thr_cnt_present, thr_req, thr_req_present,
        used_cnt, used_cnt_present, used_req, used_req_present,
    )

    state = ThrottleState(
        valid=thr_valid,
        thr_cnt=thr_cnt,
        thr_cnt_present=thr_cnt_present,
        thr_req=thr_req,
        thr_req_present=thr_req_present,
        used_cnt=used_cnt,
        used_cnt_present=used_cnt_present,
        used_req=used_req,
        used_req_present=used_req_present,
        res_cnt=res_cnt,
        res_cnt_present=res_cnt_present,
        res_req=res_req,
        res_req_present=res_req_present,
        st_cnt_throttled=st_cnt,
        st_req_throttled=st_req,
        st_req_flag_present=st_req_flag_present,
    )
    counts, schedulable = check_pods_gather(
        state, pods, cols, on_equal=on_equal, step3_on_equal=step3_on_equal
    )
    if thr_axis is not None:
        # local counts cover only this tile's cols; sum across tiles and
        # re-derive the gate from the GLOBAL counts (mirrors the dense
        # full_update_step's step 5)
        counts = jax.lax.psum(counts, thr_axis)
        schedulable = (
            counts[:, CHECK_ACTIVE]
            + counts[:, CHECK_INSUFFICIENT]
            + counts[:, CHECK_POD_EXCEEDS]
        ) == 0
    return counts, schedulable, used_cnt, used_req, st_cnt, st_req


def sharded_full_update_gather(
    mesh: Mesh, *, on_equal: bool = False, step3_on_equal: bool = True
):
    """Compile the SPARSE full step over a ("pods","throttles") mesh via
    shard_map — the multi-chip serving path without any [P,T] tensor.

    Input layout: pod-side arrays AND the [P,K] global-id cols sharded on
    "pods" (cols replicate over the throttles axis; each shard rebases
    into its tile), throttle-side arrays on "throttles". Outputs: per-pod
    on "pods", per-throttle on "throttles". Comm shape identical to the
    dense ``sharded_full_update`` (two psums); per-device compute and
    memory drop from O(P·T/(dp·tp)) to O(P·K/dp)."""
    pod_spec = P("pods")
    thr_spec = P("throttles")

    sched_specs = uniform_sched_specs(thr_spec)
    pods_specs = uniform_pods_specs(pod_spec)

    def _step(sched, pods, cols, counted, res_cnt, res_cnt_p, res_req, res_req_p, thr_valid, now_ns):
        # the raw body (like the dense wrapper calls unjitted
        # full_update_step): shard_map provides the axis context
        return full_update_step_gather.__wrapped__(
            sched, pods, cols, counted,
            res_cnt, res_cnt_p, res_req, res_req_p, thr_valid, now_ns,
            on_equal=on_equal, step3_on_equal=step3_on_equal,
            pod_axis="pods", thr_axis="throttles",
        )

    mapped = shard_map(
        _step,
        mesh=mesh,
        in_specs=(
            sched_specs, pods_specs, pod_spec, pod_spec,
            thr_spec, thr_spec, thr_spec, thr_spec, thr_spec, P(),
        ),
        out_specs=(pod_spec, pod_spec, thr_spec, thr_spec, thr_spec, thr_spec),
    )
    return jax.jit(mapped)


def sharded_apply_deltas(mesh: Mesh):
    """Streaming reconcile (BASELINE cfg5) over a throttle-sharded mesh.

    The used-aggregates live tiled over the mesh's ``throttles`` axis —
    each device owns agg rows [T/tp] — and a batch of pod-churn deltas
    (global throttle ids) is REPLICATED to every device: each shard
    rebases ids into its tile (global id − tile offset) and scatter-adds
    only the rows it owns, dropping the rest (``mode="drop"``). No
    collective is needed at all — scatter targets partition exactly by
    ownership, so the update is embarrassingly parallel across shards;
    reads (gathers for status writes) stay tile-local too.

    Returns a jitted fn
    ``(used_cnt[T], used_req[T,R], contrib[T,R], ids[N,K], sign[N,K],
    pod_req[N,R], pod_present[N,R]) → (used_cnt, used_req, contrib)``
    with the agg arrays sharded on "throttles" and deltas replicated.
    Exactness: scatter-adds commute in int64, and each global id lands in
    exactly one tile, so the result is bit-identical to the single-device
    ``apply_pod_deltas_batched`` (property-tested on the 8-device mesh).
    """
    from ..ops.aggregate import apply_pod_deltas_batched

    thr_spec = P("throttles")

    def _apply(used_cnt, used_req, contrib, ids, sign, pod_req, pod_present):
        t_local = used_cnt.shape[0]  # tile rows (shard_map sees the local view)
        idx = jax.lax.axis_index("throttles")
        offset = idx * t_local
        local_ids = jnp.where(
            (ids >= offset) & (ids < offset + t_local), ids - offset, t_local
        ).astype(ids.dtype)  # out-of-tile → t_local → dropped by the scatter
        return apply_pod_deltas_batched(
            used_cnt, used_req, contrib, local_ids, sign, pod_req, pod_present
        )

    mapped = shard_map(
        _apply,
        mesh=mesh,
        in_specs=(thr_spec, thr_spec, thr_spec, P(), P(), P(), P()),
        out_specs=(thr_spec, thr_spec, thr_spec),
    )
    return jax.jit(mapped)


def sharded_full_update(mesh: Mesh, *, on_equal: bool = False, step3_on_equal: bool = True):
    """Compile the full step over a ("pods","throttles") mesh via shard_map.

    Input layout: pod-side arrays sharded on "pods", throttle-side (override
    schedule, reservations, validity) on "throttles", the mask on both.
    Outputs: per-pod arrays sharded on "pods"; per-throttle on "throttles".
    """
    pod_spec = P("pods")
    thr_spec = P("throttles")

    sched_specs = uniform_sched_specs(thr_spec)
    pods_specs = uniform_pods_specs(pod_spec)

    def _step(sched, pods, mask, counted, res_cnt, res_cnt_p, res_req, res_req_p, thr_valid, now_ns):
        return full_update_step(
            sched, pods, mask, counted,
            res_cnt, res_cnt_p, res_req, res_req_p, thr_valid, now_ns,
            on_equal=on_equal, step3_on_equal=step3_on_equal,
            pod_axis="pods", thr_axis="throttles",
        )

    mapped = shard_map(
        _step,
        mesh=mesh,
        in_specs=(
            sched_specs, pods_specs, P("pods", "throttles"), pod_spec,
            thr_spec, thr_spec, thr_spec, thr_spec, thr_spec, P(),
        ),
        out_specs=(pod_spec, pod_spec, thr_spec, thr_spec, thr_spec, thr_spec),
    )
    return jax.jit(mapped)
