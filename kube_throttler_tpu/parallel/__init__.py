"""Multi-chip scale-out: mesh construction + the sharded full update step.

The workload's parallel axes (SURVEY §2/§5: this system has no sequence/
pipeline/expert structure — its scaling axes are #pods and #throttles) map
onto a 2D device mesh:

- ``pods`` axis      — data-parallel over the pod batch (rows of the check
  matrix and of the selector mask);
- ``throttles`` axis — model-parallel-style sharding of throttle state
  (columns of the mask; thresholds/used/reserved rows).

Cross-shard communication is exactly two XLA collectives per step, both
riding ICI: a ``psum`` over the pods axis to assemble used-aggregation
partials, and a ``psum`` over the throttles axis to assemble per-pod
admission verdicts. Resource dims (R ≤ 32) stay replicated.

Alternative decomposition: ``ring.py`` keeps throttle tiles resident and
rotates pod blocks over ``ppermute`` (the ring-attention/context-parallel
pattern) for throttle-state-dominated shapes. Multi-host: ``distributed.py``
brings up jax.distributed and lays the pods axis over DCN with throttles on
each host's ICI island.
"""

from .distributed import hybrid_mesh, init_distributed, shard_global_array  # noqa: F401
from .mesh import make_mesh, mesh_shardings  # noqa: F401
from .ring import make_ring_mesh, ring_full_update  # noqa: F401
from .sharded import (  # noqa: F401
    full_update_step,
    sharded_apply_deltas,
    sharded_full_update,
)
