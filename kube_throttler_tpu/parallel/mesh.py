"""Mesh + sharding-spec helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_devices: Optional[int] = None, shape: Optional[Tuple[int, int]] = None
) -> Mesh:
    """2D ("pods", "throttles") mesh over the first n devices.

    Default factorization puts the larger factor on the pods axis (pod count
    dominates throttle count at every BASELINE config).
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(
            f"requested {n} devices but only {len(devices)} are visible"
        )
    devices = devices[:n]
    if shape is not None and shape[0] * shape[1] != n:
        raise ValueError(
            f"mesh shape {tuple(shape)} needs {shape[0] * shape[1]} devices, "
            f"got {n} (visible: {len(jax.devices())}); pass a shape whose "
            "product matches the device count, or omit it"
        )
    if shape is None:
        # largest factor pair with pods-major
        t = 1
        for cand in range(int(n**0.5), 0, -1):
            if n % cand == 0:
                t = cand
                break
        shape = (n // t, t)
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axis_names=("pods", "throttles"))


def mesh_shardings(mesh: Mesh):
    """Named shardings for the step's operand groups:

    returns (pod_sharding [P,...], throttle_sharding [T,...],
             mask_sharding [P,T], replicated).
    """
    return (
        NamedSharding(mesh, P("pods")),
        NamedSharding(mesh, P("throttles")),
        NamedSharding(mesh, P("pods", "throttles")),
        NamedSharding(mesh, P()),
    )
