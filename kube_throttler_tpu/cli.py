"""CLI entry point (reference cmd/: cobra root + kube-scheduler + version).

The reference binary embeds upstream kube-scheduler with the plugin
registered (cmd/kube_scheduler.go:90-106). The standalone TPU framework has
no scheduler to embed, so ``serve`` runs the throttler as a daemon: the
in-memory store + controllers + device mirror + the HTTP surface
(PreFilter/Reserve/Unreserve + object CRUD + /metrics).

Usage:
    python -m kube_throttler_tpu.cli serve --name kube-throttler \
        --target-scheduler-name my-scheduler [--port 10259] [--config cfg.yaml]
    python -m kube_throttler_tpu.cli version

``--config`` accepts a KubeSchedulerConfiguration-style YAML: the args are
read from ``profiles[*].pluginConfig[name=kube-throttler].args`` (the same
shape as deploy/config.yaml in the reference) or from a flat mapping.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time as _time
from typing import Any, Dict, Optional

from . import __version__
from .api.pod import Namespace
from .engine.store import Store
from .utils import tracing
from .plugin import KubeThrottler, decode_plugin_args
from .plugin.framework import RecordingEventRecorder
from .server import ThrottlerHTTPServer


def _positive_seconds(allow_inf: bool):
    """argparse type for duration knobs: ``float`` alone accepts 'nan'
    (which disables every `>` comparison downstream — the replica gate
    would fail OPEN) and negatives. Reject both at the parse boundary so
    the operator gets a usage error, not a silently-dead gate."""

    def parse(text: str) -> float:
        try:
            v = float(text)
        except ValueError:
            raise argparse.ArgumentTypeError(f"not a number: {text!r}")
        if v != v or v <= 0 or (not allow_inf and v == float("inf")):
            raise argparse.ArgumentTypeError(
                f"must be a positive{'' if allow_inf else ' finite'} "
                f"number of seconds (got {text!r})"
            )
        return v

    return parse


def _load_config_file(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        return yaml.safe_load(f) or {}


def _resolve_client_connection(raw_cfg: Dict[str, Any], args, fail) -> None:
    """KubeSchedulerConfiguration ``clientConnection.{qps,burst}`` parity:
    the scheduler-level block governs apiserver traffic in the reference's
    embedded scheduler. Precedence: explicit flag > config > defaults
    (50/100) — flags are declared with default=None so an explicitly
    passed default value still wins over the config. Non-numeric config
    values report through ``fail`` (parser.error)."""
    cc = (raw_cfg or {}).get("clientConnection") or {}
    try:
        cfg_qps = float(cc["qps"]) if "qps" in cc else None
        cfg_burst = int(cc["burst"]) if "burst" in cc else None
    except (TypeError, ValueError):
        fail(f"clientConnection qps/burst must be numeric (got {cc!r})")
        return
    if args.api_qps is None:
        args.api_qps = cfg_qps if cfg_qps is not None else 50.0
    if args.api_burst is None:
        args.api_burst = cfg_burst if cfg_burst is not None else 100


def _args_from_config(cfg: Dict[str, Any], path: str) -> Dict[str, Any]:
    for profile in cfg.get("profiles", []) or []:
        for pc in profile.get("pluginConfig", []) or []:
            if pc.get("name") == "kube-throttler":
                return dict(pc.get("args") or {})
    if "name" in cfg:
        return cfg
    # a config carrying only scheduler-level blocks (e.g. leaderElection) is
    # fine — plugin args may come from CLI flags; decode_plugin_args
    # validates the merged result
    return {}


def _serve_sharded(args, plugin_args, leader_elect: bool, stop) -> int:
    """``serve --shards N``: the scatter-gather admission front in THIS
    process, N shard worker processes under a supervisor. The front's
    store is the merged read view the HTTP surface serves; every local
    mutation routes to the owning shards; shard status writes stream
    back (sharding/front.py)."""
    from .metrics import Registry
    from .sharding.front import AdmissionFront
    from .sharding.supervisor import ShardSupervisor

    elector = None
    if leader_elect:
        from .utils.leaderelect import FileLeaseElector, default_lease_path

        lock_path = args.lock_file or default_lease_path(plugin_args.name)
        elector = FileLeaseElector(lock_path)
        print(f"leader election on {lock_path}: waiting for lease...", flush=True)
        if not elector.acquire(stop):
            return 0

    # mixed fleets: --shard-connect SID=HOST:PORT shards are dialed, not
    # spawned (somebody else runs those workers — another host, a pod)
    remote_workers = {}
    for spec in getattr(args, "shard_connect", None) or []:
        sid_s, _, hostport = spec.partition("=")
        try:
            sid = int(sid_s)
        except ValueError:
            raise SystemExit(f"--shard-connect: bad shard id in {spec!r}")
        if not (0 <= sid < args.shards) or ":" not in hostport:
            raise SystemExit(
                f"--shard-connect: want SID=HOST:PORT with 0 <= SID < "
                f"--shards, got {spec!r}"
            )
        remote_workers[sid] = hostport
    transport = getattr(args, "shard_transport", "socketpair")
    if remote_workers and transport != "tcp":
        transport = "tcp"  # remote workers imply the fleet transport
    auth_key = None
    if transport == "tcp":
        from .sharding.ipc import load_auth_key

        auth_key = load_auth_key(getattr(args, "shard_auth_key_file", ""))
        if auth_key is None and remote_workers:
            # pickled frames to a peer we cannot authenticate: the
            # workers will refuse a keyless non-loopback --listen, but
            # say it HERE too so a loopback-tunnel setup is a choice,
            # not an accident
            print(
                "WARNING: --shard-connect without a frame-auth key "
                "(--shard-auth-key-file / $KT_SHARD_AUTH_KEY): shard "
                "frames are unauthenticated pickle — only safe if every "
                "hop is loopback or locked down out-of-band",
                flush=True,
            )

    metrics_registry = Registry()
    front = AdmissionFront(
        args.shards,
        metrics_registry=metrics_registry,
        name=plugin_args.name,
        rpc_deadline=getattr(args, "shard_rpc_deadline", 30.0),
    )
    supervisor = ShardSupervisor(
        front,
        name=plugin_args.name,
        target_scheduler=plugin_args.target_scheduler_name,
        use_device=not args.no_device,
        data_dir=args.data_dir or None,
        ingest_batch=getattr(args, "ingest_batch", "adaptive"),
        transport=transport,
        remote_workers=remote_workers,
        auth_key=auth_key,
    )
    print(
        f"spawning {args.shards - len(remote_workers)} shard workers "
        f"({transport}; {len(remote_workers)} remote)...",
        flush=True,
    )
    supervisor.start()
    if front.store.get_namespace("default") is None:
        front.store.create_namespace(Namespace("default"))
    # front-side interned-verdict cache observability (the scatter tier
    # keeps its own cache keyed on front epochs)
    from .metrics import register_build_metrics, register_verdict_cache_metrics

    register_verdict_cache_metrics(metrics_registry, front.verdict_cache)
    # build/version exposition (rolling upgrades): own build_info row plus
    # one row per shard with its NEGOTIATED proto/caps, so a fleet scrape
    # shows exactly which pairings are running during a roll
    register_build_metrics(metrics_registry, role="front", front=front)
    server = ThrottlerHTTPServer(front, host=args.host, port=args.port)
    server.start()
    print(
        f"kube-throttler-tpu serving on {args.host}:{server.port} "
        f"(throttler={plugin_args.name}, "
        f"scheduler={plugin_args.target_scheduler_name}, "
        f"shards={args.shards}, device={'off' if args.no_device else 'on'})",
        flush=True,
    )
    stop.wait()
    server.mark_draining()
    front.drain(timeout=10.0)
    server.stop()
    supervisor.stop()
    front.stop()
    if elector is not None:
        elector.release()
    return 0


def main(argv: Optional[list] = None) -> int:
    # an operator's explicit JAX_PLATFORMS (e.g. =cpu when the TPU is down)
    # must win over ambient platform pinning; must run before any backend
    # touch (the device prewarm at startup), or serve hangs on a dead tunnel
    from .utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    parser = argparse.ArgumentParser(prog="kube-throttler-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    serve = sub.add_parser("serve", help="run the throttler daemon")
    serve.add_argument("--config", help="KubeSchedulerConfiguration-style YAML")
    serve.add_argument("--name", help="throttler name (spec.throttlerName to own)")
    serve.add_argument("--target-scheduler-name", help="schedulerName of governed pods")
    serve.add_argument(
        "--kubeconfig",
        help="connect to a real apiserver: list+watch reflectors keep the "
        "local cache synced and status writes go to the status subresource "
        "(plugin.go:71-130); without it the daemon runs its own in-memory "
        "apiserver fed via the HTTP surface",
    )
    serve.add_argument(
        "--api-qps",
        type=float,
        default=None,
        help="client-side write rate limit against the remote apiserver "
        "(client-go rest.Config QPS analog; 0 disables; default 50, or "
        "the --config clientConnection.qps)",
    )
    serve.add_argument(
        "--api-burst",
        type=int,
        default=None,
        help="token-bucket burst for --api-qps (rest.Config Burst analog; "
        "default 100, or the --config clientConnection.burst)",
    )
    serve.add_argument("--controller-threadiness", type=int, default=0)
    serve.add_argument("--num-key-mutex", type=int, default=0)
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument("--port", type=int, default=10259)
    serve.add_argument(
        "--apiserver-port",
        type=int,
        default=-1,
        help="ALSO serve the Kubernetes list+watch wire protocol from this "
        "daemon's store on the given port (0 = ephemeral): standby replicas "
        "or sidecars can then point their --kubeconfig at this daemon, "
        "making the standalone store a real control plane (ignored with "
        "--kubeconfig — there is already a real apiserver)",
    )
    serve.add_argument(
        "--data-dir",
        default="",
        help="standalone durability: journal every watch event to "
        "<dir>/store.journal and replay it on startup, so specs AND written "
        "statuses survive a restart (ignored with --kubeconfig, where the "
        "apiserver is the state of record and reflectors rebuild the cache). "
        "Startup runs the crash-recovery pipeline (newest valid snapshot + "
        "journal tail, engine/recovery.py) and shutdown writes a final "
        "snapshot",
    )
    serve.add_argument(
        "--snapshot-every",
        type=int,
        default=5000,
        help="with --data-dir: cut a full state snapshot every N journaled "
        "events (atomic, checksummed; recovery replays only the journal "
        "tail past it); 0 disables the journal-size trigger (shutdown "
        "snapshots still happen)",
    )
    serve.add_argument(
        "--snapshot-keep",
        type=int,
        default=3,
        help="with --data-dir: retain the newest N snapshots (older ones "
        "are checksum-verified fallbacks when the newest is torn)",
    )
    serve.add_argument(
        "--reservation-ttl",
        default="",
        help="expire scheduler-cycle reservations after this Go-style "
        'duration (e.g. "5m"): a scheduler that dies between Reserve and '
        "Bind stops pinning capacity; crash recovery rebases remaining "
        "TTLs. Empty = reservations live until observed/unreserved "
        "(reference semantics)",
    )
    serve.add_argument(
        "--ingest-batch",
        default="adaptive",
        help="micro-batched watch ingest (remote mode): 'adaptive' "
        "(default — batch grows under backlog, collapses to single-event "
        "application when idle), a fixed integer batch size, or 'off' for "
        "per-event application",
    )
    serve.add_argument(
        "--shards",
        type=int,
        default=0,
        help="shared-nothing multiprocess sharding: run N worker processes "
        "each owning a consistent-hash slice of the Throttle/ClusterThrottle "
        "keyspace (full vertical per shard: store+index+journal+device "
        "planes+controllers), behind a scatter-gather admission front on "
        "this process (docs/PERFORMANCE.md 'Multiprocess keyspace "
        "sharding'). 0 = single-process engine. Standalone mode only",
    )
    serve.add_argument(
        "--shard-transport",
        choices=("socketpair", "tcp"),
        default="socketpair",
        help="how the front reaches its shard workers: 'socketpair' "
        "(inherited fd, children on this host) or 'tcp' (the cross-host "
        "fleet transport: per-shard connection pools, reconnect backoff, "
        "epoch-fenced frames — docs/robustness.md 'Cross-host fleet')",
    )
    serve.add_argument(
        "--shard-connect",
        action="append",
        metavar="SID=HOST:PORT",
        help="mixed fleets: do not spawn shard SID locally, dial a worker "
        "somebody else runs (`python -m kube_throttler_tpu.sharding.worker "
        "--listen ...`). Repeatable; implies --shard-transport tcp",
    )
    serve.add_argument(
        "--shard-rpc-deadline",
        type=_positive_seconds(allow_inf=False),
        default=30.0,
        help="per-op deadline budget (seconds) for front→shard RPCs; a "
        "scatter call that outruns it degrades fail-safe instead of "
        "blocking admission (the bulk triage op keeps a 120s floor)",
    )
    serve.add_argument(
        "--shard-auth-key-file",
        default="",
        help="file holding the fleet's frame-auth pre-shared key (a "
        "mounted Secret); falls back to $KT_SHARD_AUTH_KEY. Every TCP "
        "shard frame is HMAC-authenticated with it before the pickle "
        "payload is deserialized — REQUIRED for fleets that leave "
        "loopback; the workers refuse a keyless non-loopback --listen "
        "(docs/robustness.md 'Transport security')",
    )
    serve.add_argument("--no-device", action="store_true", help="host-oracle decisions only")
    serve.add_argument(
        "--leader-elect",
        action="store_true",
        help="block until the leadership lease is acquired before serving "
        "(also honours leaderElection.leaderElect in --config)",
    )
    serve.add_argument(
        "--lock-file",
        default="",
        help="flock leadership lease path (default: a 0700 per-user runtime "
        "dir; with --kubeconfig leader election uses a Lease object on the "
        "apiserver instead — multi-host capable)",
    )
    serve.add_argument(
        "--ha-role",
        choices=("none", "leader", "standby", "replica"),
        default="none",
        help="active/standby HA for the standalone store (docs/robustness.md "
        "'High availability & fencing'): 'leader' acquires the lease, bumps "
        "the fencing epoch, and serves replication endpoints for warm "
        "standbys; 'standby' bootstraps from --replicate-from, streams the "
        "journal tail into its own --data-dir while /readyz reports "
        "standby, and promotes itself when the lease frees. Both imply "
        "--leader-elect and require --data-dir. 'replica' is the stateless "
        "read tier (docs/PERFORMANCE.md 'Verdict cache & read replicas'): "
        "it bootstraps and streams like a standby but never competes for "
        "the lease — it serves /v1/prefilter* locally (staleness-gated) "
        "and forwards every write to the owner",
    )
    serve.add_argument(
        "--replicate-from",
        default="",
        help="standby/replica only: the leader's HTTP base URL (its "
        "--host:--port); snapshot bootstrap + journal tail stream come "
        "from its /v1/replication endpoints (a replica also forwards "
        "reserve/bind/object writes there)",
    )
    serve.add_argument(
        "--replica-max-lag",
        type=_positive_seconds(allow_inf=True),
        default=5.0,
        help="replica only: staleness bound in seconds — when the time "
        "since the last successful replication poll exceeds this, the "
        "replica refuses prefilter traffic with 503 instead of serving "
        "possibly-stale verdicts (the flip SLO)",
    )
    serve.add_argument(
        "--lease-backend",
        choices=("auto", "file", "http"),
        default="auto",
        help="leadership lease backend: 'file' (flock, single host — the "
        "OS frees it when the leader dies), 'http' (a coordination.k8s.io "
        "Lease on the --kubeconfig apiserver, multi-host), or 'auto' "
        "(http when a kubeconfig is given and no --lock-file, else file)",
    )
    serve.add_argument(
        "--nodes",
        type=int,
        default=0,
        help="run the embedded scheduler loop binding pods onto N simulated "
        "nodes (the reference binary embeds kube-scheduler; 0 = admission "
        "daemon only, an external scheduler calls /v1/prefilter)",
    )
    serve.add_argument("--node-max-pods", type=int, default=300)
    serve.add_argument(
        "--node-allocatable",
        default="",
        help="per-node allocatable resources for the embedded scheduler, "
        'e.g. "cpu=8,memory=32Gi" (NodeResourcesFit analog); empty = '
        "pod-count capacity only",
    )
    serve.add_argument(
        "--v", type=int, default=0, dest="verbosity",
        help="klog-style verbosity (0-5); change at runtime via PUT /debug/flags/v",
    )

    sub.add_parser("version", help="print version")

    args = parser.parse_args(argv)

    if args.command == "version":
        print(f"kube-throttler-tpu version {__version__}")
        return 0

    # klog-equivalent logging: INFO to stderr, V-levels gate detail lines
    import logging

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s] %(message)s",
    )
    tracing.set_verbosity(args.verbosity)

    config: Dict[str, Any] = {}
    leader_elect = args.leader_elect
    if args.config:
        raw_cfg = _load_config_file(args.config)
        config = _args_from_config(raw_cfg, args.config)
        # KubeSchedulerConfiguration leaderElection parity (the reference
        # inherits this from the embedded kube-scheduler)
        if (raw_cfg.get("leaderElection") or {}).get("leaderElect"):
            leader_elect = True
    if args.name:
        config["name"] = args.name
    if args.target_scheduler_name:
        config["targetSchedulerName"] = args.target_scheduler_name
    if args.kubeconfig:
        config["kubeconfig"] = args.kubeconfig
    if args.controller_threadiness:
        config["controllerThrediness"] = args.controller_threadiness
    if args.num_key_mutex:
        config["numKeyMutex"] = args.num_key_mutex
    if args.reservation_ttl:
        config["reservationTTL"] = args.reservation_ttl

    try:
        plugin_args = decode_plugin_args(config)
    except ValueError as e:
        parser.error(str(e))  # clean usage error, not a traceback

    _resolve_client_connection(raw_cfg if args.config else {}, args, parser.error)
    if args.api_qps > 0 and args.api_burst < 1:
        parser.error("--api-burst must be >= 1 when --api-qps is enabled")

    # validate EARLY, with the other usage checks: this must fail as a clean
    # parser error before any heavy startup (plugin construction initializes
    # the device backend, which can block on a dead tunnel)
    node_allocatable = None
    if args.node_allocatable:
        from .quantity import parse_quantity

        try:
            node_allocatable = {}
            for kv in args.node_allocatable.split(","):
                if not kv.strip():
                    continue
                resource, _, value = kv.partition("=")
                resource, value = resource.strip(), value.strip()
                if not resource or not value:
                    raise ValueError(f"bad entry {kv!r}")
                # validate NOW, not inside the scheduler; negatives would
                # silently make the node unusable
                if parse_quantity(value) < 0:
                    raise ValueError(f"negative quantity for {resource!r}")
                if resource in node_allocatable:
                    # last-one-wins would silently shrink a typoed resource
                    raise ValueError(f"duplicate resource {resource!r}")
                node_allocatable[resource] = value
            if not node_allocatable:
                raise ValueError("no resource entries")
        except ValueError as e:
            parser.error(f"--node-allocatable must look like 'cpu=8,memory=32Gi': {e}")

    if plugin_args.kubeconfig and args.nodes > 0:
        # the embedded scheduler binds pods in the LOCAL store; in remote
        # mode the reflectors own those objects and would revert every bind
        parser.error(
            "--nodes (embedded scheduler) cannot be combined with "
            "--kubeconfig: bind decisions must go to the real apiserver — "
            "run an external scheduler against /v1/prefilter instead"
        )

    # HA flag surface (usage errors before any heavy startup)
    if args.ha_role != "none":
        if not args.data_dir:
            parser.error("--ha-role requires --data-dir (the replicated "
                         "journal + snapshots live there)")
        if plugin_args.kubeconfig:
            parser.error(
                "--ha-role is for the STANDALONE store; in --kubeconfig "
                "mode the apiserver is the state of record and plain "
                "--leader-elect active/standby already applies"
            )
        if args.ha_role != "replica":
            # a replica never competes for the lease: it is a read tier,
            # not a failover candidate
            leader_elect = True
    if args.ha_role in ("standby", "replica"):
        if not args.replicate_from:
            parser.error(f"--ha-role {args.ha_role} requires "
                         "--replicate-from (the leader's HTTP base URL)")
        if args.nodes > 0:
            parser.error(f"--nodes cannot run on a {args.ha_role}: the "
                         "embedded scheduler would bind pods locally")
    if args.ha_role == "replica" and leader_elect:
        parser.error("--leader-elect cannot be combined with --ha-role "
                     "replica: a read replica never competes for the lease")
    if args.lease_backend == "http" and not plugin_args.kubeconfig:
        parser.error("--lease-backend http requires --kubeconfig (the "
                     "Lease object lives on that apiserver)")

    # multiprocess sharding flag surface (usage errors before heavy startup)
    if args.shards > 0:
        if plugin_args.kubeconfig:
            parser.error(
                "--shards runs the standalone sharded store; in --kubeconfig "
                "mode the apiserver is the state of record — run one replica "
                "per host with --leader-elect instead"
            )
        if args.ha_role != "none":
            parser.error(
                "--shards and --ha-role are exclusive: each shard worker "
                "runs its own fenced leadership (per-shard epoch in its "
                "data dir); front-level HA is the supervisor's restart path"
            )
        if args.nodes > 0:
            parser.error(
                "--nodes (embedded scheduler) is not supported with --shards "
                "yet: run an external scheduler against /v1/prefilter"
            )

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())

    if args.shards > 0:
        return _serve_sharded(args, plugin_args, leader_elect, stop)

    rest_config = None
    if plugin_args.kubeconfig:
        from .client.transport import parse_kubeconfig

        # parse ONCE, up front: the loader is side-effectful (inline
        # *-data credentials materialize to memfds/tempfiles) and the
        # elector and the reflector session share the same RestConfig
        rest_config = parse_kubeconfig(plugin_args.kubeconfig)
    elif os.environ.get("KUBERNETES_SERVICE_HOST") and args.nodes == 0:
        # no kubeconfig but running inside a pod → remote mode via the
        # ServiceAccount mount, the clientcmd fallback the reference hits
        # through BuildConfigFromFlags("") (plugin.go:71)
        from .client.transport import in_cluster_config

        try:
            rest_config = in_cluster_config()
            print("using in-cluster ServiceAccount credentials", flush=True)
        except ValueError as e:
            # fatal, like the reference's BuildConfigFromFlags error path:
            # silently serving admission from an empty standalone store
            # inside a cluster would mask the broken SA mount
            print(f"in-cluster config unavailable: {e}", file=sys.stderr, flush=True)
            return 1

    elector = None
    # demotion hooks run on leadership loss BEFORE the stop event fires —
    # the fencing epoch (created later, once the data dir is open) appends
    # one so a deposed leader's writes are refused even while draining
    fence_hooks: list = []
    if leader_elect:
        backend = args.lease_backend
        if backend == "auto":
            backend = "http" if (rest_config is not None and not args.lock_file) else "file"
        if backend == "http":
            # multi-host: a coordination.k8s.io Lease on the shared
            # apiserver — replicas on different hosts compete for it, like
            # the reference's embedded kube-scheduler leader election
            import socket

            from .client.transport import ApiClient
            from .utils.leaderelect import HttpLeaseElector

            def _leadership_lost():
                # fail fast like the embedded kube-scheduler: a demoted
                # leader must stop serving (a standby has taken over) —
                # and must stop WRITING first (fencing)
                for hook in fence_hooks:
                    hook()
                print("leadership lost; shutting down", file=sys.stderr, flush=True)
                stop.set()

            elector = HttpLeaseElector(
                # lease renew traffic is ~0.5 writes/s — exempt from the
                # --api-qps bucket so a saturated status pipeline can never
                # starve leadership renewal into a spurious failover
                ApiClient(rest_config, qps=None),
                name=f"kube-throttler-tpu-{plugin_args.name}",
                identity=f"{socket.gethostname()}-{os.getpid()}",
                on_lost=_leadership_lost,
            )
            print(
                f"leader election on Lease kube-throttler-tpu-{plugin_args.name}: "
                "waiting...",
                flush=True,
            )
        else:
            from .utils.leaderelect import FileLeaseElector, default_lease_path

            lock_path = args.lock_file or default_lease_path(plugin_args.name)
            elector = FileLeaseElector(lock_path)
            print(f"leader election on {lock_path}: waiting for lease...", flush=True)
        if args.ha_role != "standby":
            # a standby replicates FIRST and blocks on the lease later;
            # everyone else gates startup on acquisition, as before
            try:
                if not elector.acquire(stop):
                    return 0  # interrupted while standing by
            except RuntimeError as e:
                print(str(e), file=sys.stderr, flush=True)
                return 1

    store = Store()
    session = None
    journal = None
    recovery = None
    snapshotter = None
    ingest_pipeline = None
    ha = None
    epoch = None
    replicator = None
    standby_server = None
    promoted = False
    from .metrics import Registry

    metrics_registry = Registry()  # shared: reflector metrics + the 16 families
    from .metrics import register_build_metrics

    _role = getattr(args, "ha_role", "none") or "none"
    # build/version exposition (rolling upgrades): every role exports
    # kube_throttler_build_info so a fleet scrape names each build
    register_build_metrics(
        metrics_registry, role=("standalone" if _role == "none" else _role)
    )
    if rest_config is not None:
        from .client.transport import RemoteSession

        ingest_batch = getattr(args, "ingest_batch", "adaptive")
        if ingest_batch in ("off", "none", ""):
            ingest_batch = None
        elif ingest_batch != "adaptive":
            ingest_batch = int(ingest_batch)
        session = RemoteSession(
            rest_config,
            store,
            metrics_registry=metrics_registry,
            qps=args.api_qps if args.api_qps > 0 else None,
            burst=args.api_burst,
            ingest_batch=ingest_batch,
        )
        print(
            f"syncing from apiserver {session.config.server} "
            f"(kubeconfig={plugin_args.kubeconfig})...",
            flush=True,
        )
        session.start()  # blocks until every reflector listed once
    else:
        if args.data_dir:
            from .engine.recovery import RecoveryManager
            from .engine.snapshot import SnapshotManager

            os.makedirs(args.data_dir, exist_ok=True)
            # recovery runs BEFORE the plugin registers handlers: snapshot
            # restore + journal tail replay fill the store silently; the
            # plugin's cache-sync replay then delivers the recovered
            # objects to the device mirror and controllers
            recovery = RecoveryManager(args.data_dir)
            journal = recovery.recover_store(store)
            snapshotter = SnapshotManager(
                args.data_dir, store, keep=args.snapshot_keep
            )
            r = recovery.report
            print(
                f"recovery: mode={r.journal_mode} "
                f"snapshot={r.snapshot_seq if r.snapshot_seq is not None else '-'} "
                f"({r.snapshot_objects} objects) + {r.journal_lines_replayed} "
                f"journal events in {r.duration_s:.3f}s "
                f"({len(store.list_pods())} pods, "
                f"{len(store.list_throttles())} throttles recovered)",
                flush=True,
            )
        if args.ha_role != "none":
            # HA wiring (docs/robustness.md "High availability & fencing"):
            # the fencing epoch gates the journal and snapshots; the
            # coordinator carries role/epoch for /readyz and metrics
            from .engine.replication import (
                FencingEpoch,
                HaCoordinator,
                ReplicationSource,
                StandbyReplicator,
            )

            epoch = FencingEpoch(args.data_dir)
            epoch.observe(recovery.report.epoch)
            fence_hooks.append(lambda: epoch.fence("leadership lost"))
            journal.fencing = epoch
            snapshotter.fencing = epoch
            if args.ha_role == "replica":
                # stateless read-replica tier: bootstrap + stream exactly
                # like a standby, but no lease, no promotion path, no
                # replication source of its own — it mirrors the owner's
                # planes so the verdict cache can serve prefilter locally,
                # and every write surface forwards to the owner
                replicator = StandbyReplicator(
                    store, journal, args.replicate_from, epoch=epoch
                )
                if not replicator.bootstrap(deadline_s=60.0):
                    reason = replicator.format_refused_reason or (
                        f"owner unreachable at {args.replicate_from}"
                    )
                    print(
                        f"replica bootstrap failed: {reason}",
                        file=sys.stderr, flush=True,
                    )
                    journal.close()
                    return 1
                replicator.start()
                print(
                    f"replica synced (offset={replicator.consumed_offset()}, "
                    f"events={replicator.events_applied}) from "
                    f"{args.replicate_from}",
                    flush=True,
                )
            elif args.ha_role == "standby":
                replicator = StandbyReplicator(
                    store, journal, args.replicate_from, epoch=epoch
                )
                ha = HaCoordinator(
                    epoch, role="standby", replicator=replicator,
                    journal=journal, snapshotter=snapshotter,
                )
                # HA families registered BEFORE the standby wait: the
                # replication-lag gauge must be scrapeable exactly while
                # this replica is a standby, not only after promotion
                from .metrics import register_ha_metrics

                register_ha_metrics(metrics_registry, ha)
                # the standby SERVES its role from the real port while
                # replicating: /readyz 503 {"state": "standby", ...},
                # admission endpoints refused until promotion
                standby_server = ThrottlerHTTPServer(
                    None, host=args.host, port=args.port, ha=ha,
                    metrics_registry=metrics_registry,
                )
                standby_server.start()
                print(
                    f"standby on {args.host}:{standby_server.port} "
                    f"replicating from {args.replicate_from}",
                    flush=True,
                )
                if not replicator.bootstrap(deadline_s=60.0):
                    reason = replicator.format_refused_reason or (
                        f"leader unreachable at {args.replicate_from}"
                    )
                    print(
                        f"standby bootstrap failed: {reason}",
                        file=sys.stderr, flush=True,
                    )
                    standby_server.stop()
                    journal.close()
                    return 1
                replicator.start()
                print(
                    f"standby synced (offset={replicator.consumed_offset()}, "
                    f"events={replicator.events_applied}); standing by",
                    flush=True,
                )
                if not elector.acquire(stop):
                    # interrupted while standing by: clean exit
                    replicator.stop()
                    standby_server.stop()
                    journal.close()
                    return 0
                new_epoch = ha.promote()
                promoted = True
                print(
                    f"promoted to leader (epoch {new_epoch}, tail "
                    f"fast-forward {ha.failover_duration_s:.3f}s)",
                    flush=True,
                )
            else:
                ha = HaCoordinator(
                    epoch, role="leader", journal=journal,
                    snapshotter=snapshotter,
                )
                from .metrics import register_ha_metrics

                register_ha_metrics(metrics_registry, ha)
                ha.become_leader()
                print(f"leading with fencing epoch {epoch.current()}", flush=True)
            if ha is not None:
                # leader or promoted standby: serve the replication
                # endpoints so (new) standbys/replicas bootstrap and stream
                ha.source = ReplicationSource(args.data_dir, journal, epoch)
        if store.get_namespace("default") is None:
            store.create_namespace(Namespace("default"))
        # standalone mode: the micro-batch ingest front-end over the local
        # store (embedders/REST writers submit through it; idle it costs
        # one parked thread) — built with the registry so the ingest
        # batch-size/counter families export on the LOCAL path too
        ingest_batch = getattr(args, "ingest_batch", "adaptive")
        if ingest_batch not in ("off", "none", ""):
            from .engine.ingest import MicroBatchIngest

            ingest_pipeline = MicroBatchIngest(
                store,
                batch_policy=(
                    "adaptive" if ingest_batch == "adaptive" else int(ingest_batch)
                ),
                metrics_registry=metrics_registry,
            )
    plugin = KubeThrottler(
        plugin_args,
        store,
        # remote mode posts Warning events to the real apiserver (the
        # reference emits through the framework recorder, plugin.go:190-201)
        event_recorder=(
            session.event_recorder if session is not None else RecordingEventRecorder()
        ),
        use_device=not args.no_device,
        start_workers=True,
        # the ASYNC committer: batch submit + per-key newest-wins coalescing
        # + concurrent PUT workers (transport.AsyncStatusCommitter)
        status_writer=session.status_committer if session is not None else None,
        metrics_registry=metrics_registry,
    )
    if plugin.device_manager is not None:
        # compile the steady-state kernel shapes before taking traffic —
        # a mid-burst XLA compile would land in the serving latency tail.
        # On accelerators the persistent cache makes restarts deserialize
        # instead of recompile (KT_JAX_CACHE_DIR overrides the location);
        # the helper declines on CPU. The jax.devices() probe here is the
        # daemon's intended device cold-start (prewarm right below needs
        # the backend anyway).
        import jax

        from .utils.platform import enable_persistent_compilation_cache

        enable_persistent_compilation_cache(jax.devices()[0].platform)
        _t0 = _time.perf_counter()
        _nk = plugin.device_manager.prewarm()
        print(
            f"device kernels prewarmed ({_nk} shapes, {_time.perf_counter()-_t0:.1f}s)",
            flush=True,
        )
    # /readyz components beyond the plugin's own (device, workqueues):
    # remote reflectors report down-until-synced/degraded-in-backoff; a
    # journal that recovered lossily or is dropping writes reports degraded
    if session is not None:
        session.register_health(plugin.health)
    if journal is not None:
        plugin.health.register("journal", journal.health_state)
    if recovery is not None:
        # the rest of the crash-safety wiring needs the plugin: reservation
        # ledgers live on the controllers, and the first-relist reconcile
        # compares the rebuilt device planes against the informer caches
        if replicator is not None and recovery.snapshot is None:
            # a fresh standby has no local snapshot — standing reservations
            # come from the leader's bootstrap snapshot (TTLs rebased
            # against OUR clock inside restore_reservations)
            recovery.snapshot = replicator.bootstrap_snapshot
        reservation_caches = {
            "throttle": plugin.throttle_ctr.cache,
            "clusterthrottle": plugin.cluster_throttle_ctr.cache,
        }
        recovery.restore_reservations(
            reservation_caches,
            on_change=(
                (lambda kind, key: plugin.device_manager.on_reservation_change(
                    kind, key, reservation_caches[kind]
                ))
                if plugin.device_manager is not None
                else None
            ),
        )
        # gang ledger restore AFTER the per-pod reservations (it prunes
        # expired/uncommitted groups' members back OUT of the caches), and
        # GANG journal stamps flow to the recovered journal from here on
        plugin.gang.journal = journal
        recovery.restore_gangs(plugin.gang, journal)
        # PREEMPT eviction brackets flow to the recovered journal from
        # here on (uncommitted ones were already rolled back to zero
        # evictions inside recover_store)
        plugin.preempt.journal = journal
        diverged = recovery.reconcile(
            plugin.informers,
            device_manager=plugin.device_manager,
            enqueue={
                "throttle": plugin.throttle_ctr.enqueue,
                "clusterthrottle": plugin.cluster_throttle_ctr.enqueue,
            },
        )
        if diverged:
            print(
                f"recovery: {diverged} plane divergence(s) re-enqueued for "
                "repair", flush=True,
            )
        snapshotter.reservations = reservation_caches
        snapshotter.gang_ledger = plugin.gang
        snapshotter.device_manager = plugin.device_manager
        snapshotter.bind_journal(journal, every_lines=args.snapshot_every)
        plugin.health.register("recovery", recovery.health_state)
        plugin.health.register("snapshot", snapshotter.health_state)
        from .metrics import register_recovery_metrics

        register_recovery_metrics(metrics_registry, snapshotter, recovery)
    replica_gate = None
    if args.ha_role == "replica":
        # the staleness gate fronts every locally served verdict: replica
        # lag beyond the flip SLO flips prefilter to 503 (and /readyz to
        # down) rather than serving verdicts the owner has outrun
        from .engine.replication import ReplicaGate

        replica_gate = ReplicaGate(replicator, max_lag_s=args.replica_max_lag)
        plugin.health.register("replica", replica_gate.health_state)
        plugin.health.register("replication", replicator.health_state)
        from .metrics import register_replica_metrics

        register_replica_metrics(metrics_registry, replica_gate)
    if ha is not None:
        # (HA metric families were registered at coordinator creation,
        # before the standby wait — only the health hook needs the plugin)
        plugin.health.register("ha", ha.health_state)
        if promoted:
            # flip re-publication: every key reconciles against replicated
            # truth, so flips the dead leader computed but never durably
            # published are re-derived and go out through the two-lane
            # pipeline's priority path
            n_keys = ha.promote_reconcile(plugin)
            print(
                f"promotion reconcile: {n_keys} keys re-enqueued "
                "(flips publish first)", flush=True,
            )
    scheduler = None
    if args.nodes > 0:
        from .scheduler import Node, Scheduler

        scheduler = Scheduler(
            plugin,
            store,
            nodes=[
                Node(
                    f"node-{i+1}",
                    max_pods=args.node_max_pods,
                    allocatable=node_allocatable,
                )
                for i in range(args.nodes)
            ],
        )
        scheduler.start()

    wire = None
    if session is None and args.apiserver_port >= 0:
        from .client.mockserver import MockApiServer

        wire = MockApiServer(store=store, host=args.host, port=args.apiserver_port)
        wire.start()
        print(f"wire-protocol apiserver on {args.host}:{wire.port}", flush=True)

    # columnar arena observability (slots live/recycled, intern pool,
    # lazy-edge materializations) on the serving registry
    from .metrics import register_store_metrics, register_verdict_cache_metrics

    register_store_metrics(metrics_registry, store)
    # interned-verdict cache observability (hits/misses/entries/
    # invalidations) — a no-op when the cache is disabled (KT_VERDICT_CACHE=0
    # or no device manager)
    register_verdict_cache_metrics(metrics_registry, plugin.verdict_cache)

    # last step before taking traffic: freeze the startup heap (store,
    # device mirror, kernel caches) so automatic full GCs never rescan it
    # — at 100k×10k those paused every thread 500-750ms, straight into the
    # flip-publication tail; with the columnar arena most heaps stay under
    # the freeze floor and the call is a measured no-op (gchygiene.py);
    # the hygiene thread is the periodic collect-and-refreeze leak
    # backstop (utils/gchygiene.py)
    from .utils.gchygiene import GcHygieneThread, enabled as gc_hygiene_enabled

    gc_hygiene = None
    if gc_hygiene_enabled():
        from .utils.gchygiene import freeze_startup_heap

        freeze_startup_heap()
        gc_hygiene = GcHygieneThread(tracer=plugin.tracer)
        gc_hygiene.start()
    if standby_server is not None:
        # the standby's listener (same host:port) flips to full serving —
        # no socket rebind, so in-flight probes see 503→200 atomically
        server = standby_server
        server.set_plugin(plugin)
    else:
        server = ThrottlerHTTPServer(
            plugin, host=args.host, port=args.port,
            remote=session is not None, ha=ha,
            replica_gate=replica_gate,
            owner_url=args.replicate_from if replica_gate is not None else None,
        )
        server.start()
    print(
        f"kube-throttler-tpu serving on {args.host}:{server.port} "
        f"(throttler={plugin_args.name}, scheduler={plugin_args.target_scheduler_name}, "
        f"device={'on' if not args.no_device else 'off'}, "
        f"embedded-scheduler={'%d nodes' % args.nodes if args.nodes else 'off'})",
        flush=True,
    )

    stop.wait()
    # graceful shutdown (docs/robustness.md "Crash safety & recovery"):
    # 1. flip /readyz to down so probes stop routing traffic here, then
    #    stop the intake surfaces (HTTP daemon, wire apiserver, scheduler);
    # 2. drain the controllers and flush the two-lane status committer's
    #    queued flips — a flip left queued is an admission-relevant status
    #    the cluster never saw;
    # 3. fsync the journal and write a final snapshot, so the next start
    #    recovers via the fast tail path with zero replay.
    server.mark_draining()
    if gc_hygiene is not None:
        gc_hygiene.stop()
    if wire is not None:
        wire.stop()
    if scheduler is not None:
        scheduler.stop()
    if session is not None:
        committer = getattr(session, "status_committer", None)
        if committer is not None:
            committer.flush()
        session.stop()
    if ingest_pipeline is not None:
        ingest_pipeline.stop()  # drain queued ops before the final snapshot
    if args.ha_role == "replica" and replicator is not None:
        # stop streaming before the journal closes (the tail applier
        # appends replicated events through it)
        replicator.stop()
    plugin.stop()
    if snapshotter is not None:
        snapshotter.write(reason="shutdown")
    if journal is not None:
        journal.close()  # flush + fsync
    server.stop()
    if elector is not None:
        elector.release()
    return 0


if __name__ == "__main__":
    sys.exit(main())
