"""Replica-serving scenario: the stateless read-replica admission tier
under storm, with a leader flip burst.

An owner (leader role: store + journal + snapshot + admission HTTP +
replication source) and one read replica (StandbyReplicator bootstrap +
journal-tail streaming, its own plugin + verdict cache, the staleness-
gated HTTP surface) run in-process. A paced pod-churn storm drives the
owner while a serving thread hammers the replica's prefilter path; mid-
storm the owner takes a FLIP BURST — threshold edits that flip hot
throttles throttled↔not-throttled — and every flip's propagation is
timed from the owner's status publication to the replica serving the
new verdict.

Gates:

- **verdicts**: zero wrong verdicts vs the owner oracle at every flip
  cutover AND in the final full-population sweep (replica's cached
  serving path vs a fresh owner-side recompute, code + normalized
  reasons);
- **lag**: replica verdict lag ≤ one flip SLO (the PR 5 150 ms bound)
  at the burst's p99 — the ISSUE's staleness story, measured not
  assumed;
- **staleness_gate**: with the gate's clock frozen past the bound the
  replica REFUSES reads with 503 (and counts the refusal), then serves
  again once fresh — the bound is enforced, not advisory;
- **forwarding**: a reserve submitted to the REPLICA lands on the
  owner's ledger and the response carries the forwarded-by marker;
- **cache**: the replica's verdict cache actually served during the
  storm (hits observed) — the tier ran hot, not incidentally correct.

Run: ``python -m kube_throttler_tpu.scenarios.replica --seed 0``
(wired into ``make scenario-test``).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
from dataclasses import replace as _replace
from typing import Dict, List, Optional

__all__ = ["run_replica_serving"]


def _req(port: int, method: str, path: str, body=None, timeout=10.0):
    import urllib.error
    import urllib.request

    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            payload = resp.read().decode()
            headers = dict(resp.headers)
            status = resp.status
    except urllib.error.HTTPError as e:
        payload = e.read().decode()
        headers = dict(e.headers)
        status = e.code
    try:
        return status, json.loads(payload), headers
    except json.JSONDecodeError:
        return status, payload, headers


def _cpu_throttled(thr) -> bool:
    """The flip bit the burst toggles: the cpu request flag of the
    published status (``IsResourceAmountThrottled`` is a dataclass, so a
    bare ``bool()`` of it would always be True)."""
    flags = thr.status.throttled.resource_requests or {}
    return bool(flags.get("cpu", False))


def _wait(predicate, timeout=30.0, interval=0.02) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return bool(predicate())


def _quiesce(tier, owner_store) -> bool:
    """Settle both tiers to ONE consistent cut: owner workqueues drained
    (statuses published), the replicator caught up to the owner journal's
    write position (nothing left in flight on the wire), then the
    replica's own reconciles drained. Verdict equality is only defined at
    such a cut — mid-churn the replica legitimately trails by one poll."""
    ok = _wait(
        lambda: len(tier.owner_plugin.throttle_ctr.workqueue) == 0
        and len(tier.owner_plugin.cluster_throttle_ctr.workqueue) == 0,
        timeout=30.0,
    )
    ok = _wait(
        lambda: tier.replicator._offset >= tier._oj.position()[0], timeout=30.0
    ) and ok
    ok = _wait(
        lambda: len(tier.replica_plugin.throttle_ctr.workqueue) == 0
        and len(tier.replica_plugin.cluster_throttle_ctr.workqueue) == 0,
        timeout=30.0,
    ) and ok
    ok = _wait(
        lambda: {p.key for p in owner_store.list_pods("default")}
        == {p.key for p in tier.replica_store.list_pods("default")},
        timeout=30.0,
    ) and ok
    return ok


class _Tier:
    """Owner + replica pair, in-process: the cli.py wiring of both roles
    without the process boundary (the scenario times verdict propagation
    at millisecond resolution — a subprocess would only add exec noise).

    No GUARDED_BY table: every attribute is assigned once during
    construction on the scenario thread and treated as immutable wiring
    thereafter — cross-thread safety lives inside the engine objects
    (store locks, replicator state, the gate's own counters), not here."""

    def __init__(self, workdir: str, max_lag_s: float):
        from ..api.pod import Namespace
        from ..engine.recovery import RecoveryManager
        from ..engine.replication import (
            FencingEpoch,
            HaCoordinator,
            ReplicaGate,
            ReplicationServer,
            ReplicationSource,
            StandbyReplicator,
        )
        from ..engine.snapshot import SnapshotManager
        from ..engine.store import Store
        from ..plugin import KubeThrottler, decode_plugin_args
        from ..server import ThrottlerHTTPServer

        args = decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        )
        owner_dir = os.path.join(workdir, "owner")
        replica_dir = os.path.join(workdir, "replica")
        os.makedirs(owner_dir)
        os.makedirs(replica_dir)

        self.owner_store = Store()
        self._oj = RecoveryManager(owner_dir).recover_store(self.owner_store)
        oepoch = FencingEpoch(owner_dir)
        self._oj.fencing = oepoch
        snap = SnapshotManager(owner_dir, self.owner_store)
        snap.fencing = oepoch
        snap.bind_journal(self._oj, every_lines=0)
        ha = HaCoordinator(oepoch, role="leader", journal=self._oj, snapshotter=snap)
        ha.become_leader()
        self.owner_store.create_namespace(Namespace("default"))
        snap.write(reason="bootstrap")
        self.owner_plugin = KubeThrottler(
            args, self.owner_store, use_device=True, start_workers=True
        )
        self.owner_http = ThrottlerHTTPServer(self.owner_plugin, port=0)
        self.owner_http.start()
        self._repl_server = ReplicationServer(
            ReplicationSource(owner_dir, self._oj, oepoch)
        )
        self._repl_server.start()

        self.replica_store = Store()
        self._rj = RecoveryManager(replica_dir).recover_store(self.replica_store)
        repoch = FencingEpoch(replica_dir)
        self._rj.fencing = repoch
        self.replicator = StandbyReplicator(
            self.replica_store,
            self._rj,
            f"http://127.0.0.1:{self._repl_server.port}",
            epoch=repoch,
            poll_interval=0.02,
        )
        if not self.replicator.bootstrap(30.0):
            raise RuntimeError("replica bootstrap failed")
        self.replicator.start()
        self.replica_plugin = KubeThrottler(
            args, self.replica_store, use_device=True, start_workers=True
        )
        self.gate = ReplicaGate(self.replicator, max_lag_s=max_lag_s)
        self.replica_http = ThrottlerHTTPServer(
            self.replica_plugin,
            port=0,
            replica_gate=self.gate,
            owner_url=f"http://127.0.0.1:{self.owner_http.port}",
        )
        self.replica_http.start()

    def stop(self):
        for closer in (
            self.replica_http.stop,
            self.replicator.stop,
            self.owner_http.stop,
            self._repl_server.stop,
            self.replica_plugin.stop,
            self.owner_plugin.stop,
            self._rj.close,
            self._oj.close,
        ):
            try:
                closer()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass


def run_replica_serving(
    seed: int = 0,
    pods: int = 800,
    throttles: int = 48,
    groups: int = 24,
    pace_hz: float = 200.0,
    flips: int = 12,
    flip_slo_ms: float = 150.0,
    storm_s: float = 8.0,
    max_lag_s: float = 5.0,
) -> Dict:
    from ..api.pod import make_pod
    from ..api.types import ResourceAmount
    from .measure import served_throttle

    host_cores = len(os.sched_getaffinity(0))
    report: Dict = {
        "scenario": "replica_serving",
        "seed": seed,
        "pods": pods,
        "throttles": throttles,
        "groups": groups,
        "pace_hz": pace_hz,
        "flip_burst": flips,
        "flip_slo_ms": flip_slo_ms,
        "host_cores": host_cores,
        "gates": {},
    }
    rng = random.Random(seed)
    workdir = tempfile.mkdtemp(prefix="kt-replica-scn-")
    tier = _Tier(workdir, max_lag_s=max_lag_s)
    try:
        owner_store = tier.owner_store

        def bound_pod(name: str, grp: str, cpu_m: int):
            p = make_pod(
                name, labels={"grp": grp}, requests={"cpu": f"{cpu_m}m"}
            )
            p = _replace(p, spec=_replace(p.spec, node_name="n0"))
            p.status.phase = "Running"
            return p

        # topology: served_throttle's threshold classes, plus a FLIP BAND —
        # one hot throttle per flip whose cpu threshold starts ABOVE its
        # group's usage (not throttled) so the burst's edit flips it hard
        for i in range(throttles):
            owner_store.create_throttle(served_throttle(i, groups))
        flip_keys: List[str] = []
        for k in range(flips):
            thr = served_throttle(1_000 + k, groups)
            thr = _replace(
                thr,
                name=f"flip{k}",
                spec=_replace(
                    thr.spec,
                    threshold=ResourceAmount.of(requests={"cpu": "100000m"}),
                ),
            )
            owner_store.create_throttle(thr)
            flip_keys.append(thr.key)
        for i in range(pods):
            owner_store.create_pod(
                bound_pod(f"p{i}", f"g{i % groups}", (i % 7 + 1) * 100)
            )

        # replica catches up: same object population, then both plugins'
        # controllers settle
        synced = _wait(
            lambda: len(tier.replica_store.list_pods("default")) == pods
            and len(tier.replica_store.list_throttles()) == throttles + flips,
            timeout=60.0,
        )
        report["bootstrap_synced"] = synced
        if not synced:
            report["gates"]["verdicts"] = {"pass": False, "error": "never synced"}
            report["pass"] = False
            return report
        for plg in (tier.owner_plugin, tier.replica_plugin):
            _wait(
                lambda p=plg: len(p.throttle_ctr.workqueue) == 0
                and len(p.cluster_throttle_ctr.workqueue) == 0,
                timeout=60.0,
            )

        # the probe population: one representative pod per group (NOT in
        # the store — pure admission probes, so churn can't delete them)
        probes = [
            make_pod(f"probe-g{g}", labels={"grp": f"g{g}"}, requests={"cpu": "100m"})
            for g in range(groups)
        ]

        # ---- the storm: paced pod churn on the OWNER + a replica-serving
        # hammer. Writes go through the owner store (the leader's ingest
        # surface); reads hammer the replica plugin (the tier under test).
        stop = threading.Event()
        pause = threading.Event()  # set ⇒ churner idles (quiesced oracle cut)
        # Concurrency contract for the shared tallies below (no locks, no
        # GUARDED_BY — closure state, not class attrs): each cell has ONE
        # writer (churn_done ← churner thread, served ← hammer thread;
        # serve_errors is append-only from either, and list.append is
        # GIL-atomic). The main thread only reads them after stop.set()
        # + join(), which is the happens-before edge — mid-run reads
        # don't exist, so torn counts can't either.
        churn_done = [0]
        served = [0]
        serve_errors: List[str] = []

        def churner():
            try:
                _churn_loop()
            except Exception as e:  # noqa: BLE001 — a dead storm is a finding
                serve_errors.append(f"churner: {e!r}")

        def _churn_loop():
            crng = random.Random(seed + 1)
            period = 1.0 / pace_hz
            i = [pods]
            alive: List[str] = [f"p{j}" for j in range(pods)]
            while not stop.is_set():
                if pause.is_set():
                    time.sleep(0.01)
                    continue
                if crng.random() < 0.5 or not alive:
                    name = f"p{i[0]}"
                    i[0] += 1
                    owner_store.create_pod(
                        bound_pod(
                            name,
                            f"g{crng.randrange(groups)}",
                            crng.randrange(1, 8) * 100,
                        )
                    )
                    alive.append(name)
                else:
                    victim = alive.pop(crng.randrange(len(alive)))
                    try:
                        owner_store.delete_pod("default", victim)
                    except Exception:  # noqa: BLE001 — already gone is fine
                        pass
                churn_done[0] += 1
                time.sleep(period)

        def server_hammer():
            # paced, not flat-out: an unthrottled cache-hit loop would
            # monopolize the GIL on a 1-core harness and starve the very
            # controller threads whose flip propagation the lag gate times
            srng = random.Random(seed + 2)
            while not stop.is_set():
                try:
                    tier.replica_plugin.pre_filter(
                        probes[srng.randrange(len(probes))]
                    )
                    served[0] += 1
                except Exception as e:  # noqa: BLE001 — a serving crash is a finding
                    serve_errors.append(repr(e))
                    return
                time.sleep(0.001)

        threads = [
            threading.Thread(target=churner),
            threading.Thread(target=server_hammer),
        ]
        cache_hits_before = tier.replica_plugin.verdict_cache.stats()[0]
        for t in threads:
            t.start()

        # ---- the leader flip burst, mid-storm: force each flip throttle
        # across its threshold and time owner-publication → replica-verdict.
        time.sleep(min(1.0, storm_s / 4))
        lags_ms: List[float] = []
        flip_wrong: List[str] = []
        flip_timeouts = 0
        for k, key in enumerate(flip_keys):
            ns, name = key.split("/")
            thr = owner_store.get_throttle(ns, name)
            was = _cpu_throttled(thr)
            # flip hard: 1m throttles any non-empty group; 100000m clears
            new_mc = 1 if not was else 100_000
            owner_store.update_throttle_spec(
                _replace(
                    thr,
                    spec=_replace(
                        thr.spec,
                        threshold=ResourceAmount.of(requests={"cpu": f"{new_mc}m"}),
                    ),
                )
            )
            grp = thr.spec.selector.selector_terms[0].pod_selector.match_labels["grp"]
            probe = probes[int(grp[1:])]

            # owner publication: the flipped status lands in the owner store
            t_pub: Optional[float] = None
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                cur = owner_store.get_throttle(ns, name)
                if _cpu_throttled(cur) != was:
                    t_pub = time.monotonic()
                    break
                time.sleep(0.002)
            if t_pub is None:
                flip_timeouts += 1
                continue
            want = tier.owner_plugin.pre_filter(probe)

            # replica serving catches up: its verdict for the group probe
            # agrees with the owner's post-flip verdict
            t_rep: Optional[float] = None
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                got = tier.replica_plugin.pre_filter(probe)
                if got.code == want.code:
                    t_rep = time.monotonic()
                    break
                time.sleep(0.002)
            if t_rep is None:
                flip_timeouts += 1
                flip_wrong.append(f"{key}: replica never converged")
                continue
            lags_ms.append(max(0.0, (t_rep - t_pub) * 1e3))

            # cutover oracle: a QUIESCED cut — the lag above was timed
            # under live churn, but verdict equality is only defined at a
            # consistent state, so the churner pauses, both tiers settle,
            # and every pod of the flipped group must agree (replica's
            # cached serving path vs a fresh owner recompute)
            import tools.harness as H

            pause.set()
            _quiesce(tier, owner_store)
            for pod in owner_store.list_pods("default"):
                if pod.labels.get("grp") != grp:
                    continue
                got = tier.replica_plugin.pre_filter(pod)
                ref = tier.owner_plugin._pre_filter_uncached(
                    pod, emit_events=False
                )
                if got.code != ref.code or H.normalized_reasons(
                    got.reasons
                ) != H.normalized_reasons(ref.reasons):
                    flip_wrong.append(
                        f"{key}/{pod.name}: {got.code} vs {ref.code}"
                    )
            pause.clear()

        stop.set()
        for t in threads:
            t.join(timeout=30)
        cache_hits = (
            tier.replica_plugin.verdict_cache.stats()[0] - cache_hits_before
        )
        report["storm"] = {
            "churn_events": churn_done[0],
            "replica_decisions_served": served[0],
            "serve_errors": serve_errors[:3],
            "replica_cache_hits": cache_hits,
        }

        lags_sorted = sorted(lags_ms)
        lag_p99 = (
            lags_sorted[max(0, int(len(lags_sorted) * 0.99) - 1)]
            if lags_sorted
            else None
        )
        lag_max = lags_sorted[-1] if lags_sorted else None
        from .slo import _latency_gates_enforced

        enforced = _latency_gates_enforced()
        lag_ok = lag_p99 is not None and lag_p99 <= flip_slo_ms
        # unmeasurable flips and timeouts stay enforced on any host —
        # only the wall-clock p99 comparison degrades to advisory
        report["gates"]["lag"] = {
            "pass": bool(lags_sorted)
            and flip_timeouts == 0
            and (lag_ok or not enforced),
            "flips_measured": len(lags_sorted),
            "flip_timeouts": flip_timeouts,
            "lag_p99_ms": round(lag_p99, 1) if lag_p99 is not None else None,
            "lag_max_ms": round(lag_max, 1) if lag_max is not None else None,
            "bound_ms": flip_slo_ms,
        }
        if not enforced and not lag_ok and lags_sorted:
            report["gates"]["lag"]["note"] = (
                "ADVISORY (host below latency core floor) — would FAIL"
            )

        # ---- final convergence + full-population verdict sweep
        import tools.harness as H

        conv = _quiesce(tier, owner_store)
        wrong: List[str] = []
        checked = 0
        for pod in owner_store.list_pods("default"):
            got = tier.replica_plugin.pre_filter(pod)
            ref = tier.owner_plugin._pre_filter_uncached(pod, emit_events=False)
            checked += 1
            if got.code != ref.code or H.normalized_reasons(
                got.reasons
            ) != H.normalized_reasons(ref.reasons):
                wrong.append(f"{pod.key}: {got.code} vs {ref.code}")
        report["gates"]["verdicts"] = {
            "pass": conv and not flip_wrong and not wrong and not serve_errors,
            "converged": conv,
            "cutover_wrong": len(flip_wrong),
            "final_wrong": len(wrong),
            "final_checked": checked,
            "examples": (flip_wrong + wrong)[:5],
        }

        # ---- the staleness bound is ENFORCED: freeze the gate's clock
        # past the bound — reads refuse with 503 + the refusal is counted —
        # then unfreeze — reads serve again
        refused_before = tier.gate.refused_total
        real_clock = tier.gate._monotonic
        tier.gate._monotonic = lambda: (
            (tier.replicator.last_contact_monotonic or 0.0) + max_lag_s + 60.0
        )
        code_stale, body_stale, _ = _req(
            tier.replica_http.port,
            "POST",
            "/v1/prefilter",
            {"podKey": f"default/p{pods - 1}"},
        )
        tier.gate._monotonic = real_clock
        code_fresh, _, _ = _req(
            tier.replica_http.port,
            "POST",
            "/v1/prefilter",
            {"podKey": f"default/p{pods - 1}"},
        )
        report["gates"]["staleness_gate"] = {
            "pass": code_stale == 503
            and isinstance(body_stale, dict)
            and "stale" in body_stale.get("error", "")
            and tier.gate.refused_total > refused_before
            and code_fresh in (200, 404),
            "stale_status": code_stale,
            "fresh_status": code_fresh,
            "refusals": tier.gate.refused_total - refused_before,
        }

        # ---- forward-on-write: reserve through the REPLICA lands on the
        # owner's ledger, response marked as forwarded
        rsv = bound_pod("rsv0", "g0", 100)
        owner_store.create_pod(rsv)
        _wait(
            lambda: any(
                p.name == "rsv0" for p in tier.replica_store.list_pods("default")
            ),
            timeout=30.0,
        )
        code_fwd, _, headers = _req(
            tier.replica_http.port, "POST", "/v1/reserve", {"podKey": "default/rsv0"}
        )
        landed = _wait(
            lambda: any(
                "default/rsv0"
                in tier.owner_plugin.throttle_ctr.cache.reserved_pod_keys(t.key)
                for t in owner_store.list_throttles()
            ),
            timeout=30.0,
        )
        _req(
            tier.replica_http.port,
            "POST",
            "/v1/unreserve",
            {"podKey": "default/rsv0"},
        )
        report["gates"]["forwarding"] = {
            "pass": code_fwd == 200
            and headers.get("X-KT-Forwarded-By") == "replica"
            and landed,
            "status": code_fwd,
            "forwarded_by": headers.get("X-KT-Forwarded-By"),
            "landed_on_owner": landed,
        }

        # ---- the cache actually served the storm
        report["gates"]["cache"] = {
            "pass": cache_hits > 0 and served[0] > 0,
            "hits": cache_hits,
            "decisions": served[0],
        }

        report["pass"] = all(g["pass"] for g in report["gates"].values())
        return report
    finally:
        tier.stop()
        shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="scenarios.replica")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--pace", type=float, default=200.0)
    parser.add_argument("--flips", type=int, default=12)
    parser.add_argument("--flip-slo-ms", type=float, default=150.0)
    parser.add_argument("--json", default="", help="write the report here too")
    args = parser.parse_args(argv)
    report = run_replica_serving(
        seed=args.seed,
        pace_hz=args.pace,
        flips=args.flips,
        flip_slo_ms=args.flip_slo_ms,
    )
    print(json.dumps(report, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
