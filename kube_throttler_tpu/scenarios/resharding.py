"""Resharding chaos scenario: live scale 2→4→3 under storm load, with a
kill-mid-handoff episode.

The PR 9 sharded bad-day scenario proved the scatter-gather plane
survives a shard death; this one proves the plane survives TOPOLOGY
CHANGE while serving. The composed ``bad_day`` trace replays through a
2-shard front at storm pace; mid-replay the supervisor live-splits to 4
shards — with a chaos flag arming ``reshard.dest.crash:kill`` on one NEW
worker's first incarnation, so the destination SIGKILLs mid-warm-up, the
coordinator aborts back to the source, the monitor respawns the worker
clean, and the retry cuts over — then live-merges 4→3. No restarts of
surviving workers, no replay pause.

Gates:

- **reshard**: both rescales complete inside the replay window, the
  armed kill demonstrably fired (≥1 abort observed + ≥1 worker restart),
  and the killed worker rejoined;
- **verdicts**: zero wrong verdicts — after convergence every pod's
  sharded ``pre_filter`` equals a single-process oracle rebuilt from the
  final state (code + normalized reasons);
- **flips** (zero LOST flips): every front-store throttle's published
  ``status.throttled`` flags equal the oracle's deterministic recompute
  — a flip dropped in a cutover (computed by the destination during
  warm-up, never re-published) would show here as a stale flag;
- **flip_p99**: crossing-anchored flip publication bounded OUTSIDE the
  handoff windows (a flip whose crossing lands inside a window may ride
  the cutover's re-publication path; the windows are reported);
- **orphans**: after the run every shard's ``reshard_audit`` is clean —
  zero reservations against throttles the shard no longer holds, zero
  pending handoffs, zero standing fences.

Run: ``python -m kube_throttler_tpu.scenarios.resharding``
(wired into ``make scenario-test``).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["run_resharding_storm"]

logger = logging.getLogger(__name__)

_WINDOW_PAD_S = 0.25
UNDERSUBSCRIBED_PACE_HZ = 600.0
STORM_PACE_HZ = 1200.0


def _build_stack(n_shards: int):
    from ..sharding.front import AdmissionFront
    from ..sharding.supervisor import ShardSupervisor

    front = AdmissionFront(n_shards)
    supervisor = ShardSupervisor(
        front,
        use_device=True,
        restart_backoff=0.3,
        env={**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"},
    )
    supervisor.start(ready_timeout=300.0)
    return front, supervisor


def run_resharding_storm(
    seed: int = 0,
    pace_hz: Optional[float] = None,
    min_pace_frac: float = 0.6,
    flip_p99_ms: float = 300.0,
    rescale_deadline_s: float = 150.0,
    scenario_name: str = "bad_day",
    scale_path: Tuple[int, ...] = (2, 4, 3),
    kill_mid_handoff: bool = True,
) -> Dict:
    from .engine import _materialize_pod, _pod_fields, _seed_remote_store
    from .corpus import get_scenario
    from .measure import count_watch_of, flip_watch_of, group_keys_of, lag_tracker
    from .trace import build_topology, build_trace, serialize_trace, trace_sha256

    host_cores = len(os.sched_getaffinity(0))
    undersubscribed = host_cores < max(scale_path) + 1
    if pace_hz is None or pace_hz <= 0:
        pace_hz = UNDERSUBSCRIBED_PACE_HZ if undersubscribed else STORM_PACE_HZ
    scn = get_scenario(scenario_name)
    topology = build_topology(scn, seed)
    header, ops = build_trace(scn, seed)
    trace_sha = trace_sha256(serialize_trace(header, ops))
    front, supervisor = _build_stack(scale_path[0])
    report: Dict = {
        "scenario": f"resharding_{scenario_name}",
        "scale_path": list(scale_path),
        "seed": seed,
        "trace_sha256": trace_sha,
        "pace_hz": pace_hz,
        "host_cores": host_cores,
        "undersubscribed": undersubscribed,
        "gates": {},
    }
    rescale_reports: List[Dict] = []
    rescale_windows: List[List[float]] = []  # [t0, t1] perf_counter
    rescale_errors: List[str] = []
    try:
        _seed_remote_store(front.store, scn, topology)
        front.drain(timeout=300.0)
        time.sleep(0.5)

        pending, flip_pending, pend_lock, _lags, flip_lags, flip_walls, on_write = (
            lag_tracker()
        )
        group_keys = group_keys_of(front.store)
        flip_watch, run_sums = flip_watch_of(front.store)
        count_watch, run_counts = count_watch_of(front.store)
        front.store.add_event_handler("Throttle", on_write, replay=False)

        from ..engine.ingest import MicroBatchIngest

        pipeline = MicroBatchIngest(front.store, batch_policy="adaptive")

        # rescale episodes fire at fixed fractions of the trace, in a
        # worker thread — the replay must keep pacing THROUGH the handoff
        # (that is the whole point of live resharding)
        def run_rescale(step: int, n_new: int) -> None:
            t0 = time.perf_counter()
            spawn_args = None
            if step == 0 and kill_mid_handoff:
                # arm the kill on the FIRST new worker's first incarnation
                # only: SIGKILL at its 2nd import chunk (mid-warm-up); the
                # monitor respawn comes up clean and the retry succeeds
                sid = supervisor.n_shards
                spawn_args = {
                    sid: ["--fault-site", "reshard.dest.crash:kill:1"]
                }
            try:
                rep = supervisor.rescale(
                    n_new,
                    handoff_deadline_s=rescale_deadline_s,
                    spawn_args=spawn_args,
                )
                rescale_reports.append(rep)
            except Exception as e:  # noqa: BLE001 — gate evidence, not a crash
                logger.exception("rescale to %d failed", n_new)
                rescale_errors.append(f"rescale->{n_new}: {e}")
            finally:
                rescale_windows.append([t0, time.perf_counter()])

        # ONE runner thread walks the whole scale path (rescale() is
        # one-at-a-time by contract), each step gated on replay progress
        op_counter = [0]
        replay_done = threading.Event()

        def rescale_runner() -> None:
            # top-level routing (threads checker): a dead runner means the
            # scale path silently never completes while the replay stays
            # green — route the death into the reshard gate's evidence
            try:
                for step, n_new in enumerate(scale_path[1:]):
                    target_idx = int(len(ops) * (0.25 + 0.35 * step))
                    while op_counter[0] < target_idx and not replay_done.is_set():
                        time.sleep(0.05)
                    run_rescale(step, n_new)
            except Exception as e:  # noqa: BLE001 — gate evidence, not a crash
                logger.exception("rescale runner died")
                rescale_errors.append(f"runner: {e!r}")

        runner = threading.Thread(
            target=rescale_runner, name="rescale-runner", daemon=True
        )
        n_applied_target = 0
        t0 = time.perf_counter()
        runner.start()
        for i, op in enumerate(ops):
            next_at = t0 + i / pace_hz
            delay = next_at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            op_counter[0] = i
            verb = op["verb"]
            now = time.perf_counter()
            grp = op.get("grp")
            with pend_lock:
                for key in group_keys.get(grp, ()):
                    pending.setdefault(key, now)
                if verb in ("update_pod", "create_pod", "delete_pod"):
                    watch = flip_watch.get(grp)
                    if watch:
                        s_old = run_sums.get(grp, 0)
                        s_new = s_old + op["cpu_m"] - op["prev_m"]
                        run_sums[grp] = s_new
                        for key, thr_mc in watch:
                            if (s_old >= thr_mc) != (s_new >= thr_mc):
                                flip_pending[key] = now
                    cwatch = count_watch.get(grp)
                    if cwatch and verb != "update_pod":
                        c_old = run_counts.get(grp, 0)
                        c_new = c_old + (1 if verb == "create_pod" else -1)
                        run_counts[grp] = c_new
                        for key, thr_n in cwatch:
                            if (c_old >= thr_n) != (c_new >= thr_n):
                                flip_pending[key] = now
            if verb in ("update_pod", "create_pod"):
                pod = _materialize_pod(
                    op["name"], op["grp"], op.get("node", "n0"), op["cpu_m"],
                    **_pod_fields(op),
                )
                pipeline.submit("upsert", "Pod", pod)
                n_applied_target += 1
            elif verb == "delete_pod":
                pipeline.submit("delete", "Pod", f"default/{op['name']}")
                n_applied_target += 1
            elif verb == "update_throttle":
                try:
                    thr = front.store.get_throttle("default", op["name"])
                except Exception:  # noqa: BLE001
                    continue
                from dataclasses import replace as _replace

                from ..api.types import ResourceAmount

                front.store.update_throttle_spec(
                    _replace(
                        thr,
                        spec=_replace(
                            thr.spec,
                            threshold=ResourceAmount.of(
                                pod=op.get("pod_threshold", 10)
                            ),
                        ),
                    )
                )
        t_fired = time.perf_counter() - t0
        replay_done.set()
        pipeline.flush(timeout=120.0)
        front.drain(timeout=300.0)
        # the sustain clock stops HERE: fire window + ingest drain. The
        # rescale runner may still be warming a destination — that wait
        # is the reshard gate's bookkeeping, not ingest.
        t_sustain = time.perf_counter() - t0
        runner.join(timeout=(rescale_deadline_s + 120.0) * len(scale_path))
        front.drain(timeout=300.0)
        time.sleep(1.5)
        pipe_stats = pipeline.stats()
        front.store.remove_event_handler("Throttle", on_write)
        pipeline.stop()

        sustained = pipe_stats["events_applied"] / t_sustain
        report["events"] = pipe_stats["events_applied"]
        report["fired_hz"] = round(len(ops) / t_fired, 1)
        report["sustained_hz"] = round(sustained, 1)
        from .slo import _latency_gates_enforced

        enforced = _latency_gates_enforced()
        pace_ok = sustained >= pace_hz * min_pace_frac
        # dropped events are a correctness failure on any host; only the
        # sustained-rate comparison is host-speed-dependent
        report["gates"]["pace"] = {
            "pass": (pace_ok or not enforced) and pipe_stats["dropped"] == 0,
            "sustained_hz": round(sustained, 1),
            "target_hz": pace_hz,
            "min_frac": min_pace_frac,
        }
        if not enforced and not pace_ok:
            report["gates"]["pace"]["note"] = (
                "ADVISORY (host below latency core floor) — would FAIL"
            )

        aborts = sum(r.get("aborts", 0) for r in rescale_reports)
        restarts = supervisor.restart_counts()
        final_state, _detail = front._shards_health()
        report["rescales"] = rescale_reports
        report["gates"]["reshard"] = {
            "pass": (
                not rescale_errors
                and len(rescale_reports) == len(scale_path) - 1
                and front.n_shards == scale_path[-1]
                and (not kill_mid_handoff or aborts >= 1)
                and (not kill_mid_handoff or sum(restarts.values()) >= 1)
                and final_state == "ok"
            ),
            "errors": rescale_errors,
            "aborts": aborts,
            "restarts": restarts,
            "final_shards": front.n_shards,
            "final_health": final_state,
            "windows_s": [
                [round(w[0] - t0, 2), round(w[1] - t0, 2)]
                for w in rescale_windows
            ],
        }

        # flip p99 outside the handoff windows (a crossing anchored inside
        # one may ride the cutover's re-publication path; the reshard gate
        # bounds the windows themselves)
        def in_window(anchor: float) -> bool:
            return any(
                w[0] - _WINDOW_PAD_S <= anchor <= w[1] + _WINDOW_PAD_S
                for w in rescale_windows
            )

        samples = [
            lag for lag, wall in zip(flip_lags, flip_walls)
            if not in_window(wall - lag)
        ]
        if samples:
            p50 = float(np.percentile(np.asarray(samples), 50)) * 1e3
            p99 = float(np.percentile(np.asarray(samples), 99)) * 1e3
        else:
            p50 = p99 = 0.0
        flip_ok = p99 <= flip_p99_ms
        # unmeasurable (zero samples) stays enforced on any host
        report["gates"]["flip_p99"] = {
            "pass": (flip_ok or not enforced) and len(samples) > 0,
            "p50_ms": round(p50, 1),
            "p99_ms": round(p99, 1),
            "bound_ms": flip_p99_ms,
            "samples": len(samples),
            "window_excluded": max(0, len(flip_lags) - len(samples)),
        }
        if not enforced and not flip_ok:
            report["gates"]["flip_p99"]["note"] = (
                "ADVISORY (host below latency core floor) — would FAIL"
            )

        # oracle: verdicts + zero lost flips (flags ≡ deterministic recompute)
        import tools.harness as H
        from ..api.pod import Namespace
        from ..engine.store import Store

        oracle_store = Store()
        oracle_store.create_namespace(Namespace("default"))
        for thr in front.store.list_throttles():
            oracle_store.create_throttle(thr)
        for pod in front.store.list_pods():
            oracle_store.create_pod(pod)
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        wrong = []
        for pod in oracle_store.list_pods():
            got = front.pre_filter(pod)
            want = oracle.pre_filter(pod)
            if got.code != want.code or H.normalized_reasons(
                got.reasons
            ) != H.normalized_reasons(want.reasons):
                wrong.append(pod.key)
        report["gates"]["verdicts"] = {
            "pass": not wrong,
            "wrong": len(wrong),
            "checked": len(oracle_store.list_pods()),
            "examples": wrong[:5],
        }
        stale_flags = []
        oracle_by_key = {t.key: t for t in oracle_store.list_throttles()}
        for thr in front.store.list_throttles():
            want = oracle_by_key.get(thr.key)
            if want is not None and thr.status.throttled != want.status.throttled:
                stale_flags.append(thr.key)
        report["gates"]["flips"] = {
            "pass": not stale_flags,
            "stale": len(stale_flags),
            "checked": len(oracle_by_key),
            "examples": stale_flags[:5],
        }

        # zero orphans: every shard's reshard audit must come back clean
        audit_bad: List[str] = []
        audits = {}
        for sid in range(front.n_shards):
            handle = front.shards.get(sid)
            if handle is None or not handle.alive:
                audit_bad.append(f"shard-{sid}: down")
                continue
            try:
                a = handle.request("reshard_audit", None, timeout=30.0)
            except Exception as e:  # noqa: BLE001 — a dark shard fails the gate
                audit_bad.append(f"shard-{sid}: {e}")
                continue
            audits[sid] = a
            if a["orphan_reservations"]:
                audit_bad.append(
                    f"shard-{sid}: orphan reservations {a['orphan_reservations'][:3]}"
                )
            if a["pending_handoffs"]:
                audit_bad.append(f"shard-{sid}: pending handoffs")
            if a["fenced_handoffs"]:
                audit_bad.append(f"shard-{sid}: standing fences")
        report["gates"]["orphans"] = {
            "pass": not audit_bad,
            "bad": audit_bad,
            "audits": audits,
        }

        report["pass"] = all(g["pass"] for g in report["gates"].values())
        return report
    finally:
        supervisor.stop()
        front.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="scenarios.resharding")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--pace", type=float, default=0.0,
        help="replay pace in ev/s; 0 = auto (host-core aware)",
    )
    parser.add_argument("--scenario", default="bad_day")
    parser.add_argument(
        "--scale-path", default="2,4,3",
        help="comma-separated shard counts the run walks through",
    )
    parser.add_argument("--no-kill", action="store_true",
                        help="skip the kill-mid-handoff episode")
    parser.add_argument("--json", default="", help="write the report here too")
    args = parser.parse_args(argv)
    scale_path = tuple(int(s) for s in args.scale_path.split(",") if s)
    report = run_resharding_storm(
        seed=args.seed,
        pace_hz=args.pace,
        scenario_name=args.scenario,
        scale_path=scale_path,
        kill_mid_handoff=not args.no_kill,
    )
    print(json.dumps(report, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
