"""Partition bad-day scenario: the composed bad-day trace replayed
through a TCP shard fleet with a seeded asymmetric partition + heal.

The sharded runner (scenarios/sharded.py) proves the multiprocess stack
over inherited socketpairs — same host, kernel-guaranteed delivery, no
reconnects. This runner drives the SAME trace bytes (``build_trace`` of
the corpus' ``bad_day`` entry; the report pins the sha) through the
cross-host transport instead: a ``transport="tcp"`` supervisor fleet
where every front→worker frame can refuse, tear, stall, or blackhole
(the ``net.*`` sites, faults/plan.py).

Mid-storm, ONE shard's client-side plan arms ``net.partition`` for a
wall-clock window: that client's sends blackhole (asymmetric — the
worker stays healthy and can still talk, the front just can't reach it),
the maintainer thread churns through reconnect backoff, and verdicts
for the dark shard degrade fail-safe. When the window closes the client
heals, the supervisor's ``on_up`` bumps the fencing epoch and resyncs,
and any frame the partitioned-then-healed path held onto arrives stale
and is fenced by the worker. A seeded ``net.send.torn_frame`` rule adds
one mid-stream tear after heal so the reconnect path runs twice.

Gates (all deterministic — no timing SLO; the partition window IS the
latency story):

- **verdicts**: zero wrong verdicts vs a single-process oracle rebuilt
  from the final state (code + normalized reasons);
- **flips**: zero lost flips — every published ``status.throttled``
  equals the oracle's (heal ⇒ resync + re-push, nothing dropped while
  the send queue was dark);
- **recovery**: heal→converged (every shard ``ok``) within the bound;
- **audits**: clean two-phase state on every worker — zero orphan
  reservations, zero pending/fenced handoffs;
- **fencing**: the partition was REAL (connection losses observed,
  reconnects counted) and the healed client runs at a bumped epoch with
  the worker's ``wire_epoch`` agreeing.

Run: ``python -m kube_throttler_tpu.scenarios.partition --seed 0``
(wired into ``make scenario-test``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

__all__ = ["run_partition_bad_day"]


def _build_fleet(n_shards: int, rpc_deadline: float):
    from ..sharding.front import AdmissionFront
    from ..sharding.supervisor import ShardSupervisor

    front = AdmissionFront(n_shards, rpc_deadline=rpc_deadline)
    supervisor = ShardSupervisor(
        front,
        transport="tcp",
        # device ON like the sharded runner: the flip lane (the zero-lost-
        # flips gate's subject) lives on the device mirror
        use_device=True,
        restart_backoff=0.3,
        env={**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"},
    )
    supervisor.start(ready_timeout=300.0)
    return front, supervisor


def run_partition_bad_day(
    n_shards: int = 2,
    seed: int = 0,
    pace_hz: float = 500.0,
    partition_at_frac: float = 0.35,
    partition_s: float = 2.0,
    recovery_s: float = 20.0,
    rpc_deadline: float = 10.0,
    scenario_name: str = "bad_day",
) -> Dict:
    from ..faults.plan import FaultPlan
    from .corpus import get_scenario
    from .engine import _materialize_pod, _pod_fields, _seed_remote_store
    from .trace import build_topology, build_trace, serialize_trace, trace_sha256

    host_cores = len(os.sched_getaffinity(0))
    # byte-identical bad-day trace: built from the CORPUS entry, not from
    # partition_bad_day — the net faults live client-side, outside the
    # trace, so the replayed bytes equal the composed bad day's exactly
    scn = get_scenario(scenario_name)
    topology = build_topology(scn, seed)
    header, ops = build_trace(scn, seed)
    trace_sha = trace_sha256(serialize_trace(header, ops))

    front, supervisor = _build_fleet(n_shards, rpc_deadline)
    target_sid = 1 if n_shards > 1 else 0
    replay_len = len(ops) / pace_hz
    t_part = replay_len * partition_at_frac
    window = (t_part, t_part + partition_s)

    report: Dict = {
        "scenario": "partition_bad_day",
        "trace_scenario": scenario_name,
        "shards": n_shards,
        "seed": seed,
        "trace_sha256": trace_sha,
        "pace_hz": pace_hz,
        "host_cores": host_cores,
        "partitioned_shard": target_sid,
        "partition_window_s": [round(window[0], 2), round(window[1], 2)],
        "gates": {},
    }
    try:
        _seed_remote_store(front.store, scn, topology)
        front.drain(timeout=300.0)
        time.sleep(0.5)

        # the asymmetric partition: a client-side plan on ONE shard's
        # handle (TcpShardClient reads .faults per frame, so installing
        # it post-start is race-free w.r.t. the initial sync). The wall
        # clock anchors at replay start; the torn-frame rule fires once
        # after the heal so reconnect+resync runs a second time.
        handle = front.shards[target_sid]
        plan = FaultPlan(seed=seed)
        plan.rule("net.partition", mode="error", window=window)
        plan.rule(
            "net.send.torn_frame", mode="torn", times=1,
            window=(window[1] + 1.0, replay_len + 60.0),
        )
        t0_box: List[float] = [float("inf")]
        plan.set_time_source(lambda: time.perf_counter() - t0_box[0])
        handle.faults = plan

        from ..engine.ingest import MicroBatchIngest

        pipeline = MicroBatchIngest(front.store, batch_policy="adaptive")
        losses_before = dict(supervisor.connection_losses())
        t0 = time.perf_counter()
        t0_box[0] = t0
        for i, op in enumerate(ops):
            next_at = t0 + i / pace_hz
            delay = next_at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            verb = op["verb"]
            if verb in ("update_pod", "create_pod"):
                pipeline.submit(
                    "upsert", "Pod",
                    _materialize_pod(
                        op["name"], op["grp"], op.get("node", "n0"),
                        op["cpu_m"], **_pod_fields(op),
                    ),
                )
            elif verb == "delete_pod":
                pipeline.submit("delete", "Pod", f"default/{op['name']}")
        pipeline.flush(timeout=120.0)
        t_heal = t0 + window[1]

        # recovery: heal→converged, measured from the window's CLOSE (the
        # partition window itself is scheduled downtime, not recovery)
        rec_deadline = max(time.monotonic(), time.monotonic() + (
            t_heal - time.perf_counter()
        )) + recovery_s
        recovered_at: Optional[float] = None
        while time.monotonic() < rec_deadline:
            state, _ = front._shards_health()
            if state == "ok" and time.perf_counter() >= t_heal:
                recovered_at = time.perf_counter()
                break
            time.sleep(0.1)
        front.drain(timeout=300.0)
        time.sleep(1.0)
        pipe_stats = pipeline.stats()
        pipeline.stop()

        heal_lag = (
            max(0.0, recovered_at - t_heal) if recovered_at is not None else None
        )
        report["events"] = pipe_stats["events_applied"]
        report["dropped"] = pipe_stats["dropped"]
        report["gates"]["recovery"] = {
            "pass": recovered_at is not None,
            "heal_to_converged_s": (
                round(heal_lag, 2) if heal_lag is not None else None
            ),
            "bound_s": recovery_s,
        }

        # fencing evidence: the partition must have been REAL (the client
        # observably lost and re-established its primary lane) and the
        # healed path must run at a BUMPED epoch the worker agrees on
        losses_after = supervisor.connection_losses()
        conn_lost = losses_after.get(target_sid, 0) - losses_before.get(
            target_sid, 0
        )
        worker_stats: Dict = {}
        try:
            worker_stats = handle.request("stats", None, timeout=30.0)
        except Exception as e:  # noqa: BLE001 — a dark shard fails the gate
            worker_stats = {"error": repr(e)}
        client_epoch = getattr(handle, "epoch", 0)
        wire_epoch = worker_stats.get("wire_epoch", -1)
        report["gates"]["fencing"] = {
            "pass": (
                conn_lost >= 1
                and handle.reconnects >= 1
                and client_epoch >= 2
                and wire_epoch == client_epoch
            ),
            "connection_losses": conn_lost,
            "reconnects": handle.reconnects,
            "client_epoch": client_epoch,
            "worker_wire_epoch": wire_epoch,
            "fenced_frames": worker_stats.get("fenced_frames"),
            "restarts": supervisor.restart_counts(),
        }

        # zero wrong verdicts + zero lost flips vs the rebuilt oracle
        import tools.harness as H
        from ..api.pod import Namespace
        from ..engine.store import Store

        oracle_store = Store()
        oracle_store.create_namespace(Namespace("default"))
        for thr in front.store.list_throttles():
            oracle_store.create_throttle(thr)
        for pod in front.store.list_pods():
            oracle_store.create_pod(pod)
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        wrong = []
        for pod in oracle_store.list_pods():
            got = front.pre_filter(pod)
            want = oracle.pre_filter(pod)
            if got.code != want.code or H.normalized_reasons(
                got.reasons
            ) != H.normalized_reasons(want.reasons):
                wrong.append(pod.key)
        report["gates"]["verdicts"] = {
            "pass": not wrong,
            "wrong": len(wrong),
            "checked": len(oracle_store.list_pods()),
            "examples": wrong[:5],
        }
        oracle_by_key = {t.key: t for t in oracle_store.list_throttles()}
        stale = [
            thr.key
            for thr in front.store.list_throttles()
            if (w := oracle_by_key.get(thr.key)) is not None
            and thr.status.throttled != w.status.throttled
        ]
        report["gates"]["flips"] = {
            "pass": not stale, "stale": len(stale), "examples": stale[:5],
        }

        # clean two-phase audits on every worker
        audit_bad = []
        for sid in range(front.n_shards):
            h = front.shards.get(sid)
            if h is None or not h.alive:
                audit_bad.append(f"shard-{sid}: down")
                continue
            try:
                a = h.request("reshard_audit", None, timeout=30.0)
            except Exception as e:  # noqa: BLE001 — a dark shard fails the gate
                audit_bad.append(f"shard-{sid}: {e}")
                continue
            if a["orphan_reservations"] or a["pending_handoffs"] or a["fenced_handoffs"]:
                audit_bad.append(f"shard-{sid}: {a}")
        report["gates"]["audits"] = {"pass": not audit_bad, "bad": audit_bad}

        report["net"] = {
            "partition_fired": plan.hits("net.partition") > 0
            and bool(plan.history.get("net.partition")),
            "torn_fired": bool(plan.history.get("net.send.torn_frame")),
            "deadline_exceeded": getattr(handle, "deadline_exceeded", 0),
            "partition_seconds": round(
                getattr(handle, "outage_seconds", lambda: 0.0)(), 2
            ),
        }
        report["pass"] = all(g["pass"] for g in report["gates"].values())
        return report
    finally:
        supervisor.stop()
        front.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="scenarios.partition")
    parser.add_argument("--shards", type=int, default=2)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--pace", type=float, default=500.0)
    parser.add_argument("--partition-s", type=float, default=2.0)
    parser.add_argument("--json", default="", help="write the report here too")
    args = parser.parse_args(argv)
    report = run_partition_bad_day(
        n_shards=args.shards, seed=args.seed, pace_hz=args.pace,
        partition_s=args.partition_s,
    )
    print(json.dumps(report, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
