"""Coverage-guided adversarial scenario search over the scenario DSL.

The PR 8 engine replays the scenarios somebody wrote; production breaks
systems with the scenario nobody wrote. This package searches the
(arrival × topology × fault-schedule) space for it:

- **mutate.py** — seeded, pure-function mutations of ``dsl.Scenario``
  programs. ``mutate(program, seed)`` is byte-deterministic: the child is
  a pure function of (program content, seed), children are
  content-addressed (``hunt-<sha12>`` names), and every child preserves
  the PR 8 trace property (``build_trace`` purity holds for any valid
  program, so same child + same trace seed ⇒ identical trace bytes).
- **coverage.py** — the novelty signal: a run fingerprints as the set of
  fired fault sites (log-bucketed hit counts), ``kube_throttler_*``
  metric-family deltas, and health-component state transitions (the
  engine's structured ``report["fingerprint"]``). The corpus keeps only
  children that reach coverage nobody reached before, weighted by how
  much new behavior they found.
- **shrink.py** — when a run fails an SLO gate (or the zero-wrong-verdicts
  sweep trips), bisect the program — drop faults, strip pattern/arrival
  structure, shed topology mass, shorten — re-replaying each candidate in
  a FRESH interpreter; byte-determinism makes every re-replay exact, so
  shrinking is sound. The minimal repro is promoted into
  ``scenarios/corpus/regressions/`` as a permanent tier gate
  (corpus.load_regressions).
- **loop.py** — the budgeted search loop + coverage-report artifact.
- **longhorizon.py** — the multi-virtual-day soak tier (diurnal day
  cycles, restart waves, durability churn, the 1M-pod columnar rung).

Drivers: ``make scenario-hunt`` (budgeted random search),
``make scenario-hunt-smoke`` (CI: planted-bug find → shrink → promote),
``make scenario-hunt-long`` (the long-horizon tier).
"""

from .coverage import CoverageMap, fingerprint_keys  # noqa: F401
from .mutate import MUTABLE_FAULT_SITES, mutate, program_sha, program_size  # noqa: F401
from .shrink import shrink  # noqa: F401

__all__ = [
    "CoverageMap",
    "MUTABLE_FAULT_SITES",
    "fingerprint_keys",
    "mutate",
    "program_sha",
    "program_size",
    "shrink",
]
