"""The hunt's coverage signal: run fingerprints → novelty accounting.

A run's behavior is fingerprinted by the engine's structured
``report["fingerprint"]`` (scenarios/engine.py): which fault sites
actually fired and how often, which ``kube_throttler_*`` metric families
the run moved, and which health-component state transitions it drove.
``fingerprint_keys`` flattens that into a set of discrete coverage keys:

- ``fault:<site>:<bucket>`` — fired sites, hit counts log2-bucketed
  (1, 2, 4, 8, …) so "fired a lot more" is new coverage but "fired 37 vs
  38 times" is not;
- ``metric:<family>`` — a family whose series/values moved during the
  run (post-convergence baseline delta);
- ``health:<component>:<old>-><new>`` — an observed state transition;
- ``gate:<name>:<pass|fail>`` — each SLO gate's verdict (a mutant that
  makes a *different gate* fail is novel even at equal fault coverage).

``CoverageMap`` is the accumulator: ``observe(keys)`` returns how many
keys were globally new — the child's novelty score, the corpus
admission criterion, and its priority-queue weight in the loop.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List

__all__ = ["CoverageMap", "fingerprint_keys", "hit_bucket"]


def hit_bucket(hits: int) -> int:
    """Log2 bucket of a hit count: 0→0, 1→1, 2-3→2, 4-7→4, 8-15→8, …"""
    if hits <= 0:
        return 0
    b = 1
    while b * 2 <= hits:
        b *= 2
    return b


def fingerprint_keys(report: Dict) -> FrozenSet[str]:
    """Flatten one run report (engine schema) into coverage keys."""
    keys = set()
    fp = report.get("fingerprint") or {}
    for site, hits in (fp.get("fault_sites") or {}).items():
        keys.add(f"fault:{site}:{hit_bucket(int(hits))}")
    for family in fp.get("metric_families") or {}:
        keys.add(f"metric:{family}")
    for item in fp.get("health_transitions") or []:
        comp, old, new = item[0], item[1], item[2]
        keys.add(f"health:{comp}:{old}->{new}")
    for gate, g in (report.get("gates") or {}).items():
        keys.add(f"gate:{gate}:{'pass' if g.get('pass') else 'fail'}")
    return frozenset(keys)


class CoverageMap:
    """Global coverage accumulator. Single-threaded by design (the hunt
    loop is sequential — one fresh-interpreter evaluation at a time, so
    coverage order is deterministic given the iteration order)."""

    def __init__(self) -> None:
        self._seen: Dict[str, int] = {}  # key → times observed

    def observe(self, keys: Iterable[str]) -> int:
        """Record a run's keys; returns the count of globally-new ones
        (the run's novelty)."""
        new = 0
        for key in keys:
            if key not in self._seen:
                new += 1
            self._seen[key] = self._seen.get(key, 0) + 1
        return new

    def __len__(self) -> int:
        return len(self._seen)

    def __contains__(self, key: str) -> bool:
        return key in self._seen

    def keys_by_class(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for key in sorted(self._seen):
            out.setdefault(key.split(":", 1)[0], []).append(key)
        return out

    def report(self) -> Dict:
        """The coverage-report artifact body: totals per key class plus
        the full sorted key list (the CI artifact diffable across
        nights)."""
        by_class = self.keys_by_class()
        return {
            "coverage_keys": len(self._seen),
            "by_class": {cls: len(ks) for cls, ks in sorted(by_class.items())},
            "fault_sites_reached": sorted(
                {k.split(":")[1] for k in by_class.get("fault", [])}
            ),
            "metric_families_touched": sorted(
                k.split(":", 1)[1] for k in by_class.get("metric", [])
            ),
            "health_transitions_seen": sorted(
                k.split(":", 1)[1] for k in by_class.get("health", [])
            ),
            "keys": sorted(self._seen),
        }
