"""The long-horizon hunt tier: multi-virtual-day soak programs.

The budgeted random hunt explores breadth; this tier buys depth — the
failure classes that only appear when a cluster has been up for days:

- **compressed virtual days** — diurnal arrival with one sinusoid cycle
  per "day", several days per run, so day/night load swings (and the
  adaptive batcher's grow/shrink cycles) repeat many times;
- **reservation-TTL expiry waves** — recurring herd waves: a
  deployment-sized create burst lands near each day's peak and is torn
  down into the trough, so every group's used sum and pod count steps up
  and decays like a TTL expiry front;
- **journal compaction + snapshot cycles** — ``durable=True`` attaches
  the PR 4 stack (journal, size-triggered snapshots, compaction) to the
  serving store, with the trigger cadence scaled so a run cuts several
  snapshots and crosses compaction at least once UNDER storm load;
- **rolling restarts** — a restart + watch-cut pair per virtual day
  (the control-plane rolling-restart shape; the sharded tier's
  ``shard.worker.kill`` is its process-level analog, armed when these
  programs replay through scenarios/sharded.py);
- **the 1M-pod columnar-arena rung** — the PR 11 scale on the hunt's
  full stack. ~4 GB RSS and minutes of build time: nightly-soak
  material, never CI (``--mega-pods`` scales it down to smoke the
  mechanics).

``make scenario-hunt-long`` evaluates every tier program under the same
gates + fingerprinting as the hunt loop (findings shrink and promote the
same way), then mutates FROM them for whatever budget remains.
"""

from __future__ import annotations

from typing import List

from ..dsl import Arrival, FaultSpec, Scenario, SloGates, Topology

__all__ = ["MEGA_PODS_DEFAULT", "long_horizon_programs"]

MEGA_PODS_DEFAULT = 1_000_000


def long_horizon_programs(
    days: int = 3,
    day_s: float = 12.0,
    mega_pods: int = MEGA_PODS_DEFAULT,
    include_mega: bool = True,
) -> List[Scenario]:
    """The tier's program list. ``days`` compressed virtual days of
    ``day_s`` real seconds each (defaults: a 36 s replay standing in for
    a 3-day soak). NOTE: these are built RAW (no hunt-tier bound
    clamping) — the whole point of the mega rung is to exceed the search
    tier's envelope."""
    duration = days * day_s
    # one restart + one watch-cut storm per day, offset into each day so
    # the restart lands on the climb and the cuts ride the peak
    rolling: List[FaultSpec] = []
    for d in range(days):
        t_day = d * day_s
        rolling.append(
            FaultSpec(
                site="scenario.apiserver.restart", mode="restart",
                t=round(t_day + 0.35 * day_s, 2),
            )
        )
        rolling.append(
            FaultSpec(
                site="mock.watch.cut", mode="close",
                window=(round(t_day + 0.5 * day_s, 2), round(t_day + 0.7 * day_s, 2)),
                probability=0.05, times=2,
            )
        )
    programs = [
        Scenario(
            name="long_diurnal_days",
            description=(
                f"{days} compressed virtual days: diurnal churn with a "
                "TTL-expiry-shaped herd wave per day (create burst at the "
                "peak, torn down into the trough), journal compaction + "
                "snapshot cycles under load (durable), and a rolling "
                "restart + watch-cut pair per day"
            ),
            duration_s=duration,
            arrival=Arrival(
                kind="diurnal", rate_hz=450.0, trough_frac=0.15, cycles=float(days)
            ),
            topology=Topology(pods=5000, throttles=300, groups=150, nodes=10),
            pattern="herd",
            herd_size=1200,
            faults=tuple(rolling),
            durable=True,
            slo=SloGates(
                flip_p50_ms=250.0, flip_p99_ms=2500.0, recovery_s=20.0,
                min_pace_frac=0.4,
            ),
        ),
        Scenario(
            name="long_compaction_churn",
            description=(
                "sustained high-churn with the durability stack attached: "
                "several snapshot cuts and at least one journal compaction "
                "must land under storm load without touching a verdict"
            ),
            duration_s=duration * 0.6,
            arrival=Arrival(kind="constant", rate_hz=600.0),
            topology=Topology(pods=8000, throttles=360, groups=180, nodes=8),
            # delete/create-heavy mix: compaction pressure comes from
            # membership churn, not status echoes
            mix=(
                ("update", 0.70), ("create", 0.14), ("delete", 0.13), ("spec", 0.03),
            ),
            durable=True,
            slo=SloGates(flip_p99_ms=250.0),
        ),
    ]
    if include_mega:
        programs.append(
            Scenario(
                name="long_mega_arena",
                description=(
                    f"the {mega_pods:,}-pod columnar-arena rung: PR 11 scale "
                    "through the whole remote stack — reflector relists, "
                    "micro-batched ingest, sparse selector index, device "
                    "planes — at a drizzle rate (the build IS the test; the "
                    "gates prove verdicts stay exact at population scale)"
                ),
                duration_s=30.0,
                arrival=Arrival(kind="constant", rate_hz=300.0),
                topology=Topology(
                    pods=mega_pods,
                    throttles=max(mega_pods // 10, 100),
                    groups=max(mega_pods // 200, 50),
                    nodes=16,
                ),
                slo=SloGates(
                    flip_p99_ms=2500.0, flip_p50_ms=500.0, min_pace_frac=0.2
                ),
            )
        )
    return programs
