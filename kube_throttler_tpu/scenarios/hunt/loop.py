"""The budgeted adversarial search loop: mutate → evaluate → cover →
shrink → promote.

One sequential loop (evaluations are the cost unit; each runs the FULL
remote stack in a fresh interpreter, so parallelizing on a 1-core host
would only contaminate the SLO gates):

1. pop the most-promising parent from the novelty-weighted corpus
   (priority = novelty of its own run, decayed per use so the search
   keeps widening instead of strip-mining one lineage);
2. ``mutate(parent, iteration)`` — deterministic child, content-addressed
   dedupe against everything already evaluated;
3. evaluate the child (fresh interpreter, full SLO gates + structured
   fingerprint);
4. ``CoverageMap.observe`` — children that reach new behavior join the
   corpus with their novelty as weight; barren children are dropped;
5. any gate failure is CONFIRMED by one re-evaluation (determinism means
   a real failure reproduces; a co-tenant noise spike does not), then
   shrunk (shrink.py) and promoted into ``scenarios/corpus/regressions/``
   as a permanent tier gate.

The loop emits a machine-readable coverage report (fault sites reached,
metric families touched, health transitions seen, per-iteration log) —
the CI artifact `hack/ci.sh` archives.
"""

from __future__ import annotations

import heapq
import json
import logging
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Sequence

from ..corpus import REGRESSIONS_DIR
from ..dsl import Arrival, FaultSpec, Scenario, SloGates, Topology, scenario_to_dict
from .coverage import CoverageMap, fingerprint_keys
from .mutate import mutate, normalize, program_sha
from .shrink import failed_gates_of, shrink

logger = logging.getLogger(__name__)

__all__ = [
    "HuntConfig",
    "InProcessEvaluator",
    "SubprocessEvaluator",
    "base_programs",
    "hunt",
    "planted_bug_program",
    "promote",
]


def base_programs() -> List[Scenario]:
    """The hunt tier's seed corpus: small programs (fast evaluations —
    the budget buys iterations, not pods) spanning the three arrival
    regimes the mutators then cross with the fault space. Gate bounds are
    the corpus' steady-state posture with headroom for the smaller
    topology (fresh-interpreter runs, so no test-process contamination
    allowance needed)."""
    slo = SloGates(flip_p99_ms=250.0, min_pace_frac=0.3, min_flip_samples=3)
    base = Scenario(
        name="hunt-base",
        description="hunt seed: small constant churn",
        duration_s=2.5,
        arrival=Arrival(kind="constant", rate_hz=350.0),
        topology=Topology(pods=900, throttles=60, groups=30, nodes=4),
        slo=slo,
    )
    return [
        normalize(base),
        normalize(
            replace(
                base,
                arrival=Arrival(kind="diurnal", rate_hz=400.0, trough_frac=0.3),
            )
        ),
        normalize(
            replace(base, topology=replace(base.topology, hot_frac=0.5))
        ),
    ]


def planted_bug_program() -> Scenario:
    """The planted-bug fixture: a minimal program whose schedule stalls
    every status PUT through the REAL mockserver fault verb
    (``mock.status.delay``) for longer than the flip SLO — the known
    regression class PR 8's gate demonstration injects via a knob; here
    it lives IN the searched program space, so finding it, shrinking it,
    and promoting it exercises the whole hunt lifecycle end to end
    against a failure that is genuinely detected by the gates, not
    assumed."""
    base = base_programs()[0]
    return normalize(
        replace(
            base,
            faults=(
                FaultSpec(
                    site="mock.status.delay",
                    mode="delay",
                    delay=0.4,
                    # covers the replay AND its overrun/quiesce on a busy
                    # host (virtual time is wall time; see normalize())
                    window=(0.2, base.duration_s + 10.0),
                ),
            ),
        )
    )


# -- evaluators ---------------------------------------------------------------


class SubprocessEvaluator:
    """Evaluate a program in a FRESH interpreter (the soundness
    requirement: sequential same-process runs contaminate each other's
    heaps — scenarios/__main__._run_isolated measured 79→440 ms flip p99
    by run five). Each call writes the program JSON and runs
    ``python -m kube_throttler_tpu.scenarios run --file …``."""

    def __init__(self, workdir: str, timeout_s: float = 900.0):
        self.workdir = workdir
        self.timeout_s = timeout_s
        self.evals = 0

    def __call__(self, scn: Scenario, seed: int) -> Optional[Dict]:
        self.evals += 1
        wd = os.path.join(self.workdir, f"eval-{self.evals:04d}-{scn.name}")
        os.makedirs(wd, exist_ok=True)
        program_path = os.path.join(wd, "program.json")
        with open(program_path, "w") as f:
            json.dump(scenario_to_dict(scn), f, indent=2)
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the hunt DETECTS regressions by latency gates failing through
        # the real stack; the slow-host advisory calibration in
        # scenarios/slo.py would blind it, so evals always enforce
        env.setdefault("KT_SCENARIO_ENFORCE_LATENCY", "1")
        try:
            proc = subprocess.run(
                [
                    sys.executable, "-m", "kube_throttler_tpu.scenarios", "run",
                    "--file", program_path, "--seed", str(seed), "--workdir", wd,
                ],
                capture_output=True, text=True, timeout=self.timeout_s, env=env,
            )
        except subprocess.TimeoutExpired:
            logger.warning("hunt eval timed out: %s", scn.name)
            return None
        report_path = os.path.join(wd, f"report-{scn.name}-s{seed}.json")
        if not os.path.exists(report_path):
            logger.warning(
                "hunt eval produced no report (rc=%s): %s\n%s",
                proc.returncode, scn.name, proc.stdout[-1500:],
            )
            return None
        with open(report_path) as f:
            return json.load(f)


class InProcessEvaluator:
    """Evaluate by calling run_scenario in THIS process. Orders of
    magnitude cheaper (no interpreter + jax import per run) but runs
    contaminate each other's timing — use only where the failing gates
    under test are timing-insensitive or bounds are loose (the tier-1
    hunt tests), never for the nightly soak."""

    def __init__(self, workdir: str):
        self.workdir = workdir
        self.evals = 0

    def __call__(self, scn: Scenario, seed: int) -> Optional[Dict]:
        from ..engine import run_scenario

        self.evals += 1
        wd = os.path.join(self.workdir, f"eval-{self.evals:04d}-{scn.name}")
        # same contract as SubprocessEvaluator: hunt evals enforce the
        # latency gates (advisory mode would hide every planted stall)
        had = os.environ.get("KT_SCENARIO_ENFORCE_LATENCY")
        if had is None:
            os.environ["KT_SCENARIO_ENFORCE_LATENCY"] = "1"
        try:
            return run_scenario(scn, seed, wd)
        except Exception:
            logger.warning("in-process hunt eval crashed", exc_info=True)
            return None
        finally:
            if had is None:
                os.environ.pop("KT_SCENARIO_ENFORCE_LATENCY", None)


# -- promotion ----------------------------------------------------------------


def promote(
    minimal: Scenario,
    seed: int,
    failed_gates: Sequence[str],
    provenance: Dict,
    promote_dir: str,
) -> str:
    """Write the shrunk repro into the regression corpus
    (corpus.load_regressions' schema). ``expect`` pins the verdict the
    replay must keep producing: ``fail:<gate>`` — the permanent proof
    that this trace still trips that gate. When a promoted repro's
    underlying bug is FIXED, the maintainer flips the committed file to
    ``"expect": "pass"`` and it becomes an always-green regression test
    (lifecycle: docs/scenarios.md)."""
    os.makedirs(promote_dir, exist_ok=True)
    entry = {
        "scenario": scenario_to_dict(minimal),
        "seed": seed,
        "expect": f"fail:{sorted(failed_gates)[0]}",
        "provenance": dict(provenance, found_by="scenario-hunt"),
    }
    path = os.path.join(promote_dir, f"{minimal.name}.json")
    with open(path, "w") as f:
        json.dump(entry, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


# -- the loop -----------------------------------------------------------------


@dataclass
class HuntConfig:
    workdir: str
    budget_s: float = 600.0
    max_iterations: int = 40
    hunt_seed: int = 0
    trace_seed: int = 0
    bases: Optional[List[Scenario]] = None
    extra_programs: List[Scenario] = field(default_factory=list)
    promote_dir: str = REGRESSIONS_DIR
    do_promote: bool = True
    max_findings: int = 3
    shrink_stages: Sequence[str] = ("faults", "flags", "arrival", "scale", "duration")
    shrink_max_attempts: int = 16
    confirm_findings: bool = True
    # CI smoke posture: end the run as soon as one finding is confirmed,
    # shrunk, and handled (the lifecycle is proven; iterations are money)
    stop_on_finding: bool = False
    report_path: Optional[str] = None


def hunt(
    cfg: HuntConfig,
    evaluate: Optional[Callable[[Scenario, int], Optional[Dict]]] = None,
    registry=None,
) -> Dict:
    """Run the budgeted search; returns (and writes) the coverage report.

    ``evaluate`` defaults to the fresh-interpreter SubprocessEvaluator;
    tests inject cheaper ones. ``registry`` (metrics.Registry) receives
    the kube_throttler_hunt_* families when given."""
    os.makedirs(cfg.workdir, exist_ok=True)
    if evaluate is None:
        evaluate = SubprocessEvaluator(os.path.join(cfg.workdir, "evals"))
    fams = None
    if registry is not None:
        from ...metrics import register_hunt_metrics

        fams = register_hunt_metrics(registry)

    coverage = CoverageMap()
    seen: Dict[str, int] = {}  # program sha → iteration first seen
    # corpus priority queue: (-priority, tiebreak, program); parents are
    # re-pushed with decayed priority so high-novelty lineages dominate
    # but never monopolize
    heap: List = []
    push_seq = 0

    def push(program: Scenario, priority: float) -> None:
        nonlocal push_seq
        push_seq += 1
        heapq.heappush(heap, (-priority, push_seq, program))

    t0 = time.monotonic()
    iterations = 0
    findings: List[Dict] = []
    promoted: List[str] = []
    log_lines: List[Dict] = []
    corpus_programs: Dict[str, Scenario] = {}

    def budget_left() -> bool:
        if cfg.stop_on_finding and findings:
            return False
        return (
            time.monotonic() - t0 < cfg.budget_s
            and iterations < cfg.max_iterations
        )

    def evaluate_program(program: Scenario, origin: str) -> None:
        nonlocal iterations
        sha = program_sha(program)
        if sha in seen:
            return
        seen[sha] = iterations
        iterations += 1
        report = evaluate(program, cfg.trace_seed)
        keys = fingerprint_keys(report) if report else frozenset()
        novelty = coverage.observe(keys)
        failed = failed_gates_of(report)
        log_lines.append(
            {
                "iteration": iterations,
                "origin": origin,
                "program": program.name,
                "sha": sha[:12],
                "evaluated": report is not None,
                "novelty": novelty,
                "failed_gates": failed,
            }
        )
        if fams is not None:
            fams["iterations"].inc({}, 1.0)
            fams["coverage"].set({}, float(len(coverage)))
            fams["corpus"].set({}, float(len(corpus_programs)))
        if report is None:
            return
        if novelty > 0:
            corpus_programs[sha] = program
            push(program, float(novelty))
        if failed and len(findings) < cfg.max_findings:
            _handle_finding(program, report, failed, origin)

    def _handle_finding(
        program: Scenario, report: Dict, failed: List[str], origin: str
    ) -> None:
        if cfg.confirm_findings:
            confirm = evaluate(program, cfg.trace_seed)
            confirmed = sorted(set(failed) & set(failed_gates_of(confirm)))
            if not confirmed:
                log_lines.append(
                    {
                        "iteration": iterations,
                        "program": program.name,
                        "unconfirmed_failure": failed,
                    }
                )
                return
            failed = confirmed
        if fams is not None:
            fams["findings"].inc({}, 1.0)
        res = shrink(
            program,
            cfg.trace_seed,
            evaluate,
            failed,
            stages=cfg.shrink_stages,
            max_attempts=cfg.shrink_max_attempts,
        )
        if fams is not None:
            fams["shrink_steps"].inc({}, float(res["steps"]))
        finding = {
            "origin": origin,
            "found_program": program.name,
            "found_sha": program_sha(program),
            "failed_gates": failed,
            "minimal_program": res["program"].name,
            "minimal_sha": program_sha(res["program"]),
            "minimal_size": res["size"],
            "shrink_steps": res["steps"],
            "shrink_attempts": res["attempts"],
            "shrink_history": res["history"],
            "trace_sha256": report.get("trace_sha256"),
        }
        findings.append(finding)
        if cfg.do_promote:
            path = promote(
                res["program"],
                cfg.trace_seed,
                res["failed_gates"] or failed,
                {
                    "hunt_seed": cfg.hunt_seed,
                    "iteration": iterations,
                    "parent": program.name,
                    "parent_sha": program_sha(program),
                    "shrink_steps": res["steps"],
                    "shrink_history": res["history"],
                    "original_trace_sha256": report.get("trace_sha256"),
                },
                cfg.promote_dir,
            )
            promoted.append(path)
            finding["promoted_path"] = path

    # seed the corpus: the base programs plus any planted extras — all
    # evaluated through the same pipeline (a seeded program that fails a
    # gate is a finding like any other)
    for program in (cfg.bases if cfg.bases is not None else base_programs()):
        if not budget_left():
            break
        evaluate_program(normalize(program), "base")
    for program in cfg.extra_programs:
        if not budget_left():
            break
        evaluate_program(normalize(program), "seeded")

    mutation_counter = 0
    while budget_left() and heap:
        neg_priority, _, parent = heapq.heappop(heap)
        mutation_counter += 1
        child = mutate(parent, cfg.hunt_seed * 100_000 + mutation_counter)
        evaluate_program(child, f"mutant-of-{parent.name}")
        # decay and re-offer the parent (half weight per use, floor 0.25)
        decayed = max(-neg_priority / 2.0, 0.25)
        push(parent, decayed)

    report = {
        "hunt_seed": cfg.hunt_seed,
        "trace_seed": cfg.trace_seed,
        "budget_s": cfg.budget_s,
        "wall_s": round(time.monotonic() - t0, 3),
        "iterations": iterations,
        "corpus_size": len(corpus_programs),
        "findings": findings,
        "promoted": promoted,
        "coverage": coverage.report(),
        "log": log_lines,
    }
    path = cfg.report_path or os.path.join(cfg.workdir, "hunt-report.json")
    with open(path, "w") as f:
        json.dump(report, f, indent=2, default=str)
    report["report_path"] = path
    return report
