"""Adversarial hunt CLI.

Usage:
    python -m kube_throttler_tpu.scenarios.hunt run   [--budget-s 600] [--iterations 40]
    python -m kube_throttler_tpu.scenarios.hunt smoke [--workdir WD] [--report R.json]
    python -m kube_throttler_tpu.scenarios.hunt long  [--budget-s 3600] [--mega-pods N]

``run`` is the nightly budgeted soak (`make scenario-hunt`): random
coverage-guided search from the base programs, findings shrunk and
promoted into ``scenarios/corpus/regressions/``.

``smoke`` is the CI acceptance check (`make scenario-hunt-smoke`,
hack/ci.sh): the planted-bug program (a mock.status.delay stall inside
the searched space) is seeded into the corpus; the run must FIND it
(flip gate fails through the real stack), CONFIRM it, SHRINK it to a
minimal program, and PROMOTE it — exit 1 otherwise. The coverage report
is the archived artifact.

``long`` evaluates the long-horizon tier programs (multi-virtual-day
diurnal soaks, durability-cycle churn, the 1M-pod arena rung) and then
mutates from them for the remaining budget.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

from .longhorizon import MEGA_PODS_DEFAULT, long_horizon_programs
from .loop import HuntConfig, base_programs, hunt, planted_bug_program


def _common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--workdir", default="")
    p.add_argument("--report", default="", help="coverage report path")
    p.add_argument("--budget-s", type=float, default=600.0)
    p.add_argument("--iterations", type=int, default=40)
    p.add_argument("--hunt-seed", type=int, default=0)
    p.add_argument("--trace-seed", type=int, default=0)
    p.add_argument(
        "--promote-dir", default="",
        help="where shrunk repros land (default: the committed corpus)",
    )
    p.add_argument(
        "--no-promote", action="store_true",
        help="report findings without writing regression-corpus entries",
    )


def _config(args, **overrides) -> HuntConfig:
    workdir = args.workdir or tempfile.mkdtemp(prefix="kt-hunt-")
    os.makedirs(workdir, exist_ok=True)
    kwargs = dict(
        workdir=workdir,
        budget_s=args.budget_s,
        max_iterations=args.iterations,
        hunt_seed=args.hunt_seed,
        trace_seed=args.trace_seed,
        do_promote=not args.no_promote,
        report_path=args.report or None,
    )
    if args.promote_dir:
        kwargs["promote_dir"] = args.promote_dir
    kwargs.update(overrides)
    return HuntConfig(**kwargs)


def _summarize(report: dict) -> None:
    cov = report["coverage"]
    print(
        f"hunt: {report['iterations']} iterations in {report['wall_s']:.0f}s | "
        f"coverage {cov['coverage_keys']} keys "
        f"({cov['by_class']}) | corpus {report['corpus_size']} | "
        f"findings {len(report['findings'])} | promoted {len(report['promoted'])}"
    )
    for f in report["findings"]:
        print(
            f"  FINDING {f['found_program']} failed {f['failed_gates']} → "
            f"shrunk to {f['minimal_program']} "
            f"(size {f['minimal_size']}, {f['shrink_steps']} steps)"
            + (f" → promoted {f['promoted_path']}" if "promoted_path" in f else "")
        )
    print(f"coverage report: {report['report_path']}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kube_throttler_tpu.scenarios.hunt")
    sub = parser.add_subparsers(dest="command", required=True)
    for name in ("run", "smoke", "long"):
        p = sub.add_parser(name)
        _common(p)
        if name == "long":
            p.add_argument("--mega-pods", type=int, default=MEGA_PODS_DEFAULT)
            p.add_argument("--skip-mega", action="store_true")
            p.add_argument("--days", type=int, default=3)
    args = parser.parse_args(argv)

    if args.command == "run":
        report = hunt(_config(args))
        _summarize(report)
        return 0

    if args.command == "smoke":
        # small budget, planted bug seeded into the search corpus; ops-only
        # shrink stages keep the fresh-interpreter evaluation count small
        cfg = _config(
            args,
            budget_s=min(args.budget_s, 480.0),
            max_iterations=min(args.iterations, 6),
            bases=[base_programs()[0]],  # one clean baseline, then the plant
            extra_programs=[planted_bug_program()],
            shrink_stages=("faults", "flags", "arrival"),
            shrink_max_attempts=6,
            max_findings=1,
            stop_on_finding=True,
        )
        report = hunt(cfg)
        _summarize(report)
        found = [f for f in report["findings"] if "flip_p99" in f["failed_gates"]]
        promoted_ok = bool(report["promoted"]) or (
            not cfg.do_promote and bool(report["findings"])
        )
        if not (found and promoted_ok):
            print(
                "HUNT SMOKE FAILED: the planted mock.status.delay regression "
                "was not found+shrunk+promoted", file=sys.stderr,
            )
            return 1
        minimal_sizes = [f["minimal_size"] for f in found]
        if min(minimal_sizes) > 2:
            print(
                f"HUNT SMOKE FAILED: minimal repro size {min(minimal_sizes)} > 2 "
                "DSL ops (shrinker regressed)", file=sys.stderr,
            )
            return 1
        print("hunt smoke: planted bug found, shrunk, promoted — OK")
        return 0

    # long
    programs = long_horizon_programs(
        days=args.days, mega_pods=args.mega_pods, include_mega=not args.skip_mega
    )
    cfg = _config(args, bases=programs)
    report = hunt(cfg)
    _summarize(report)
    # the long tier doubles as a gate: its committed programs must pass
    failing = [
        line for line in report["log"]
        if line.get("origin") == "base" and line.get("failed_gates")
    ]
    if failing:
        print(json.dumps(failing, indent=2))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
