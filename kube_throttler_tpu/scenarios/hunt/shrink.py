"""Shrink a gate-failing scenario program to a minimal repro.

Classic delta-debugging, made *sound* by the PR 8 determinism property:
``build_trace(program, seed)`` is pure and every candidate re-replays in
a FRESH interpreter (the hunt's evaluator), so "the candidate still fails
the same gate" is a statement about the program, not about scheduler
noise in a polluted process. The transform ladder sheds structure in
order of explanatory weight:

1. **faults** — drop schedule entries one at a time (the usual culprit
   is one entry; everything else is camouflage);
2. **flags** — strip the leader-kill episode, collapse drain/herd
   patterns to plain churn, drop the hot-key group;
3. **arrival** — flatten the arrival process to constant at the same
   nominal rate;
4. **scale** — halve topology mass (pods/throttles/groups) toward the
   tier floors;
5. **duration** — halve the run length.

A candidate is accepted iff its re-replay still fails at least one of
the ORIGINAL failing gates; accepted candidates restart the ladder
(greedy fixpoint) until nothing reduces or the attempt budget runs out.
The result carries the accepted-step history — the repro's provenance
trail committed alongside it at promotion.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..dsl import Arrival, Scenario
from .mutate import normalize, program_sha, program_size

__all__ = ["failed_gates_of", "shrink"]

DEFAULT_STAGES: Tuple[str, ...] = ("faults", "flags", "arrival", "scale", "duration")

# Evaluator contract: (program, trace_seed) → report dict | None.
# None (crashed / no report) is treated as "does not reproduce" — a
# shrink step may never be accepted on missing evidence.
Evaluator = Callable[[Scenario, int], Optional[Dict]]


def failed_gates_of(report: Optional[Dict]) -> List[str]:
    if not report:
        return []
    return sorted(
        name for name, g in (report.get("gates") or {}).items() if not g.get("pass")
    )


def _candidates(scn: Scenario, stage: str) -> List[Tuple[str, Scenario]]:
    """Deterministically-ordered transform candidates for one stage."""
    out: List[Tuple[str, Scenario]] = []
    if stage == "faults":
        for i in range(len(scn.faults)):
            out.append(
                (
                    f"drop_fault[{scn.faults[i].site}]",
                    replace(scn, faults=scn.faults[:i] + scn.faults[i + 1 :]),
                )
            )
    elif stage == "flags":
        if scn.leader_kill:
            out.append(("drop_leader_kill", replace(scn, leader_kill=False)))
        if scn.pattern != "churn":
            out.append(
                ("pattern_to_churn", replace(scn, pattern="churn", herd_size=0))
            )
        if scn.topology.hot_frac > 0:
            out.append(
                (
                    "drop_hot_group",
                    replace(scn, topology=replace(scn.topology, hot_frac=0.0)),
                )
            )
    elif stage == "arrival":
        if scn.arrival.kind != "constant":
            out.append(
                (
                    "arrival_to_constant",
                    replace(scn, arrival=Arrival(rate_hz=scn.arrival.rate_hz)),
                )
            )
    elif stage == "scale":
        topo = scn.topology
        if topo.pods > 400:
            out.append(
                (
                    "halve_pods",
                    replace(
                        scn,
                        topology=replace(
                            topo,
                            pods=max(topo.pods // 2, 200),
                            groups=max(min(topo.groups, topo.pods // 16), 8),
                        ),
                    ),
                )
            )
        if topo.throttles > 48:
            out.append(
                (
                    "halve_throttles",
                    replace(
                        scn,
                        topology=replace(topo, throttles=max(topo.throttles // 2, 24)),
                    ),
                )
            )
    elif stage == "duration":
        if scn.duration_s > 2.4:
            out.append(("halve_duration", replace(scn, duration_s=scn.duration_s / 2)))
    return out


def shrink(
    program: Scenario,
    seed: int,
    evaluate: Evaluator,
    target_gates: Sequence[str],
    stages: Sequence[str] = DEFAULT_STAGES,
    max_attempts: int = 24,
) -> Dict:
    """Greedy fixpoint shrink of ``program`` under ``evaluate``.

    ``target_gates`` are the gates the original run failed; a candidate
    survives iff its fresh re-replay fails at least one of them. Returns
    ``{"program", "seed", "steps", "attempts", "size", "failed_gates",
    "history"}`` where ``history`` lists every accepted transform."""
    target = set(target_gates)
    if not target:
        raise ValueError("shrink needs the failing gate set (nothing to preserve)")
    current = normalize(program)
    attempts = 0
    steps = 0
    history: List[Dict] = []
    last_failed = sorted(target)
    progress = True
    while progress and attempts < max_attempts:
        progress = False
        for stage in stages:
            if attempts >= max_attempts:
                break
            for label, candidate in _candidates(current, stage):
                if attempts >= max_attempts:
                    break
                candidate = normalize(candidate)
                if program_sha(candidate) == program_sha(current):
                    continue
                attempts += 1
                report = evaluate(candidate, seed)
                failed = failed_gates_of(report)
                if target & set(failed):
                    steps += 1
                    history.append(
                        {
                            "transform": label,
                            "size": program_size(candidate),
                            "failed_gates": failed,
                        }
                    )
                    current = candidate
                    last_failed = failed
                    progress = True
                    break  # restart this stage's candidate list on the new program
            if progress:
                break  # restart the ladder from stage 1
    return {
        "program": current,
        "seed": seed,
        "steps": steps,
        "attempts": attempts,
        "size": program_size(current),
        "failed_gates": last_failed,
        "history": history,
    }
