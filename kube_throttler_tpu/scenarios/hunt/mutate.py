"""Seeded, pure-function mutations of scenario DSL programs.

``mutate(program, seed)`` is byte-deterministic: the mutation RNG is
keyed by ``(program content sha, seed)`` and nothing else — no wall
clock, no global state — so the same (program, seed) pair produces the
same child on any host, any run. Children are content-addressed
(``hunt-<sha12>`` names derived from the program body with name and
description excluded), which gives the hunt loop exact dedupe for free:
two mutation paths that land on the same program collapse to one corpus
entry, and two mutants whose fault schedules differ only in surface form
collapse because the sha hashes the CANONICAL compiled fault plan
(trace.canonical_fault_plan), not the raw FaultSpec tuple.

Every mutator returns a program inside the hunt tier's validity envelope
(clamped topology/rate/duration bounds, fault sites restricted to the
registered ``MUTABLE_FAULT_SITES`` subset of ``faults.plan.KNOWN_SITES``)
so the PR 8 trace property holds for every child: ``build_trace(child,
trace_seed)`` is pure, hence same seed ⇒ identical trace bytes — the
precondition that makes coverage comparison and shrinking sound.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import replace
from typing import Callable, Dict, List, Optional, Tuple

from ..dsl import Arrival, FaultSpec, Scenario, Topology, scenario_to_dict
from ..trace import canonical_fault_plan

__all__ = [
    "BOUNDS",
    "MUTABLE_FAULT_SITES",
    "MUTATORS",
    "SHARD_TIER_PREFIXES",
    "mutate",
    "needs_shard_tier",
    "normalize",
    "program_sha",
    "program_size",
]

# site → the modes a mutator may arm there. Every key MUST be a member of
# faults.plan.KNOWN_SITES (pinned by tests/test_hunt.py): an unregistered
# site silently never fires, which would make the mutant a wasted
# evaluation. shard.worker.kill and the reshard.* family only fire in the
# sharded replay tier — a program arming any of them routes through
# scenarios.sharded.run_sharded_program (scenarios/__main__.py), which
# replays the trace against the real multiprocess stack and drives one
# live rescale so the sites are actually reachable end to end.
# scenario.leader.kill is armed via the leader_kill flag, not a FaultSpec.
MUTABLE_FAULT_SITES: Dict[str, Tuple[str, ...]] = {
    "mock.list": ("error", "gone", "delay"),
    "mock.watch.cut": ("close",),
    "mock.watch.gone": ("gone",),
    "mock.status.conflict": ("conflict",),
    "mock.status.error": ("error",),
    "mock.status.delay": ("delay",),
    "mock.lease": ("conflict", "error", "delay"),
    "transport.request": ("error",),
    "transport.put.conflict": ("error",),
    "transport.watch.open": ("error",),
    "transport.watch.read": ("close", "gone", "error", "delay"),
    "ingest.batch.partial": ("error",),
    "scenario.apiserver.restart": ("restart", "expire_continues"),
    "scenario.churn.stall": ("delay",),
    "shard.worker.kill": ("kill",),
    "reshard.handoff.torn": ("torn", "error"),
    "reshard.dest.crash": ("kill", "error"),
    "reshard.fence.race": ("error",),
    "reshard.front.crash": ("error",),
    # the network-fault axis: net.* sites only fire on the TCP transport,
    # so a program arming any of them rides the sharded tier with a
    # transport="tcp" fleet (run_sharded_program arms them client-side)
    "net.connect.refused": ("error",),
    "net.send.torn_frame": ("torn",),
    "net.recv.stall": ("delay",),
    "net.partition": ("error",),
    "net.reconnect.storm": ("error",),
    # the shared-memory event-plane axis: shm.* sites live in the ring
    # transport (sharding/shmring.py) — socketpair fleets with the ring
    # enabled reach them; the sharded tier arms them supervisor-side
    "shm.ring.full": ("delay", "error"),
    "shm.slot.torn_commit": ("torn",),
    "shm.doorbell.lost": ("error",),
    "shm.reader.stall": ("delay",),
    "shm.segment.unlink": ("error",),
}

# the sharded-tier families: a program arming any of these is evaluated
# through the multiprocess replayer, not the single-process engine.
# net.* rides the same tier (the sites live in the TCP framing layer —
# a single-process replay could never reach them); shm.* likewise (the
# ring only exists between a real supervisor and a spawned worker)
SHARD_TIER_PREFIXES = ("shard.", "reshard.", "net.", "shm.")


def needs_shard_tier(scn: Scenario) -> bool:
    return any(f.site.startswith(SHARD_TIER_PREFIXES) for f in scn.faults)

# the hunt tier's validity envelope: wide enough to reach interesting
# regimes (the 1-core composed-stack knee, hot-key dominance, relist
# storms), bounded so one mutant cannot eat the whole wall-clock budget
BOUNDS = {
    "pods": (200, 20_000),
    "throttles": (24, 600),
    "groups": (8, 300),
    "nodes": (2, 16),
    "rate_hz": (100.0, 900.0),
    "duration_s": (1.2, 15.0),
    "max_faults": 6,
    "gang_size": (0, 48),
    "accel_classes": (0, 6),
    "class_threshold_frac": (0.0, 0.8),
    "priority_levels": (0, 10),
}


def _clamp(v, lo, hi):
    return max(lo, min(hi, v))


def _fault_sort_key(f: FaultSpec):
    return (
        f.site,
        f.mode,
        -1.0 if f.t is None else f.t,
        f.window or (),
        f.probability,
        -1 if f.times is None else f.times,
        f.delay,
    )


def program_sha(scn: Scenario) -> str:
    """Content address of the program BODY: name/description excluded
    (they are derived from this sha), raw faults replaced by the canonical
    compiled plan so surface-form schedule differences collapse."""
    body = scenario_to_dict(scn)
    body.pop("name", None)
    body.pop("description", None)
    body["faults"], _ = canonical_fault_plan(scn)
    blob = json.dumps(body, sort_keys=True, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


def program_size(scn: Scenario) -> int:
    """The shrinker's minimality measure, in DSL ops: one per fault entry
    plus one per non-default structural axis (pattern, leader-kill,
    non-constant arrival, hot-key group). Topology/duration magnitude is
    shed by the shrinker but doesn't count as an op — a minimal repro is
    'few program constructs', not 'few pods'."""
    return (
        len(scn.faults)
        + int(scn.pattern != "churn")
        + int(scn.leader_kill)
        + int(scn.arrival.kind != "constant")
        + int(scn.topology.hot_frac > 0)
    )


def normalize(scn: Scenario) -> Scenario:
    """Normal form shared by every mutator output: faults sorted into the
    canonical order (order-only schedule differences collapse — the
    sorted form IS the program, so what dedupes is also what runs),
    bounds clamped, gates made meaningful for whatever the schedule now
    arms (a mutant that inserts a restart gets a recovery bound; one that
    arms leader_kill gets a failover window), and the content-addressed
    ``hunt-<sha12>`` identity stamped last."""
    topo = scn.topology
    topo = replace(
        topo,
        pods=_clamp(topo.pods, *BOUNDS["pods"]),
        throttles=_clamp(topo.throttles, *BOUNDS["throttles"]),
        groups=_clamp(min(topo.groups, max(topo.pods // 8, 8)), *BOUNDS["groups"]),
        nodes=_clamp(topo.nodes, *BOUNDS["nodes"]),
        hot_frac=_clamp(topo.hot_frac, 0.0, 0.5),
        gang_size=_clamp(int(topo.gang_size), *BOUNDS["gang_size"]),
        accel_classes=_clamp(int(topo.accel_classes), *BOUNDS["accel_classes"]),
        class_threshold_frac=round(
            _clamp(float(topo.class_threshold_frac),
                   *BOUNDS["class_threshold_frac"]), 3
        ),
        priority_levels=_clamp(
            int(topo.priority_levels), *BOUNDS["priority_levels"]
        ),
    )
    arrival = replace(
        scn.arrival, rate_hz=_clamp(scn.arrival.rate_hz, *BOUNDS["rate_hz"])
    )
    duration = _clamp(scn.duration_s, *BOUNDS["duration_s"])
    faults = []
    for f in scn.faults[: BOUNDS["max_faults"]]:
        t = None if f.t is None else round(_clamp(f.t, 0.1, duration * 0.9), 3)
        window = f.window
        if window is not None:
            # the end clamp leaves generous overrun slop: virtual time is
            # wall time, a loaded host replays slower than the trace's
            # nominal pacing, and a window that silently closed mid-overrun
            # would make the same program behave differently on a busy box
            w0 = round(_clamp(window[0], 0.0, duration), 3)
            w1 = round(_clamp(window[1], w0 + 0.1, duration + 10.0), 3)
            window = (w0, w1)
        faults.append(replace(f, t=t, window=window))
    faults.sort(key=_fault_sort_key)
    slo = scn.slo
    if slo.recovery_s is None and any(
        f.site == "scenario.apiserver.restart" for f in faults
    ):
        slo = replace(slo, recovery_s=20.0)
    if scn.leader_kill and slo.failover_window_s is None:
        slo = replace(slo, failover_window_s=15.0)
    herd = scn.herd_size if scn.pattern == "herd" else 0
    if scn.pattern == "herd" and herd <= 0:
        herd = max(topo.pods // 4, 50)
    out = replace(
        scn,
        arrival=arrival,
        topology=topo,
        duration_s=duration,
        faults=tuple(faults),
        slo=slo,
        herd_size=herd,
    )
    sha12 = program_sha(out)[:12]
    return replace(
        out,
        name=f"hunt-{sha12}",
        description=f"hunt-generated program {sha12}",
    )


# -- mutators ----------------------------------------------------------------
# Each is (program, rng) → program | None (None = inapplicable here).
# They operate on the RAW program; normalize() runs after every mutation.


def _mut_arrival_kind(scn: Scenario, rng: random.Random):
    kinds = ["constant", "ramp", "diurnal", "bursts"]
    if scn.arrival.kind in kinds:
        kinds.remove(scn.arrival.kind)
    kind = kinds[rng.randrange(len(kinds))]
    return replace(
        scn,
        arrival=replace(
            scn.arrival,
            kind=kind,
            trough_frac=rng.choice([0.1, 0.2, 0.35]),
            cycles=rng.choice([1.0, 2.0, 3.0]),
            burst_s=rng.choice([0.3, 0.5, 1.0]),
            idle_s=rng.choice([0.5, 1.0, 2.0]),
        ),
    )


def _mut_arrival_rate(scn: Scenario, rng: random.Random):
    factor = rng.choice([0.5, 0.75, 1.25, 1.5, 2.0])
    return replace(
        scn, arrival=replace(scn.arrival, rate_hz=scn.arrival.rate_hz * factor)
    )


def _mut_duration(scn: Scenario, rng: random.Random):
    return replace(scn, duration_s=scn.duration_s * rng.choice([0.6, 1.4]))


def _mut_topology_scale(scn: Scenario, rng: random.Random):
    factor = rng.choice([0.5, 2.0])
    topo = scn.topology
    return replace(
        scn,
        topology=replace(
            topo,
            pods=int(topo.pods * factor),
            throttles=int(topo.throttles * (factor if factor < 1 else 1.5)),
            groups=int(topo.groups * (factor if factor < 1 else 1.5)),
        ),
    )


def _mut_topology_hot(scn: Scenario, rng: random.Random):
    choices = [0.0, 0.25, 0.5]
    if scn.topology.hot_frac in choices:
        choices.remove(scn.topology.hot_frac)
    return replace(
        scn,
        topology=replace(scn.topology, hot_frac=rng.choice(choices)),
    )


def _mut_topology_nodes(scn: Scenario, rng: random.Random):
    return replace(
        scn, topology=replace(scn.topology, nodes=rng.choice([2, 4, 8, 12, 16]))
    )


def _mut_topology_gang(scn: Scenario, rng: random.Random):
    """Gang axis (PR 7): toggle/resize the PodGroup cohorts the initial
    population is stamped with — group-size choices cross the per-group
    pod counts, so mutants cover never-completable and exactly-fitting
    gangs alike."""
    choices = [0, 2, 4, 8, 16, 32]
    if scn.topology.gang_size in choices:
        choices.remove(scn.topology.gang_size)
    return replace(
        scn, topology=replace(scn.topology, gang_size=rng.choice(choices))
    )


def _mut_topology_accel(scn: Scenario, rng: random.Random):
    """Heterogeneity axis (PR 7): the accel-class mix and the per-class
    threshold skew — class-resolved admission diverges from the base
    inequality once both are on."""
    n = rng.choice([0, 2, 3, 4, 6])
    frac = 0.0 if n == 0 else rng.choice([0.2, 0.4, 0.6, 0.8])
    return replace(
        scn,
        topology=replace(
            scn.topology, accel_classes=n, class_threshold_frac=frac
        ),
    )


def _mut_topology_priority(scn: Scenario, rng: random.Random):
    """Priority-distribution axis (PR 15's policy paths): spread the
    population over N priority annotations — level choices cross the
    ordered-lane and victim-ranking code paths with both shallow and deep
    priority ladders."""
    choices = [0, 2, 3, 5, 8]
    if scn.topology.priority_levels in choices:
        choices.remove(scn.topology.priority_levels)
    return replace(
        scn,
        topology=replace(scn.topology, priority_levels=rng.choice(choices)),
    )


def _mut_preempt_shape(scn: Scenario, rng: random.Random):
    """Preemption-toggle axis: arm (or disarm) the preemption-SHAPED
    topology — gangs AND a priority ladder together, the precondition for
    every gang-aware preemption path (a gang axis alone never ranks
    victims; a priority axis alone never forms groups)."""
    if scn.topology.gang_size > 0 and scn.topology.priority_levels > 0:
        return replace(
            scn,
            topology=replace(scn.topology, gang_size=0, priority_levels=0),
        )
    return replace(
        scn,
        topology=replace(
            scn.topology,
            gang_size=rng.choice([2, 4, 8]),
            priority_levels=rng.choice([2, 3, 5]),
        ),
    )


def _mut_pattern(scn: Scenario, rng: random.Random):
    patterns = ["churn", "drain", "herd"]
    if scn.pattern in patterns:
        patterns.remove(scn.pattern)
    pattern = patterns[rng.randrange(len(patterns))]
    herd = max(scn.topology.pods // 4, 50) if pattern == "herd" else 0
    return replace(scn, pattern=pattern, herd_size=herd)


def _mut_mix(scn: Scenario, rng: random.Random):
    # rebalance toward one op class (membership churn vs status churn vs
    # spec churn stress different pipelines)
    boosted = rng.choice(["update", "create", "delete", "spec"])
    mix = []
    for k, w in scn.mix:
        mix.append((k, round(w * (2.5 if k == boosted else 1.0), 4)))
    total = sum(w for _, w in mix) or 1.0
    return replace(scn, mix=tuple((k, round(w / total, 4)) for k, w in mix))


def _mut_leader_kill(scn: Scenario, rng: random.Random):
    return replace(scn, leader_kill=not scn.leader_kill)


def _mut_epoch_churn(scn: Scenario, rng: random.Random):
    """Epoch-churn/cache axis (PR 17's interned-verdict cache): push the
    op mix toward throttle-SPEC edits (every edit bumps the covered cols'
    epochs, invalidating cached verdicts) while collapsing the group
    count toward the degenerate-shape regime where the cache serves most
    decisions. Jointly this is the adversarial shape for a stale-verdict
    bug — maximal cache hit traffic under maximal invalidation pressure —
    and the existing zero-wrong-verdicts sweep is the judge (the serving
    plugin replays WITH its cache; the oracle rebuild recomputes)."""
    lo, _hi = BOUNDS["groups"]
    groups = max(lo, scn.topology.groups // rng.choice([4, 8, 16]))
    spec_w = rng.choice([0.25, 0.4, 0.6])
    rest = {k: w for k, w in scn.mix if k != "spec"}
    total = sum(rest.values()) or 1.0
    mix = tuple(
        [(k, round(w / total * (1.0 - spec_w), 4)) for k, w in rest.items()]
        + [("spec", round(spec_w, 4))]
    )
    return replace(scn, topology=replace(scn.topology, groups=groups), mix=mix)


def _draw_fault(scn: Scenario, rng: random.Random) -> FaultSpec:
    # Stratified site draw: the shard-tier axis keeps growing (shard.*,
    # reshard.*, net.*, now shm.*) and a flat draw would crowd out the
    # single-process bug classes a little more with every transport PR
    # — and convert that many more replays to the expensive sharded
    # tier. Pick the tier first (bounded share), then uniform within.
    ordered = sorted(MUTABLE_FAULT_SITES)
    tier = [s for s in ordered if s.startswith(SHARD_TIER_PREFIXES)]
    core = [s for s in ordered if not s.startswith(SHARD_TIER_PREFIXES)]
    pool = tier if (tier and rng.random() < 1.0 / 3.0) else core
    site = pool[rng.randrange(len(pool))]
    mode = rng.choice(MUTABLE_FAULT_SITES[site])
    delay = rng.choice([0.05, 0.1, 0.2, 0.3]) if mode == "delay" else (
        rng.choice([0.0, 0.2]) if site == "scenario.apiserver.restart" else 0.0
    )
    if site in ("scenario.apiserver.restart", "scenario.churn.stall"):
        # one-shot action sites: a single virtual instant
        return FaultSpec(
            site=site,
            mode=mode,
            t=round(rng.uniform(0.3, max(scn.duration_s * 0.8, 0.4)), 2),
            delay=delay,
        )
    t0 = round(rng.uniform(0.2, max(scn.duration_s * 0.7, 0.3)), 2)
    t1 = round(t0 + rng.uniform(0.4, max(scn.duration_s * 0.5, 0.5)), 2)
    return FaultSpec(
        site=site,
        mode=mode,
        window=(t0, t1),
        probability=rng.choice([1.0, 0.5, 0.25, 0.1]),
        times=rng.choice([1, 2, 3, None]),
        delay=delay,
    )


def _mut_fault_insert(scn: Scenario, rng: random.Random):
    if len(scn.faults) >= BOUNDS["max_faults"]:
        return None
    return replace(scn, faults=scn.faults + (_draw_fault(scn, rng),))


def _mut_fault_remove(scn: Scenario, rng: random.Random):
    if not scn.faults:
        return None
    idx = rng.randrange(len(scn.faults))
    return replace(scn, faults=scn.faults[:idx] + scn.faults[idx + 1 :])


def _mut_fault_move(scn: Scenario, rng: random.Random):
    if not scn.faults:
        return None
    idx = rng.randrange(len(scn.faults))
    f = scn.faults[idx]
    shift = rng.uniform(-scn.duration_s * 0.3, scn.duration_s * 0.3)
    if f.t is not None:
        f = replace(f, t=round(f.t + shift, 2))
    elif f.window is not None:
        f = replace(
            f,
            window=(round(f.window[0] + shift, 2), round(f.window[1] + shift, 2)),
        )
    else:
        return None
    faults = list(scn.faults)
    faults[idx] = f
    return replace(scn, faults=tuple(faults))


def _mut_fault_widen(scn: Scenario, rng: random.Random):
    """Escalate one schedule entry: widen its window, raise its firing
    probability, or lift its times cap."""
    candidates = [i for i, f in enumerate(scn.faults) if f.window is not None]
    if not candidates:
        return None
    idx = candidates[rng.randrange(len(candidates))]
    f = scn.faults[idx]
    kind = rng.choice(["window", "probability", "times"])
    if kind == "window":
        w0, w1 = f.window
        span = (w1 - w0) * rng.choice([1.5, 2.0])
        f = replace(f, window=(w0, round(w0 + span, 2)))
    elif kind == "probability":
        f = replace(f, probability=min(1.0, f.probability * 2.0))
    else:
        f = replace(f, times=None if f.times is None else f.times * 2)
    faults = list(scn.faults)
    faults[idx] = f
    return replace(scn, faults=tuple(faults))


MUTATORS: List[Tuple[str, Callable[[Scenario, random.Random], Optional[Scenario]]]] = [
    ("arrival_kind", _mut_arrival_kind),
    ("arrival_rate", _mut_arrival_rate),
    ("duration", _mut_duration),
    ("topology_scale", _mut_topology_scale),
    ("topology_hot", _mut_topology_hot),
    ("topology_nodes", _mut_topology_nodes),
    ("topology_gang", _mut_topology_gang),
    ("topology_accel", _mut_topology_accel),
    ("topology_priority", _mut_topology_priority),
    ("preempt_shape", _mut_preempt_shape),
    ("pattern", _mut_pattern),
    ("mix", _mut_mix),
    ("epoch_churn", _mut_epoch_churn),
    ("leader_kill", _mut_leader_kill),
    ("fault_insert", _mut_fault_insert),
    # fault insertion carries triple weight: faults are the point, and the
    # structural axes above (gang/accel/priority/epoch-churn) would
    # otherwise dilute the draw below the discovery rate the seeded
    # planted-bug search budget assumes
    ("fault_insert2", _mut_fault_insert),
    ("fault_insert3", _mut_fault_insert),
    ("fault_remove", _mut_fault_remove),
    ("fault_move", _mut_fault_move),
    ("fault_widen", _mut_fault_widen),
]


def mutate(program: Scenario, seed: int) -> Scenario:
    """One seeded mutation step: pure in (program content, seed). Draws
    mutators until one applies and actually changes the program (≤8
    attempts — a fixpoint draw sequence returns the normalized program
    itself, which the loop's dedupe then skips)."""
    base_sha = program_sha(program)
    rng = random.Random(f"{base_sha}/{seed}/mutate")
    for _ in range(8):
        _, fn = MUTATORS[rng.randrange(len(MUTATORS))]
        child = fn(program, rng)
        if child is None:
            continue
        child = normalize(child)
        if program_sha(child) != base_sha:
            return child
    return normalize(program)
