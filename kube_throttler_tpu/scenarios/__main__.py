"""Scenario engine CLI.

Usage:
    python -m kube_throttler_tpu.scenarios list
    python -m kube_throttler_tpu.scenarios run --name hotkey_throttle [--seed 0]
    python -m kube_throttler_tpu.scenarios run --file program.json [--seed 0]
    python -m kube_throttler_tpu.scenarios matrix [--seeds 0,1,2] [--names a,b]
    python -m kube_throttler_tpu.scenarios regression --name smoke [--seed 0]
    python -m kube_throttler_tpu.scenarios regressions [--workdir WD]
    python -m kube_throttler_tpu.scenarios trace --name smoke --seed 0

``make scenario-test`` runs ``matrix`` over the full corpus × 3 seeds and
exits non-zero if any SLO gate fails. ``regression`` runs one scenario
clean AND with the injected flip-stall regression, prints the per-gate
diff report, and exits non-zero unless the regression demonstrably fails
a gate the clean run passed (the gate-actually-gates acceptance check).
``run --file`` replays an arbitrary DSL program from JSON
(dsl.scenario_from_dict) — the hunt's fresh-interpreter evaluation hook.
``regressions`` replays every hunt-promoted repro committed under
``scenarios/corpus/regressions/`` and enforces each entry's pinned
verdict (``expect: fail:<gate>`` must still fail exactly that gate;
``expect: pass`` must go green) — the permanent tier gate.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile


def _run_isolated(name: str, seed: int, workdir: str, regression=None):
    """One scenario in a FRESH interpreter. Sequential in-process runs
    contaminate each other (each build freezes the previous runs' heaps
    and inherits their compile caches/RSS — measured 79ms → 440ms flip
    p99 by run five of a shared process), so the matrix and the
    clean-vs-regressed comparison isolate every run. Returns (report or
    None, CompletedProcess)."""
    os.makedirs(workdir, exist_ok=True)
    cmd = [
        sys.executable, "-m", "kube_throttler_tpu.scenarios", "run",
        "--name", name, "--seed", str(seed), "--workdir", workdir,
    ]
    if regression:
        cmd += ["--regression", regression]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=1200, env=env)
    report_path = os.path.join(workdir, f"report-{name}-s{seed}.json")
    report = None
    if os.path.exists(report_path):
        with open(report_path) as f:
            report = json.load(f)
    return report, proc


def _gate_line(report: dict) -> str:
    bits = []
    for name, g in sorted(report["gates"].items()):
        bits.append(f"{name}={'PASS' if g['pass'] else 'FAIL'}")
    m = report["measurements"]
    extra = (
        f"flip_p99={m['flip_lag_p99_ms']:.1f}ms/{m['flip_samples']}smp "
        f"eps={m['events_per_sec']:,.0f} restarts={m['restarts']}"
    )
    if m.get("recovery_s") is not None:
        extra += f" recovery={m['recovery_s']:.2f}s"
    return f"{' '.join(bits)} | {extra}"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kube_throttler_tpu.scenarios")
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("list", help="list the corpus")

    run = sub.add_parser("run", help="one scenario run")
    run.add_argument("--name", default="")
    run.add_argument("--file", default="", help="DSL program JSON (hunt mutants)")
    run.add_argument("--seed", type=int, default=0)
    run.add_argument("--workdir", default="")
    run.add_argument("--regression", default=None, choices=[None, "flip_stall"])

    regs = sub.add_parser(
        "regressions",
        help="replay the committed hunt-promoted repros, enforce pinned verdicts",
    )
    regs.add_argument("--workdir", default="")

    tr = sub.add_parser("trace", help="emit a committed trace (stdout)")
    tr.add_argument("--name", required=True)
    tr.add_argument("--seed", type=int, default=0)

    matrix = sub.add_parser("matrix", help="corpus × seeds, exit 1 on any gate failure")
    matrix.add_argument("--seeds", default="0,1,2")
    matrix.add_argument("--names", default="")
    matrix.add_argument("--workdir", default="")

    reg = sub.add_parser(
        "regression", help="clean vs injected-regression diff for one scenario"
    )
    reg.add_argument("--name", default="smoke")
    reg.add_argument("--seed", type=int, default=0)
    reg.add_argument("--workdir", default="")

    args = parser.parse_args(argv)

    from .corpus import corpus, get_scenario

    if args.command == "list":
        for scn in corpus(include_smoke=True):
            print(f"{scn.name:<18} {scn.description}")
        return 0

    if args.command == "trace":
        from .trace import build_trace, serialize_trace

        scn = get_scenario(args.name)
        header, ops = build_trace(scn, args.seed)
        sys.stdout.buffer.write(serialize_trace(header, ops))
        return 0

    from .engine import run_scenario

    def workdir_of(ns) -> str:
        if ns.workdir:
            os.makedirs(ns.workdir, exist_ok=True)
            return ns.workdir
        return tempfile.mkdtemp(prefix="kt-scenarios-")

    if args.command == "run":
        wd = workdir_of(args)
        if args.file:
            from .dsl import scenario_from_dict

            with open(args.file) as f:
                scn = scenario_from_dict(json.load(f))
        elif args.name:
            scn = get_scenario(args.name)
        else:
            print("run: one of --name / --file is required", file=sys.stderr)
            return 2
        from .hunt.mutate import needs_shard_tier

        if needs_shard_tier(scn):
            # shard.*/reshard.* sites only exist in the multiprocess
            # stack: route the program through the sharded replayer (one
            # live rescale included when reshard.* is armed) so hunt
            # mutants arming those sites actually fire them end to end
            from .sharded import run_sharded_program

            report = run_sharded_program(scn, args.seed, wd)
        else:
            report = run_scenario(scn, args.seed, wd, regression=args.regression)
        print(json.dumps(report, indent=2, default=str))
        return 0 if report["all_pass"] else 1

    if args.command == "regressions":
        from .corpus import load_regressions
        from .dsl import scenario_to_dict

        entries = load_regressions()
        if not entries:
            print("regression corpus is empty — nothing to gate")
            return 0
        wd_root = workdir_of(args)
        bad = 0
        for entry in entries:
            wd = os.path.join(wd_root, entry["name"])
            os.makedirs(wd, exist_ok=True)
            program_path = os.path.join(wd, "program.json")
            with open(program_path, "w") as f:
                json.dump(scenario_to_dict(entry["scenario"]), f)
            cmd = [
                sys.executable, "-m", "kube_throttler_tpu.scenarios", "run",
                "--file", program_path, "--seed", str(entry["seed"]),
                "--workdir", wd,
            ]
            env = dict(os.environ)
            env.setdefault("JAX_PLATFORMS", "cpu")
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=1200, env=env
            )
            report_path = os.path.join(
                wd, f"report-{entry['scenario'].name}-s{entry['seed']}.json"
            )
            if not os.path.exists(report_path):
                bad += 1
                print(
                    f"FAIL {entry['name']}: no report (rc={proc.returncode})\n"
                    f"{proc.stdout[-1500:]}"
                )
                continue
            with open(report_path) as f:
                report = json.load(f)
            failed = sorted(
                g for g, v in report["gates"].items() if not v["pass"]
            )
            if entry["expect"] == "pass":
                ok = report["all_pass"]
                want = "all gates green"
            else:
                gate = entry["expect"].split(":", 1)[1]
                ok = gate in failed
                want = f"gate {gate} still failing"
            bad += 0 if ok else 1
            print(
                f"{'PASS' if ok else 'FAIL'} {entry['name']:<28} "
                f"expect={entry['expect']} got failed={failed or 'none'} "
                f"({want})"
            )
        print(f"\n{len(entries) - bad}/{len(entries)} regression repros verdict-stable")
        return 1 if bad else 0

    if args.command == "regression":
        from .slo import diff_reports

        wd = workdir_of(args)
        clean, p1 = _run_isolated(args.name, args.seed, os.path.join(wd, "clean"))
        regressed, p2 = _run_isolated(
            args.name, args.seed, os.path.join(wd, "regressed"),
            regression="flip_stall",
        )
        if clean is None or regressed is None:
            print(f"run crashed:\n{p1.stdout[-2000:]}\n{p2.stdout[-2000:]}")
            return 1
        print(diff_reports(clean, regressed))
        demonstrated = clean["all_pass"] and not regressed["all_pass"]
        print(
            "\nregression demonstrably failed its gate"
            if demonstrated
            else "\nREGRESSION NOT DEMONSTRATED (clean run failed, or the "
            "injected stall passed every gate)"
        )
        return 0 if demonstrated else 1

    # matrix
    seeds = [int(s) for s in args.seeds.split(",") if s != ""]
    names = [n for n in args.names.split(",") if n]
    scns = [get_scenario(n) for n in names] if names else corpus()
    wd_root = workdir_of(args)
    failures = 0
    for scn in scns:
        for seed in seeds:
            wd = os.path.join(wd_root, f"{scn.name}-s{seed}")
            try:
                report, proc = _run_isolated(scn.name, seed, wd)
            except Exception as e:  # noqa: BLE001 — a run must not kill the matrix
                failures += 1
                print(f"FAIL {scn.name:<18} seed={seed} crashed: {e!r}")
                continue
            if report is None:
                failures += 1
                print(
                    f"FAIL {scn.name:<18} seed={seed} no report "
                    f"(rc={proc.returncode}):\n{proc.stdout[-1500:]}"
                )
                continue
            ok = report["all_pass"]
            failures += 0 if ok else 1
            print(
                f"{'PASS' if ok else 'FAIL'} {scn.name:<18} seed={seed} "
                f"{_gate_line(report)}"
            )
    total = len(scns) * len(seeds)
    print(f"\n{total - failures}/{total} scenario runs green (workdir {wd_root})")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
