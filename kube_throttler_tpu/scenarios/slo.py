"""Per-scenario SLO gates and their evaluation.

A gate is a named bound over the replay's measurements; a scenario's
verdict is the AND of its gates. Gates (bounds per scenario,
scenarios/dsl.py SloGates):

- ``flip_p99`` — crossing-anchored flip-publication p99 ≤ the bound
  (150 ms is the corpus default, the PR 2/PR 5 serving SLO). A run with
  fewer than ``min_flip_samples`` flip samples FAILS the gate as
  unmeasurable rather than passing vacuously;
- ``ingest_sustain`` — the replayer achieved at least ``min_pace_frac``
  of the trace's nominal op rate AND the full pipeline converged (local
  mirror ≡ apiserver truth) inside the quiesce deadline, with at least
  ``min_applied_frac`` of fired ops surviving shedding (shed-then-relist
  repairs count as applied once the relist lands them);
- ``recovery`` — after every scheduled apiserver restart: every
  reflector relisted past the reset RV floor, the wire backlog drained,
  and — when anything remained to publish — the first post-resync
  status publication landed, all within ``recovery_s`` of the restart
  (the watch → relist → reconcile → PUT loop closed again);
- ``verdicts`` — ZERO wrong admission verdicts: the serving stack's
  batch triage over its reflected state equals an oracle rebuilt from
  apiserver truth, full-population, plus a seeded per-pod host-oracle
  spot check (``thr.check_throttled_for`` against the written statuses —
  independent of every device plane and batch kernel);
- ``failover`` — the process-level kill-the-leader episode (bad-day
  scenario) promoted a standby within ``failover_window_s``.

``diff_reports`` renders the clean-vs-regressed comparison the
injected-regression acceptance check prints: per gate, both runs' values
against the shared bound, and which gates changed verdict.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from .dsl import Scenario

__all__ = ["evaluate_gates", "host_spot_check", "diff_reports"]


def _gate(value, bound, ok: bool, note: str = "") -> Dict:
    out = {"pass": bool(ok), "value": value, "bound": bound}
    if note:
        out["note"] = note
    return out


def _latency_gates_enforced() -> bool:
    """The corpus flip-lag bounds are wall-clock SLOs calibrated against
    hosts with at least KT_SCENARIO_LATENCY_CORE_FLOOR cores (default 2
    — the replayer, the serving stack, and the apiserver twin each need
    scheduling headroom). On a more starved host the p99 overshoots with
    no code regression, so below the floor the latency gates report
    their measured values as ADVISORY (pass, with a would-FAIL note)
    instead of enforcing; correctness gates (verdicts, recovery,
    ingest_sustain) are host-speed-independent and always enforce.
    KT_SCENARIO_ENFORCE_LATENCY=1 forces enforcement regardless — the
    injected-regression acceptance test sets it so the gate demonstrably
    still gates."""
    if os.environ.get("KT_SCENARIO_ENFORCE_LATENCY") == "1":
        return True
    try:
        floor = int(os.environ.get("KT_SCENARIO_LATENCY_CORE_FLOOR", "2"))
    except ValueError:
        floor = 2  # malformed override must not change the gate contract
    return len(os.sched_getaffinity(0)) >= floor


def evaluate_gates(scn: Scenario, m: Dict) -> Dict[str, Dict]:
    """``m`` is the engine's measurement dict (scenarios/engine.py). Gates
    whose bound is None (or whose fault never fired) are skipped."""
    slo = scn.slo
    gates: Dict[str, Dict] = {}

    p99 = m.get("flip_lag_p99_ms")
    samples = m.get("flip_samples", 0)
    if samples < slo.min_flip_samples:
        gates["flip_p99"] = _gate(
            None, slo.flip_p99_ms, False,
            f"unmeasurable: {samples} flip samples < {slo.min_flip_samples}",
        )
    else:
        enforced = _latency_gates_enforced()
        ok99 = p99 <= slo.flip_p99_ms
        note99 = f"{samples} samples from {m.get('flip_crossings', 0)} crossings"
        if not enforced and not ok99:
            note99 += "; ADVISORY (host below latency core floor) — would FAIL"
        gates["flip_p99"] = _gate(
            round(p99, 2), slo.flip_p99_ms, ok99 or not enforced, note99
        )
        if slo.flip_p50_ms is not None:
            p50 = m.get("flip_lag_p50_ms", 0.0)
            ok50 = p50 <= slo.flip_p50_ms
            gates["flip_p50"] = _gate(
                round(p50, 2), slo.flip_p50_ms, ok50 or not enforced,
                ""
                if enforced or ok50
                else "ADVISORY (host below latency core floor) — would FAIL",
            )

    pace_frac = m.get("pace_frac", 0.0)
    applied_frac = m.get("applied_frac", 0.0)
    converged = bool(m.get("converged"))
    gates["ingest_sustain"] = _gate(
        {
            "pace_frac": round(pace_frac, 3),
            "applied_frac": round(applied_frac, 3),
            "converged": converged,
            "events_per_sec": round(m.get("events_per_sec", 0.0), 1),
            "shed": m.get("ingest_dropped", 0),
        },
        {
            "min_pace_frac": slo.min_pace_frac,
            "min_applied_frac": slo.min_applied_frac,
        },
        converged
        and pace_frac >= slo.min_pace_frac
        and applied_frac >= slo.min_applied_frac,
    )

    if slo.recovery_s is not None and m.get("restarts", 0) > 0:
        rec = m.get("recovery_s")
        gates["recovery"] = _gate(
            None if rec is None else round(rec, 3),
            slo.recovery_s,
            rec is not None and rec <= slo.recovery_s,
            f"{m.get('restarts')} restart(s)",
        )

    wrong = m.get("wrong_verdicts")
    gates["verdicts"] = _gate(
        {
            "wrong": wrong,
            "checked": m.get("verdicts_checked", 0),
            "spot_checked": m.get("spot_checked", 0),
            "examples": m.get("wrong_examples", [])[:5],
        },
        slo.max_wrong_verdicts,
        wrong is not None and wrong <= slo.max_wrong_verdicts,
    )

    if scn.leader_kill and slo.failover_window_s is not None:
        window = m.get("failover_window_s")
        gates["failover"] = _gate(
            None if window is None else round(window, 3),
            slo.failover_window_s,
            window is not None and window <= slo.failover_window_s,
        )
    return gates


def host_spot_check(serving_verdicts: Dict[str, bool], oracle_store,
                    sample: List, throttles=None, cluster_throttles=None,
                    ) -> List[str]:
    """Independent per-pod admission oracle over ``sample`` pods: a plain
    Python walk of the oracle store's throttles — selector match +
    ``check_throttled_for`` against the WRITTEN statuses, no device
    planes, no batch kernels, no listers. Returns the pod keys whose
    serving verdict disagrees."""
    from ..api.pod import accel_class_of
    from ..api.types import ResourceAmount

    if throttles is None:
        throttles = oracle_store.list_throttles()
    if cluster_throttles is None:
        cluster_throttles = oracle_store.list_cluster_throttles()
    empty = ResourceAmount()
    wrong: List[str] = []
    for pod in sample:
        accel = accel_class_of(pod)
        blocked = False
        for thr in throttles:
            if thr.namespace != pod.namespace:
                continue
            if not thr.spec.selector.matches_to_pod(pod):
                continue
            if (
                thr.check_throttled_for(pod, empty, False, accel_class=accel)
                != "not-throttled"
            ):
                blocked = True
                break
        if not blocked:
            for thr in cluster_throttles:
                if not thr.spec.selector.matches_to_pod(pod):
                    continue
                if (
                thr.check_throttled_for(pod, empty, False, accel_class=accel)
                != "not-throttled"
            ):
                    blocked = True
                    break
        want = not blocked
        got = serving_verdicts.get(pod.key)
        if got is not want:
            wrong.append(pod.key)
    return wrong


def diff_reports(clean: Dict, regressed: Dict) -> str:
    """Human-readable per-gate diff between a clean run's report and an
    injected-regression run's — the acceptance artifact proving a broken
    SLO demonstrably fails its gate."""
    lines = [
        f"scenario {clean['scenario']} seed {clean['seed']}: "
        "clean vs injected-regression",
        f"  regression: {regressed.get('regression') or '(none)'}",
    ]
    names = sorted(set(clean["gates"]) | set(regressed["gates"]))
    flipped = []
    for name in names:
        c = clean["gates"].get(name)
        r = regressed["gates"].get(name)
        cs = "-" if c is None else ("PASS" if c["pass"] else "FAIL")
        rs = "-" if r is None else ("PASS" if r["pass"] else "FAIL")
        cv = None if c is None else c["value"]
        rv = None if r is None else r["value"]
        bound = (c or r)["bound"]
        lines.append(
            f"  {name:<14} clean={cs:<4} {cv!r:<40} regressed={rs:<4} {rv!r} "
            f"(bound {bound!r})"
        )
        if cs == "PASS" and rs == "FAIL":
            flipped.append(name)
    lines.append(
        f"  verdict: clean all_pass={clean['all_pass']} regressed "
        f"all_pass={regressed['all_pass']}; gates flipped by the "
        f"regression: {flipped or 'NONE'}"
    )
    lines.extend(_diff_fingerprints(clean, regressed))
    return "\n".join(lines)


def _diff_fingerprints(clean: Dict, regressed: Dict) -> List[str]:
    """Behavioral diff from the structured run fingerprints (engine
    ``report["fingerprint"]``): fault sites fired in only one run (or at
    different hit counts), health transitions unique to either side, and
    metric families only one run touched — the "what actually changed"
    companion to the per-gate value diff."""
    cf = clean.get("fingerprint")
    rf = regressed.get("fingerprint")
    if not cf or not rf:
        return []
    lines: List[str] = ["  fingerprint diff:"]
    c_sites, r_sites = cf.get("fault_sites", {}), rf.get("fault_sites", {})
    site_diffs = [
        f"{s}({c_sites.get(s, 0)}→{r_sites.get(s, 0)})"
        for s in sorted(set(c_sites) | set(r_sites))
        if c_sites.get(s, 0) != r_sites.get(s, 0)
    ]
    lines.append(f"    fault sites:  {', '.join(site_diffs) or '(identical)'}")

    def _tset(fp):
        return {tuple(t) for t in fp.get("health_transitions", [])}

    only_c = _tset(cf) - _tset(rf)
    only_r = _tset(rf) - _tset(cf)
    if only_c or only_r:
        for tag, ts in (("clean-only", only_c), ("regressed-only", only_r)):
            if ts:
                rendered = ", ".join(
                    f"{c}:{old}->{new}" for c, old, new in sorted(ts)
                )
                lines.append(f"    transitions {tag}: {rendered}")
    else:
        lines.append("    transitions:  (identical)")
    c_fams = set(cf.get("metric_families", {}))
    r_fams = set(rf.get("metric_families", {}))
    fam_bits = []
    if r_fams - c_fams:
        fam_bits.append(f"regressed-only: {', '.join(sorted(r_fams - c_fams))}")
    if c_fams - r_fams:
        fam_bits.append(f"clean-only: {', '.join(sorted(c_fams - r_fams))}")
    lines.append(
        f"    metric families: {'; '.join(fam_bits) or '(same set touched)'}"
    )
    return lines
