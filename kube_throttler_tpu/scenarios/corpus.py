"""The starter scenario corpus (≥6 entries) plus the tier-1 smoke.

Each entry composes arrival × topology × fault schedule into a shape
production control planes actually see (ROADMAP item 5's list), with
per-scenario SLO bounds the slow tier gates on. Scales are chosen so the
full matrix (6 scenarios × 3 seeds, ``make scenario-test``) runs in
minutes on one CPU core while still forcing the behaviors the gates
exist to catch: a full relist mid-churn, one throttle matching half the
pod population, a deployment-sized create burst, a composed bad day.

``smoke`` is the tier-1 determinism scenario: small enough for two
back-to-back runs in the fast tier, still crossing thresholds (flip
samples) and restarting the apiserver (recovery gate).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

from .dsl import Arrival, FaultSpec, Scenario, SloGates, Topology, scenario_from_dict

__all__ = [
    "REGRESSIONS_DIR",
    "SCENARIOS",
    "corpus",
    "get_scenario",
    "load_regressions",
]

# hunt-promoted minimal repros (scenarios/hunt/): each JSON file is one
# shrunk, gate-failing program plus its pinned verdict — a PERMANENT tier
# gate replayed by `python -m kube_throttler_tpu.scenarios regressions`
# (wired into `make scenario-test`). Plain data directory, not a package.
REGRESSIONS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "corpus", "regressions"
)


def _scenarios() -> List[Scenario]:
    return [
        Scenario(
            name="smoke",
            description=(
                "tier-1 determinism smoke: small diurnal churn with one "
                "mid-run apiserver restart (RV reset) — two runs of the same "
                "seed must produce byte-identical traces and identical gate "
                "verdicts"
            ),
            duration_s=2.0,
            arrival=Arrival(kind="diurnal", rate_hz=500.0, trough_frac=0.35, cycles=1.0),
            topology=Topology(pods=600, throttles=48, groups=24, nodes=4),
            faults=(
                FaultSpec(site="scenario.apiserver.restart", mode="restart", t=0.9),
            ),
            # tier-1 bounds are deliberately loose: this scenario proves
            # determinism + pipeline correctness inside a busy test
            # process; the strict flip SLO is the corpus' job (slow tier)
            slo=SloGates(flip_p99_ms=400.0, recovery_s=15.0, min_pace_frac=0.3),
        ),
        Scenario(
            name="diurnal_ramp",
            description=(
                "compressed day/night traffic: sinusoidal arrival between "
                "20% and 100% of peak, two cycles — the baseline 'nothing "
                "broken, load just moves' scenario every other gate is "
                "compared against"
            ),
            duration_s=6.0,
            arrival=Arrival(kind="diurnal", rate_hz=600.0, trough_frac=0.2, cycles=2.0),
            topology=Topology(pods=6000, throttles=300, groups=150, nodes=8),
            slo=SloGates(flip_p99_ms=150.0),
        ),
        Scenario(
            name="relist_storm",
            description=(
                "post-restart relist storm: the apiserver restarts mid-churn "
                "with a fresh RV horizon (410 on every re-watch ⇒ full "
                "paginated relists of the whole object population) and then "
                "expires outstanding continue tokens mid-pagination (410 ⇒ "
                "unpaginated fallback) — the reflector's full relist must "
                "not starve the flip express lane"
            ),
            duration_s=7.0,
            arrival=Arrival(kind="constant", rate_hz=500.0),
            topology=Topology(pods=12000, throttles=360, groups=180, nodes=10),
            faults=(
                FaultSpec(site="scenario.apiserver.restart", mode="restart", t=2.5),
                FaultSpec(
                    site="scenario.apiserver.restart", mode="expire_continues", t=3.1
                ),
            ),
            slo=SloGates(flip_p99_ms=150.0, recovery_s=15.0, min_pace_frac=0.4),
        ),
        Scenario(
            name="rolling_drain",
            description=(
                "rolling node drain: every node's pods deleted in waves and "
                "recreated on replacement nodes while background churn "
                "continues — sustained delete/create pressure with correct "
                "used-sum convergence"
            ),
            duration_s=8.0,
            arrival=Arrival(kind="constant", rate_hz=350.0),
            topology=Topology(pods=4800, throttles=300, groups=150, nodes=12),
            pattern="drain",
            # membership churn (deletes + recreates) keeps the 1-core
            # harness near its knee and the p99 rides co-tenant noise:
            # gate the stable center tightly and BOUND the degradation
            slo=SloGates(flip_p50_ms=250.0, flip_p99_ms=2500.0),
        ),
        Scenario(
            name="thundering_herd",
            description=(
                "thundering-herd deployment: an 1800-pod create wave lands "
                "at 25% of the run over ~2s, is deleted again at 65% — "
                "admission verdicts and flip publication must survive the "
                "step change in every group's used sum"
            ),
            duration_s=8.0,
            arrival=Arrival(kind="constant", rate_hz=350.0),
            topology=Topology(pods=4000, throttles=240, groups=120, nodes=8),
            pattern="herd",
            herd_size=1800,
            # same posture as rolling_drain: the herd window saturates the
            # harness by design — tight p50, bounded p99 degradation
            slo=SloGates(flip_p50_ms=250.0, flip_p99_ms=2500.0),
        ),
        Scenario(
            name="hotkey_throttle",
            description=(
                "hot-key throttle: HALF the pod population shares one label "
                "group matched by a single throttle whose cpu threshold sits "
                "at the group's expected sum — the dominant (N,K) column "
                "flips under churn and its publication must stay inside the "
                "SLO while every event in the cluster touches its key"
            ),
            duration_s=6.0,
            arrival=Arrival(kind="constant", rate_hz=550.0),
            topology=Topology(
                pods=10000, throttles=300, groups=150, hot_frac=0.5, nodes=8
            ),
            slo=SloGates(flip_p99_ms=150.0),
        ),
        Scenario(
            name="bad_day",
            description=(
                "the composed bad day: diurnal churn + an apiserver restart "
                "storm (RV reset) + a status-409 conflict burst while the "
                "backlog drains + watch cuts, then a process-level "
                "kill-the-leader failover episode through the PR 6 ha.* "
                "sites (tools/harness.py + tools/hatest.py)"
            ),
            duration_s=7.0,
            arrival=Arrival(kind="diurnal", rate_hz=700.0, trough_frac=0.3, cycles=1.5),
            topology=Topology(pods=6000, throttles=300, groups=150, nodes=8),
            faults=(
                FaultSpec(site="scenario.apiserver.restart", mode="restart", t=2.0),
                FaultSpec(
                    site="mock.status.conflict", mode="conflict",
                    window=(2.6, 4.2), probability=0.25,
                ),
                FaultSpec(
                    site="mock.watch.cut", mode="close",
                    window=(4.5, 5.0), probability=0.02, times=2,
                ),
            ),
            # 250ms: flips here pay the INJECTED 409-retry storms by
            # design (refresh+retry per conflict); the clean-storm 150ms
            # SLO is relist_storm's and hotkey_throttle's gate
            slo=SloGates(
                flip_p99_ms=250.0, recovery_s=15.0, min_pace_frac=0.4,
                failover_window_s=10.0,
            ),
            leader_kill=True,
        ),
        Scenario(
            name="partition_bad_day",
            description=(
                "the composed bad day replayed through a TCP shard fleet "
                "(transport='tcp' supervisor) with a seeded ASYMMETRIC "
                "network partition mid-storm: one shard's client-side "
                "net.partition window blackholes front→worker sends while "
                "the worker stays healthy, then heals into an epoch-bumped "
                "resync (stale frames fenced), plus one post-heal torn "
                "frame so reconnect runs twice. Trace bytes are IDENTICAL "
                "to bad_day (the net faults live client-side, outside the "
                "trace) — the gates are the deterministic ones: zero wrong "
                "verdicts, zero lost flips, bounded heal→converged "
                "recovery, clean two-phase audits, real fencing evidence. "
                "Driven by scenarios/partition.py — excluded from the "
                "generic replay matrix (like preempt_storm), wired into "
                "`make scenario-test` via its own runner"
            ),
            duration_s=7.0,
            arrival=Arrival(kind="diurnal", rate_hz=700.0, trough_frac=0.3, cycles=1.5),
            topology=Topology(pods=6000, throttles=300, groups=150, nodes=8),
            faults=(
                FaultSpec(
                    site="net.partition", mode="error", window=(3.5, 5.5)
                ),
                FaultSpec(site="net.send.torn_frame", mode="torn", times=1),
            ),
            # no flip SLO: the partition window IS the latency story; the
            # runner gates recovery + the zero-wrong/zero-lost invariants
            slo=SloGates(flip_p99_ms=10_000.0, recovery_s=20.0),
        ),
        Scenario(
            name="rolling_upgrade",
            description=(
                "a live fleet rolled one process at a time under the "
                "composed bad-day storm: a 3-worker TCP shard fleet takes "
                "diurnal churn while tools/upgradetest.py bounces every "
                "worker front-first AND worker-first behind the resync "
                "barrier (ShardSupervisor.rolling_restart), stages version "
                "skew via KT_PROTO_CAPS_MASK (old-caps workers speak the "
                "pickle fallback while new ones speak columnar frames), "
                "SIGKILLs one non-bouncing shard mid-roll, and refuses an "
                "incompatible KT_PROTO_MAJOR cleanly (typed VersionMismatch, "
                "degraded health, paced restarts — no crash loop). Gates: "
                "zero wrong verdicts, zero lost flips, zero orphan "
                "reservations, bounded per-bounce recovery. Driven by "
                "tools/upgradetest.py (`make upgrade-test`) — excluded from "
                "the generic replay matrix (like partition_bad_day): it "
                "needs the live fleet its runner builds"
            ),
            duration_s=7.0,
            arrival=Arrival(kind="diurnal", rate_hz=700.0, trough_frac=0.3, cycles=1.5),
            topology=Topology(pods=6000, throttles=300, groups=150, nodes=8),
            # no flip SLO: bounces ARE the latency story; the runner gates
            # per-bounce recovery + the zero-wrong/zero-lost invariants
            slo=SloGates(flip_p99_ms=10_000.0, recovery_s=20.0),
        ),
        Scenario(
            name="preempt_storm",
            description=(
                "preemption storm: waves of high-priority gangs land on "
                "throttles filled by low-priority running work (some of it "
                "gang-shaped), each wave forcing gang-aware victim "
                "selection, whole-gang eviction, and delete-then-requeue "
                "admission; evicted victims are recreated between waves so "
                "the no-thrash SLO gate (evicted-then-readmitted rate "
                "bounded) has a real churn signal. Driven by "
                "scenarios/preemption.py through a real plugin + scheduler "
                "stack with a preemption-enabled policy — excluded from "
                "the generic replay matrix (like smoke), wired into "
                "`make scenario-test` via its own runner"
            ),
            duration_s=6.0,
            arrival=Arrival(kind="bursts", rate_hz=400.0, burst_s=0.5, idle_s=1.0),
            topology=Topology(
                pods=480, throttles=24, groups=12, nodes=8,
                gang_size=4, priority_levels=4,
            ),
            slo=SloGates(flip_p99_ms=2500.0),
        ),
    ]


def load_regressions() -> List[Dict]:
    """The committed regression corpus, parsed and validated. Each entry:

    - ``scenario`` — the shrunk minimal repro (a full DSL program);
    - ``seed`` — the trace seed it was found and shrunk under;
    - ``expect`` — the pinned verdict: ``"fail:<gate>"`` while the
      underlying bug (or the injected fault class the gate must catch) is
      live — the replay must STILL fail exactly that gate, proving the
      gate still gates this trace; or ``"pass"`` once a real bug is fixed
      — the repro becomes an ordinary always-green regression test.
      Maintainers flip fail→pass in the committed file when they land the
      fix (the lifecycle is documented in docs/scenarios.md);
    - ``provenance`` — how the hunt found it (parent sha, hunt seed,
      iteration, shrink steps, original trace sha).

    A malformed file raises: a promoted repro that silently fails to load
    is a regression gate that silently stopped gating."""
    entries: List[Dict] = []
    if not os.path.isdir(REGRESSIONS_DIR):
        return entries
    for fn in sorted(os.listdir(REGRESSIONS_DIR)):
        if not fn.endswith(".json"):
            continue
        path = os.path.join(REGRESSIONS_DIR, fn)
        with open(path) as f:
            raw = json.load(f)
        expect = raw.get("expect", "pass")
        if expect != "pass" and not expect.startswith("fail:"):
            raise ValueError(f"{path}: bad expect {expect!r}")
        entries.append(
            {
                "path": path,
                "name": os.path.splitext(fn)[0],
                "scenario": scenario_from_dict(raw["scenario"]),
                "seed": int(raw.get("seed", 0)),
                "expect": expect,
                "provenance": raw.get("provenance", {}),
            }
        )
    return entries


def corpus(include_smoke: bool = False) -> List[Scenario]:
    # preempt_storm never rides the generic replay matrix: its gates need
    # the scheduler+preemption stack its dedicated runner builds
    # (scenarios/preemption.py, its own `make scenario-test` line).
    # partition_bad_day likewise: it needs the TCP fleet its runner builds
    # (scenarios/partition.py, its own `make scenario-test` line).
    # rolling_upgrade likewise: it needs the live fleet + process bounces
    # its runner builds (tools/upgradetest.py, `make upgrade-test`)
    out = [
        s for s in _scenarios()
        if s.name not in ("preempt_storm", "partition_bad_day", "rolling_upgrade")
    ]
    return out if include_smoke else [s for s in out if s.name != "smoke"]


SCENARIOS: Dict[str, Scenario] = {s.name: s for s in _scenarios()}


def get_scenario(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; known: {', '.join(sorted(SCENARIOS))}"
        )
