"""Shared measurement anchors for the scenario engine and bench.py.

These were born in bench.py's serving rungs (PR 2/PR 5) and moved here so
the scenario corpus and the bench ladder measure flip lag with ONE
implementation — a per-scenario flip-p99 that silently anchored
differently from the bench's would make the SLO gate incomparable with
every BENCH_*.json on record. bench.py imports these under its historical
underscore names.
"""

from __future__ import annotations

from ..utils.lockorder import make_lock


def lag_tracker():
    """(pending, flip_pending, lock, lags, flip_lags, flip_walls,
    handler): handler pops a key's oldest pending timestamp on its
    MODIFIED event and records the lag sample — into ``lags`` always
    (total lag), and ALSO into ``flip_lags`` when the write changed the
    throttled flags or the calculated threshold (a FLIP: the only status
    change that alters admission verdicts); ``flip_walls[i]`` is flip
    sample i's publication wall time (perf_counter), which lets the
    scenario engine partition flips into steady-state vs outage-affected
    (a crossing stamped while the apiserver is restarting cannot publish
    before the relist closes the loop — the recovery gate owns that
    window, the flip gate owns steady state). The flip/total split is the
    bench-side mirror of the daemon's
    kube_throttler_status_flip_lag_seconds histograms.

    The two samples anchor to DIFFERENT events, deliberately:

    - total lag anchors to the key's OLDEST unpublished event (the
      staleness window — coalescing must not shrink it);
    - flip lag anchors to the LATEST crossing event (``flip_pending``,
      stamped by the churn generator when a group's running cpu sum
      actually crosses a throttle's threshold — see ``flip_watch_of``).
      A value-only refresh queued 2 s ago does not make the *flag* wrong;
      the flag is only wrong from the crossing onward, so pairing a flip
      write with the oldest refresh event would overstate flip lag by the
      whole refresh backlog. Latest-crossing (overwrite, not setdefault)
      handles cross-back sequences: after cross→cross-back→cross, the
      published flag is newly wrong from the LAST crossing, and anchoring
      the first would blame the daemon for the interval the flag was
      accidentally right. The stamp is popped only by a flip write —
      clearing it on value-only writes would race a write computed from
      pre-crossing aggregates landing just after the stamp. When no
      crossing is pending for a flipping key (e.g. a calculatedThreshold
      change), the sample falls back to the oldest-pending anchor
      (conservative: overstates, never understates)."""
    import time as _time

    from ..engine.store import EventType

    pending: dict = {}
    flip_pending: dict = {}
    lock = make_lock("scenarios.lagtracker")
    lags: list = []
    flip_lags: list = []
    flip_walls: list = []

    def on_write(event):
        if event.type != EventType.MODIFIED:
            return
        now = _time.perf_counter()
        key = event.obj.key
        old = event.old_obj
        flipped = old is not None and (
            old.status.throttled != event.obj.status.throttled
            or old.status.calculated_threshold.threshold
            != event.obj.status.calculated_threshold.threshold
        )
        with lock:
            t0 = pending.pop(key, None)
            tf = flip_pending.pop(key, None) if flipped else None
        if flipped:
            anchor = tf if tf is not None else t0
            if anchor is not None:
                flip_lags.append(now - anchor)
                flip_walls.append(now)
        if t0 is not None:
            lags.append(now - t0)

    return pending, flip_pending, lock, lags, flip_lags, flip_walls, on_write


def flip_watch_of(store):
    """(flip_watch, run_sums) for crossing-anchored flip-lag measurement:
    ``flip_watch`` maps group → [(throttle key, cpu threshold milli)] for
    every throttle with a cpu-requests threshold; ``run_sums`` seeds each
    group's running cpu sum (milli) from the stored pods — the same values
    the churn generator seeds its per-pod ``prev`` from, so the
    incremental sums track the daemon's ``status.used`` exactly."""
    from ..resourcelist import pod_request_resource_list

    flip_watch: dict = {}
    for thr in store.list_throttles():
        cpu = (thr.spec.threshold.resource_requests or {}).get("cpu")
        if cpu is None:
            continue
        g = thr.spec.selector.selector_terms[0].pod_selector.match_labels["grp"]
        flip_watch.setdefault(g, []).append((thr.key, int(cpu * 1000)))
    run_sums: dict = {}
    for pod in store.list_pods():
        g = pod.labels.get("grp")
        if g is None:
            continue
        cpu = pod_request_resource_list(pod).get("cpu")
        run_sums[g] = run_sums.get(g, 0) + (int(cpu * 1000) if cpu else 0)
    return flip_watch, run_sums


def count_watch_of(store):
    """(count_watch, run_counts) — the pod-COUNT analog of
    :func:`flip_watch_of`: ``count_watch`` maps group → [(throttle key,
    pod-count threshold)] for throttles with a FINITE count threshold
    (the 10^6 open-class sentinel is ignored until spec churn lowers it);
    ``run_counts`` seeds each group's live pod count. Creates/deletes
    crossing a count threshold are flips exactly like cpu-sum crossings —
    without this watch the drain/herd scenarios' count flips anchored to
    the oldest refresh and reported backlog age as flip lag."""
    watch: dict = {}
    for thr in store.list_throttles():
        cnt = thr.spec.threshold.resource_counts
        if cnt is None or cnt >= 10**5:
            continue
        g = thr.spec.selector.selector_terms[0].pod_selector.match_labels["grp"]
        watch.setdefault(g, []).append((thr.key, int(cnt)))
    counts: dict = {}
    for pod in store.list_pods():
        g = pod.labels.get("grp")
        if g is not None:
            counts[g] = counts.get(g, 0) + 1
    return watch, counts


def group_keys_of(store):
    """group label value → [throttle keys] (the pending-registration map
    the lag tracker pairs events with)."""
    group_keys: dict = {}
    for thr in store.list_throttles():
        g = thr.spec.selector.selector_terms[0].pod_selector.match_labels["grp"]
        group_keys.setdefault(g, []).append(thr.key)
    return group_keys


def served_throttle(i: int, groups: int, flip_band_mc: int = 0):
    """Throttle i selecting pod group g{i%groups}; threshold class varies so
    probe verdicts mix (open / tight cpu / pod-count).

    ``flip_band_mc`` > 0 carves a FLIP BAND out of the tight-cpu class:
    every 24th throttle's cpu threshold sits AT the expected group cpu sum
    (P/groups × the 400m churn mean), so the paced churn's random walk
    around that sum produces real throttled↔not-throttled crossings — the
    events the flip-lag percentiles measure. Without the band, a scale
    mismatch leaves every cpu threshold far from the live sum (at 100k×10k
    the group sum ~80 cpu dwarfs the 2-14 cpu class) and a whole window
    can pass with zero flips, making flip_lag_p99 unmeasurable."""
    from ..api.types import (
        LabelSelector,
        ResourceAmount,
        Throttle,
        ThrottleSelector,
        ThrottleSelectorTerm,
        ThrottleSpec,
    )

    if flip_band_mc and i % 24 == 1:
        threshold = ResourceAmount.of(requests={"cpu": f"{flip_band_mc}m"})
    elif i % 3 == 0:
        threshold = ResourceAmount.of(pod=10**6, requests={"cpu": "100000"})
    elif i % 3 == 1:
        threshold = ResourceAmount.of(requests={"cpu": f"{(i % 7 + 1) * 2}"})
    else:
        threshold = ResourceAmount.of(pod=(i % 50) + 5)
    return Throttle(
        name=f"t{i}",
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=threshold,
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        LabelSelector(match_labels={"grp": f"g{i % groups}"})
                    ),
                )
            ),
        ),
    )


def flip_band_mc(P: int, groups: int) -> int:
    """Expected group cpu sum in milli: P/groups pods × the 400m mean of
    the churn generator's rng.randrange(1, 8) * 100 distribution."""
    return round(P / groups * 400)
