"""Committed, replayable scenario traces.

``build_trace(scenario, seed)`` is a PURE function: topology and op
stream derive from seeded ``random.Random`` instances keyed by
``(scenario.name, seed)`` and nothing else — no wall clock, no host
state. The serialized form (one JSON line per record, sorted keys, fixed
separators, integer-microsecond timestamps) is therefore byte-identical
across runs and hosts: the tier-1 determinism smoke hashes it, and a
committed trace file IS the reproduction recipe for whatever its replay
exposed.

Record shapes:

- header — scenario parameters, seed, trace format version, the fault
  schedule, and the topology's sha256 (topology is derivable, so only its
  hash ships);
- ops — ``update_pod`` / ``create_pod`` / ``delete_pod`` /
  ``update_throttle``, each carrying the virtual time ``t_us``, the pod's
  label group, and the cpu delta bookkeeping (``cpu_m``/``prev_m``) the
  replayer needs for crossing-anchored flip stamping
  (scenarios/measure.py) without re-deriving generator state.

Patterns beyond plain churn are generated INLINE with the background
stream (a single time-ordered pass), so the per-pod ``prev_m`` chain
stays exact across drain waves and herd bursts — the flip-stamp
bookkeeping would silently drift if patterns were generated separately
and merged.
"""

from __future__ import annotations

import hashlib
import heapq
import json
import random
from dataclasses import asdict
from typing import Dict, List, Tuple

from .dsl import Scenario, arrival_rate, compile_fault_rules

# v2: the header additionally commits the CANONICAL compiled fault plan
# (faults/plan.py FaultRule.canonical, priority order preserved) and its
# sha256 — the hunt's dedupe key for mutants whose schedules differ only
# in surface form, and the reviewer's answer to "what does this trace
# actually arm?" without re-deriving the compile step.
TRACE_VERSION = 2

__all__ = [
    "TRACE_VERSION",
    "build_topology",
    "build_trace",
    "canonical_fault_plan",
    "serialize_trace",
    "trace_sha256",
]


def canonical_fault_plan(scn: Scenario) -> Tuple[List[Dict], str]:
    """→ (canonical rule list, sha256 of its stable JSON form): the
    scenario's fault schedule compiled exactly as the engine compiles it
    (dsl.compile_fault_rules), then canonicalized. Equal shas ⇒ the two
    scenarios arm byte-for-byte the same effective plan."""
    from ..faults.plan import FaultPlan

    plan = FaultPlan(seed=0)
    compile_fault_rules(plan, scn)
    rules = plan.canonical_rules()
    blob = json.dumps(rules, sort_keys=True, separators=(",", ":")).encode()
    return rules, hashlib.sha256(blob).hexdigest()


def build_topology(scn: Scenario, seed: int) -> Dict:
    """The pre-trace object population, derived from the seed alone:
    pod specs (name, label group, initial cpu milli, node) plus the hot
    group's size. Throttles are fully determined by the scenario (counts,
    groups, flip band) and need no randomness."""
    rng = random.Random(f"{scn.name}/{seed}/topo")
    topo = scn.topology
    n_hot = int(topo.pods * topo.hot_frac)
    n_classes = getattr(topo, "accel_classes", 0)
    gang_size = getattr(topo, "gang_size", 0)
    n_priorities = getattr(topo, "priority_levels", 0)
    gang_counters: Dict[str, int] = {}
    pods: List[Dict] = []
    for i in range(topo.pods):
        grp = "hot" if i < n_hot else f"g{rng.randrange(topo.groups)}"
        spec = {
            "name": f"p{i}",
            "grp": grp,
            "cpu_m": rng.randrange(1, 8) * 100,
            "node": f"n{i % max(topo.nodes, 1)}",
        }
        # gang/heterogeneity/priority axes (PR 7 + PR 15 admission and
        # policy paths): keys appear ONLY when the axis is on, so axis-off
        # topologies — every committed trace — keep their exact bytes/shas
        if n_classes > 0:
            spec["acl"] = f"ac{i % n_classes}"
        if gang_size > 0:
            c = gang_counters.get(grp, 0)
            gang_counters[grp] = c + 1
            spec["gang"] = f"gg-{grp}-{c // gang_size}"
        if n_priorities > 0:
            spec["pri"] = rng.randrange(n_priorities)
        pods.append(spec)
    return {"pods": pods, "n_hot": n_hot}


def _topology_sha(topology: Dict) -> str:
    blob = json.dumps(topology, sort_keys=True, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


def build_trace(scn: Scenario, seed: int) -> Tuple[Dict, List[Dict]]:
    """→ (header, ops). Ops are time-ordered; ties keep emission order via
    the monotone ``seq`` field."""
    topology = build_topology(scn, seed)
    rng = random.Random(f"{scn.name}/{seed}/ops")
    topo = scn.topology

    cur_cpu = {p["name"]: p["cpu_m"] for p in topology["pods"]}
    grp_of = {p["name"]: p["grp"] for p in topology["pods"]}
    node_of = {p["name"]: p["node"] for p in topology["pods"]}
    acl_of = {p["name"]: p["acl"] for p in topology["pods"] if "acl" in p}
    gang_of = {p["name"]: p["gang"] for p in topology["pods"] if "gang" in p}
    pri_of = {p["name"]: p["pri"] for p in topology["pods"] if "pri" in p}
    n_classes = getattr(topo, "accel_classes", 0)
    gang_size = getattr(topo, "gang_size", 0)
    n_priorities = getattr(topo, "priority_levels", 0)

    def annot_fields(name: str) -> Dict:
        out: Dict = {}
        if name in acl_of:
            out["acl"] = acl_of[name]
        if name in gang_of:
            out["gang"] = gang_of[name]
            out["gsz"] = gang_size
        if name in pri_of:
            out["pri"] = pri_of[name]
        return out
    alive = [p["name"] for p in topology["pods"]]
    alive_set = set(alive)
    weights = scn.mix_weights()
    w_update = weights.get("update", 1.0)
    w_create = w_update + weights.get("create", 0.0)
    w_delete = w_create + weights.get("delete", 0.0)
    w_total = w_delete + weights.get("spec", 0.0)

    ops: List[Dict] = []
    seq = 0

    def emit(t: float, verb: str, **fields) -> None:
        nonlocal seq
        seq += 1
        ops.append({"t_us": int(round(t * 1e6)), "seq": seq, "verb": verb, **fields})

    def pick_alive() -> str:
        # uniform over the CURRENT population; dead names are lazily
        # skipped (deletions compact on pick, keeping the draw O(1) amortized)
        while alive:
            name = alive[rng.randrange(len(alive))]
            if name in alive_set:
                return name
            alive.remove(name)
        raise RuntimeError("trace generator ran out of pods")

    def emit_update(t: float, name: str) -> None:
        prev = cur_cpu[name]
        new_cpu = rng.randrange(1, 8) * 100
        if new_cpu == prev:
            new_cpu = new_cpu % 700 + 100
        cur_cpu[name] = new_cpu
        emit(
            t, "update_pod",
            name=name, grp=grp_of[name], node=node_of[name],
            cpu_m=new_cpu, prev_m=prev, **annot_fields(name),
        )

    def emit_create(t: float, name: str, grp: str, node: str) -> None:
        cpu = rng.randrange(1, 8) * 100
        cur_cpu[name] = cpu
        grp_of[name] = grp
        node_of[name] = node
        if n_classes > 0 and name not in acl_of:
            acl_of[name] = f"ac{rng.randrange(n_classes)}"
        if n_priorities > 0 and name not in pri_of:
            pri_of[name] = rng.randrange(n_priorities)
        alive.append(name)
        alive_set.add(name)
        emit(
            t, "create_pod", name=name, grp=grp, node=node, cpu_m=cpu,
            prev_m=0, **annot_fields(name),
        )

    def emit_delete(t: float, name: str) -> None:
        alive_set.discard(name)
        emit(
            t, "delete_pod",
            name=name, grp=grp_of[name], node=node_of[name],
            cpu_m=0, prev_m=cur_cpu.get(name, 0),
        )
        cur_cpu[name] = 0

    # scheduled pattern extras: (t, tiebreak, kind, payload) heap, generated
    # lazily when virtual time reaches each wave/burst trigger so the
    # population snapshot they act on reflects all prior churn
    extras: List[Tuple[float, int, str, Tuple]] = []
    extra_seq = 0

    def push_extra(t: float, kind: str, payload: Tuple) -> None:
        nonlocal extra_seq
        extra_seq += 1
        heapq.heappush(extras, (t, extra_seq, kind, payload))

    triggers: List[Tuple[float, str, Tuple]] = []
    if scn.pattern == "drain":
        # waves roll node by node, spaced wider than one wave's eviction
        # window so at most ~2 waves overlap (a cluster drains serially)
        for k in range(max(topo.nodes, 1)):
            t_wave = 0.8 + 1.3 * k
            if t_wave + 2.2 > scn.duration_s:
                break
            triggers.append((t_wave, "drain", (k,)))
    elif scn.pattern == "herd":
        triggers.append((scn.duration_s * 0.25, "herd_up", ()))
        triggers.append((scn.duration_s * 0.65, "herd_down", ()))
    triggers.sort(key=lambda x: x[0])
    trigger_i = 0
    herd_names: List[str] = []

    def fire_triggers(now: float) -> None:
        nonlocal trigger_i
        while trigger_i < len(triggers) and triggers[trigger_i][0] <= now:
            t_trig, kind, payload = triggers[trigger_i]
            trigger_i += 1
            if kind == "drain":
                # a real drain is PACED (eviction API / PDB throttling, the
                # kubelet's serial pod kills): each wave evicts over ~1.2s
                # and the replacements land ~0.8s behind — violent, but not
                # an apiserver-impossible instantaneous burst
                (k,) = payload
                node = f"n{k}"
                victims = [n for n in alive if n in alive_set and node_of[n] == node]
                for j, name in enumerate(victims):
                    dt = 1.2 * j / max(len(victims), 1)
                    push_extra(t_trig + dt, "delete", (name,))
                    push_extra(
                        t_trig + 0.8 + dt, "recreate",
                        (name, grp_of[name], f"n{k}r"),
                    )
            elif kind == "herd_up":
                # a deployment-sized rollout: the controller manager + the
                # apiserver's write path cap create rates at hundreds/s —
                # the herd lands over ~3s, not in one instant
                for j in range(scn.herd_size):
                    name = f"h{j}"
                    grp = f"g{rng.randrange(topo.groups)}"
                    herd_names.append(name)
                    push_extra(
                        t_trig + 3.0 * j / max(scn.herd_size, 1),
                        "create", (name, grp, f"n{j % max(topo.nodes, 1)}"),
                    )
            elif kind == "herd_down":
                for j, name in enumerate(herd_names):
                    push_extra(
                        t_trig + 3.0 * j / max(len(herd_names), 1),
                        "delete_if_alive", (name,),
                    )

    def drain_extras(upto: float) -> None:
        while extras and extras[0][0] <= upto:
            t_x, _, kind, payload = heapq.heappop(extras)
            fire_triggers(t_x)
            if kind == "delete":
                (name,) = payload
                if name in alive_set:
                    emit_delete(t_x, name)
            elif kind == "delete_if_alive":
                (name,) = payload
                if name in alive_set:
                    emit_delete(t_x, name)
            elif kind == "recreate":
                name, grp, node = payload
                if name not in alive_set:
                    emit_create(t_x, name, grp, node)
            elif kind == "create":
                name, grp, node = payload
                if name not in alive_set:
                    emit_create(t_x, name, grp, node)

    t = 0.0
    n_created = 0
    while True:
        rate = max(arrival_rate(scn.arrival, t, scn.duration_s), 1e-6)
        t_next = t + 1.0 / rate
        fire_triggers(t_next)
        drain_extras(t_next)
        t = t_next
        if t >= scn.duration_s:
            break
        r = rng.random() * w_total
        if r < w_update or not alive_set:
            emit_update(t, pick_alive())
        elif r < w_create:
            n_created += 1
            emit_create(
                t, f"x{n_created}",
                f"g{rng.randrange(topo.groups)}",
                f"n{rng.randrange(max(topo.nodes, 1))}",
            )
        elif r < w_delete:
            if len(alive_set) > topo.pods // 2:
                emit_delete(t, pick_alive())
            else:
                emit_update(t, pick_alive())
        else:
            # spec churn on the open (pod-count) threshold class only:
            # cpu thresholds are the crossing-anchored flip watch's fixed
            # reference, so the generator leaves them alone
            idx = rng.randrange(max(scn.topology.throttles // 3, 1)) * 3
            if idx < scn.topology.throttles:
                emit(
                    t, "update_throttle",
                    name=f"t{idx}", pod_threshold=rng.randrange(5, 80),
                )
            else:
                emit_update(t, pick_alive())
    fire_triggers(scn.duration_s)
    drain_extras(scn.duration_s)

    ops.sort(key=lambda o: (o["t_us"], o["seq"]))
    plan_rules, plan_sha = canonical_fault_plan(scn)
    header = {
        "version": TRACE_VERSION,
        "scenario": scn.name,
        "description": scn.description,
        "seed": seed,
        "duration_s": scn.duration_s,
        "pattern": scn.pattern,
        "herd_size": scn.herd_size,
        "leader_kill": scn.leader_kill,
        "durable": scn.durable,
        "arrival": asdict(scn.arrival),
        "topology": asdict(scn.topology),
        "topology_sha256": _topology_sha(topology),
        "mix": list(list(m) for m in scn.mix),
        "faults": [asdict(f) for f in scn.faults],
        "fault_plan": plan_rules,
        "fault_plan_sha256": plan_sha,
        "slo": asdict(scn.slo),
        "ops": len(ops),
    }
    return header, ops


def serialize_trace(header: Dict, ops: List[Dict]) -> bytes:
    """Canonical byte form: header line then one line per op, sorted keys,
    no whitespace — the determinism smoke compares these bytes."""
    lines = [json.dumps(header, sort_keys=True, separators=(",", ":"))]
    lines.extend(
        json.dumps(op, sort_keys=True, separators=(",", ":")) for op in ops
    )
    return ("\n".join(lines) + "\n").encode()


def trace_sha256(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()
