"""The scenario engine: replay a committed trace through the REAL stack
and judge the SLO gates.

Topology and trace derive deterministically from ``(scenario, seed)``
(scenarios/trace.py); the run then composes the full remote-mode daemon —
mock apiserver over real HTTP → reflectors → adaptive micro-batched
ingest → shared informers → both controllers → device planes → two-lane
async status committer — exactly the production wiring (cli.py remote
mode / bench.py's remote rung), with ONE seeded
:class:`~kube_throttler_tpu.faults.plan.FaultPlan` shared by the server's
fault verbs, the client transport, and the engine's own ``scenario.*``
action sites (apiserver restart with RV-window reset, continue-token
expiry, churn stalls, the injected regression, the leader-kill episode).

Measurements reuse the bench anchors (scenarios/measure.py): flip lag is
crossing-anchored against each label group's running cpu sum, maintained
from the trace's own ``prev_m`` chain so drain waves and herd bursts keep
the sums exact. After the replay the engine QUIESCES (reflectors past the
apiserver's final resourceVersion, ingest drained, workqueues empty,
committer flushed, no new writes) and then runs the zero-wrong-verdicts
sweep: the serving plugin's batch triage against an oracle stack rebuilt
from apiserver truth, plus a seeded per-pod host-oracle spot check that
is independent of every device plane and batch kernel.

Reports (one JSON per run) carry the gate verdicts, the measurements, the
committed trace's sha256 and path, and the fault-plan firing history (the
reproducibility witness).
"""

from __future__ import annotations

import json
import logging
import os
import random
import time
from typing import Dict, List, Optional

from .dsl import Scenario, compile_fault_rules
from .measure import (
    count_watch_of,
    flip_band_mc,
    flip_watch_of,
    group_keys_of,
    lag_tracker,
    served_throttle,
)
from .slo import evaluate_gates, host_spot_check
from .trace import build_topology, build_trace, serialize_trace, trace_sha256

logger = logging.getLogger(__name__)

# gates the in-process stack must close a restart loop within; replay
# pacing sleeps in slices this long so scenario.* sites stay responsive
_TICK_S = 0.02
_SPOT_CHECK_SAMPLE = 200

__all__ = ["run_scenario"]


def _materialize_pod(name: str, grp: str, node: str, cpu_m: int,
                     acl=None, gang=None, gsz: int = 0, pri=None):
    from dataclasses import replace as _replace

    from ..api.pod import make_pod

    pod = make_pod(
        name, labels={"grp": grp}, requests={"cpu": f"{cpu_m}m"},
        accel_class=acl, group=gang, group_size=gsz or None,
        priority=pri,
    )
    pod = _replace(pod, spec=_replace(pod.spec, node_name=node))
    pod.status.phase = "Running"
    return pod


def _pod_fields(spec_or_op: Dict) -> Dict:
    """The gang/accel/priority annotation fields a topology spec or trace
    op may carry (absent on every axis-off trace — committed corpus
    unchanged)."""
    out = {}
    if "acl" in spec_or_op:
        out["acl"] = spec_or_op["acl"]
    if "gang" in spec_or_op:
        out["gang"] = spec_or_op["gang"]
        out["gsz"] = int(spec_or_op.get("gsz", 0))
    if "pri" in spec_or_op:
        out["pri"] = int(spec_or_op["pri"])
    return out


def _accel_entries(topo, base_mc: int):
    """Per-class ``accelClassThresholds`` for a flip-band throttle: class
    c's cpu threshold scaled down by up to ``class_threshold_frac`` — the
    class-resolved admission inequality then genuinely diverges from the
    base one (PR 7's heterogeneity path, searchable by the hunt)."""
    frac = getattr(topo, "class_threshold_frac", 0.0)
    n = getattr(topo, "accel_classes", 0)
    if frac <= 0.0 or n <= 0:
        return ()
    from ..api.types import AccelClassThreshold, ResourceAmount

    return tuple(
        AccelClassThreshold(
            accel_class=f"ac{c}",
            threshold=ResourceAmount.of(
                requests={
                    "cpu": f"{max(int(base_mc * (1.0 - frac * (c + 1) / n)), 100)}m"
                }
            ),
        )
        for c in range(n)
    )


def _band_throttle(name: str, grp: str, sum_mc: int):
    from ..api.types import (
        LabelSelector,
        ResourceAmount,
        Throttle,
        ThrottleSelector,
        ThrottleSelectorTerm,
        ThrottleSpec,
    )

    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": f"{sum_mc}m"}),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels={"grp": grp})),
                )
            ),
        ),
    )


def _seed_remote_store(store, scn: Scenario, topology: Dict) -> None:
    from ..api.pod import Namespace

    store.create_namespace(Namespace("default"))
    topo = scn.topology
    band = flip_band_mc(max(topo.pods - topology["n_hot"], 1), max(topo.groups, 1))
    # flip band anchored at each group's ACTUAL initial cpu sum plus a
    # ~one-step offset: crossings need real drift (no thrash — a threshold
    # at the exact sum flips on nearly every update, and the resulting
    # flip-PUT flood feeds back into ingest as echo load), but the walk
    # still crosses within a few ops of any window opening. Density
    # matches the bench's band (every 24th throttle) so scenario flip
    # traffic stays a measurable sample stream, not a traffic class.
    sums: Dict[str, int] = {}
    for spec in topology["pods"]:
        sums[spec["grp"]] = sums.get(spec["grp"], 0) + spec["cpu_m"]
    _BAND_OFFSET_MC = 300
    from dataclasses import replace as _dreplace

    for i in range(topo.throttles):
        grp = f"g{i % max(topo.groups, 1)}"
        if i % 24 == 1 and sums.get(grp):
            thr = _band_throttle(f"t{i}", grp, sums[grp] + _BAND_OFFSET_MC)
            accel = _accel_entries(topo, sums[grp] + _BAND_OFFSET_MC)
            if accel:
                thr = _dreplace(
                    thr, spec=_dreplace(thr.spec, accel_class_thresholds=accel)
                )
            store.create_throttle(thr)
        else:
            store.create_throttle(served_throttle(i, topo.groups, flip_band_mc=band))
    if topology["n_hot"] > 0:
        # the hot key: ONE throttle matching the whole hot group, its cpu
        # threshold one step off the group's live sum so the dominant
        # (N,K) column flips under churn
        store.create_throttle(
            _band_throttle(
                "thot",
                "hot",
                sums.get("hot", topology["n_hot"] * 400) + _BAND_OFFSET_MC,
            )
        )
    for spec in topology["pods"]:
        store.create_pod(
            _materialize_pod(
                spec["name"], spec["grp"], spec["node"], spec["cpu_m"],
                **_pod_fields(spec),
            )
        )


# fault-schedule compilation is shared with the trace header's canonical
# plan commit: dsl.compile_fault_rules (one implementation, no drift)


def _oracle_store(remote):
    """Fresh store rebuilt from apiserver truth (statuses included)."""
    from ..api.pod import Namespace
    from ..engine.store import Store

    oracle = Store()
    for ns in remote.list_namespaces():
        oracle.create_namespace(Namespace(ns.name))
    ops = [("upsert", "Throttle", t) for t in remote.list_throttles()]
    ops += [("upsert", "ClusterThrottle", t) for t in remote.list_cluster_throttles()]
    ops += [("upsert", "Pod", p) for p in remote.list_pods()]
    for i in range(0, len(ops), 512):
        oracle.apply_events(ops[i : i + 512])
    return oracle


class _Replayer:
    """Walks the committed ops at their virtual times against the remote
    (apiserver) store, maintaining the crossing-anchored flip bookkeeping
    and dispatching the scenario.* action sites."""

    def __init__(self, engine):
        self.e = engine

    def run(self) -> Dict:
        e = self.e
        from dataclasses import replace as _replace

        from ..api.types import ResourceAmount

        remote = e.remote
        plan = e.plan
        pending, pend_lock = e.pending, e.pend_lock
        flip_watch, run_sums, flip_pending = e.flip_watch, e.run_sums, e.flip_pending
        count_watch, run_counts = e.count_watch, e.run_counts
        group_keys = e.group_keys
        n_crossings = 0
        n_applied = 0
        t0 = time.perf_counter()
        e.virtual_now = lambda: time.perf_counter() - t0
        plan.set_time_source(e.virtual_now)
        for op in e.ops:
            target = op["t_us"] / 1e6
            while True:
                self._scenario_sites()
                now_v = e.virtual_now()
                if now_v >= target:
                    break
                time.sleep(min(target - now_v, _TICK_S))
            verb = op["verb"]
            now = time.perf_counter()
            if verb == "update_throttle":
                key = f"default/{op['name']}"
                try:
                    thr = remote.get_throttle("default", op["name"])
                except Exception:
                    continue
                new_thr = _replace(
                    thr,
                    spec=_replace(
                        thr.spec,
                        threshold=ResourceAmount.of(pod=op["pod_threshold"]),
                    ),
                )
                grp = e.thr_grp.get(key)
                with pend_lock:
                    pending.setdefault(key, now)
                    # a spec change IS the crossing event for whatever flip
                    # it causes (calculatedThreshold and/or flags): stamp
                    # it so the sample doesn't fall back to the oldest
                    # refresh anchor (overstating by the whole backlog)
                    flip_pending[key] = now
                    if grp is not None:
                        # the new finite count threshold joins the count
                        # watch so later create/delete crossings stamp
                        entries = count_watch.setdefault(grp, [])
                        entries[:] = [(k, c) for k, c in entries if k != key]
                        entries.append((key, int(op["pod_threshold"])))
                remote.update_throttle_spec(new_thr)
                n_applied += 1
                continue
            grp = op["grp"]
            delta = op["cpu_m"] - op["prev_m"]
            delta_n = {"create_pod": 1, "delete_pod": -1}.get(verb, 0)
            with pend_lock:
                for key in group_keys.get(grp, ()):
                    pending.setdefault(key, now)
                watch = flip_watch.get(grp)
                if watch and delta:
                    s_old = run_sums.get(grp, 0)
                    s_new = s_old + delta
                    run_sums[grp] = s_new
                    for key, thr_mc in watch:
                        if (s_old >= thr_mc) != (s_new >= thr_mc):
                            flip_pending[key] = now  # latest crossing wins
                            n_crossings += 1
                cwatch = count_watch.get(grp)
                if delta_n:
                    c_old = run_counts.get(grp, 0)
                    c_new = c_old + delta_n
                    run_counts[grp] = c_new
                    for key, thr_n in cwatch or ():
                        if (c_old >= thr_n) != (c_new >= thr_n):
                            flip_pending[key] = now
                            n_crossings += 1
            try:
                if verb == "update_pod":
                    remote.update_pod(
                        _materialize_pod(
                            op["name"], grp, op["node"], op["cpu_m"],
                            **_pod_fields(op),
                        )
                    )
                elif verb == "create_pod":
                    remote.create_pod(
                        _materialize_pod(
                            op["name"], grp, op["node"], op["cpu_m"],
                            **_pod_fields(op),
                        )
                    )
                elif verb == "delete_pod":
                    remote.delete_pod("default", op["name"])
                n_applied += 1
            except Exception:
                logger.debug("replay op failed: %r", op, exc_info=True)
        self._scenario_sites()
        t_fired = time.perf_counter() - t0
        return {
            "ops_fired": len(e.ops),
            "ops_applied": n_applied,
            "fire_window_s": t_fired,
            "crossings": n_crossings,
        }

    def _scenario_sites(self) -> None:
        e = self.e
        e.sample_health()
        fault = e.plan.check("scenario.apiserver.restart")
        if fault is not None:
            if fault.mode == "expire_continues":
                n = e.server.expire_continue_tokens()
                logger.info("scenario: expired %d continue tokens", n)
            else:
                logger.info("scenario: restarting mock apiserver (RV reset)")
                e.server.restart(reset_rv_window=True, downtime_s=fault.delay)
                e.note_restart()
        fault = e.plan.check("scenario.churn.stall")
        if fault is not None:
            fault.sleep()


class _Engine:
    # analyzer annotations (PR 10): the crossing-anchored flip bookkeeping
    # is shared between the replayer thread and the remote store's status
    # handler — both sides take pend_lock (measure.lag_tracker hands the
    # dicts and their lock out together). The assignment itself happens in
    # build(), single-threaded construction before any replay thread
    # exists (waived in baseline.txt). restart/resync/caughtup lists are
    # single-writer-per-index: note_restart appends (replayer thread),
    # each poll thread writes only its own index, readers join first.
    GUARDED_BY = {
        "pending": "self.pend_lock",
        "flip_pending": "self.pend_lock",
    }

    def __init__(self, scn: Scenario, seed: int, workdir: str,
                 regression: Optional[str] = None, registry=None):
        self.scn = scn
        self.seed = seed
        self.workdir = workdir
        self.regression = regression
        self.registry = registry
        self.restart_times: List[float] = []
        # per restart: wall time every reflector's resume point passed the
        # post-reset RV floor (the relist completed), or None while pending
        self.resync_times: List[Optional[float]] = []
        # per restart: wall time the post-relist wire backlog fully
        # drained (ingest queue empty) — the outage window's end for flip
        # classification: a crossing queued behind the relist bubble
        # cannot publish sooner, and the RECOVERY gate bounds that bubble
        self.caughtup_times: List[Optional[float]] = []
        self.virtual_now = lambda: 0.0

    def note_restart(self) -> None:
        """Record a restart and watch for the full resync: recovery is
        judged from restart to the first status publication AFTER every
        reflector relisted past the reset RV floor — a PUT that lands
        while the watch path is still down is liveness of the committer,
        not recovery of the loop."""
        import threading

        t_restart = time.perf_counter()
        floor_rv = self.remote.latest_resource_version
        idx = len(self.restart_times)
        self.restart_times.append(t_restart)
        self.resync_times.append(None)
        self.caughtup_times.append(None)

        def poll() -> None:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    if all(
                        int(r.last_resource_version or 0) >= floor_rv
                        for r in self.session.reflectors.values()
                    ):
                        self.resync_times[idx] = time.perf_counter()
                        break
                except ValueError:
                    pass
                time.sleep(0.01)
            if self.resync_times[idx] is None:
                return
            # the relist bubble: events queued behind the storm drain
            # through ingest, and the relist's replace-diff fans EVERY
            # key into the workqueues — caught up means both ran empty
            # twice in a row (a flip queued behind storm-induced
            # reconciles is storm cost, owned by the recovery gate)
            empties = 0
            while time.monotonic() < deadline:
                q = self.session.ingest.qsize() if self.session.ingest else 0
                q += len(self.plugin.throttle_ctr.workqueue)
                q += len(self.plugin.cluster_throttle_ctr.workqueue)
                empties = empties + 1 if q == 0 else 0
                if empties >= 2:
                    self.caughtup_times[idx] = time.perf_counter()
                    return
                time.sleep(0.05)

        # a dead poller leaves resync_times[idx] None, which the recovery
        # gate reports as an unrecovered restart — the death is observable
        # by construction, so no extra routing is needed
        threading.Thread(  #: thread: fire-and-forget
            target=poll, daemon=True, name=f"resync-poll-{idx}"
        ).start()

    # -- stack construction -------------------------------------------------

    def build(self) -> None:
        import sys

        # the whole topology — apiserver, replayer, daemon — shares one
        # interpreter: GIL hand-off latency (default 5ms switch interval ×
        # several CPU-bound threads) stacks across the 4-thread wire-in
        # pipeline. 1ms measurably cuts delivery lag (87→63ms p50 at the
        # 950/s saturation probe) at negligible throughput cost.
        self._prev_switch_interval = sys.getswitchinterval()
        sys.setswitchinterval(0.001)
        from ..client.mockserver import MockApiServer
        from ..client.transport import RemoteSession, RestConfig
        from ..engine.store import Store
        from ..faults.plan import FaultPlan
        from ..metrics import Registry
        from ..plugin import KubeThrottler, decode_plugin_args

        self.header, self.ops = build_trace(self.scn, self.seed)
        self.topology = build_topology(self.scn, self.seed)
        blob = serialize_trace(self.header, self.ops)
        self.trace_sha = trace_sha256(blob)
        self.trace_path = os.path.join(
            self.workdir, f"trace-{self.scn.name}-s{self.seed}.jsonl"
        )
        os.makedirs(self.workdir, exist_ok=True)
        with open(self.trace_path, "wb") as f:
            f.write(blob)

        self.plan = FaultPlan(seed=self.seed)
        compile_fault_rules(self.plan, self.scn)
        if self.regression:
            # the deliberately-broken SLO: route the regression site into a
            # per-status-PUT stall — flip publication pays it wholesale
            self.plan.rule(
                "scenario.regression.flip_stall", mode="delay", delay=0.3, times=1
            )

        server = MockApiServer(bookmark_interval=0.25)
        self.server = server
        self.remote = server.store
        _seed_remote_store(self.remote, self.scn, self.topology)
        server.faults = self.plan
        server.start()

        self.local = Store()
        self.journal = self.snapshotter = None
        if self.scn.durable:
            # the long-horizon durability hook: journal + size-triggered
            # snapshots + compaction cycles run UNDER the replayed storm
            # (journal attach must precede every other store handler so
            # nothing double-dispatches). Trigger cadence scales with the
            # trace so a multi-virtual-day run cuts several snapshots and
            # at least one compaction.
            from ..engine.journal import attach as journal_attach
            from ..engine.snapshot import SnapshotManager

            data_dir = os.path.join(
                self.workdir, f"data-{self.scn.name}-s{self.seed}"
            )
            os.makedirs(data_dir, exist_ok=True)
            every = max(len(self.ops) // 4, 500)
            self.journal = journal_attach(
                self.local,
                os.path.join(data_dir, "journal.log"),
                compact_after=every * 3,
                faults=self.plan,
            )
            self.snapshotter = SnapshotManager(
                data_dir, self.local, faults=self.plan
            )
            self.snapshotter.bind_journal(self.journal, every_lines=every)
        self.metrics_registry = self.registry if self.registry is not None else Registry()
        self.session = RemoteSession(
            RestConfig(server=server.url),
            self.local,
            metrics_registry=self.metrics_registry,
            qps=None,
            faults=self.plan,
            ingest_batch="adaptive",
        )
        self.session.start(sync_timeout=60)
        self.plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            self.local,
            use_device=True,
            start_workers=True,
            status_writer=self.session.status_committer,
            metrics_registry=self.metrics_registry,
        )
        # initial statuses converge before measurement (every group has
        # pods, so every throttle ends with a materialized used count)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            thrs = self.remote.list_throttles()
            if thrs and all(
                t.status.used.resource_counts is not None for t in thrs
            ):
                break
            time.sleep(0.2)
        import gc

        from ..utils.gchygiene import freeze_startup_heap

        # same pre-serving posture as the daemon; teardown restores it so
        # an embedding process (the test suite) doesn't inherit a frozen
        # heap + deferred gen2 for its remaining lifetime
        self._prev_gc_threshold = gc.get_threshold()
        freeze_startup_heap()

        # measurement anchors: the lag tracker watches the REMOTE store's
        # Throttle MODIFIEDs (status PUTs arriving back at the apiserver)
        (
            self.pending, self.flip_pending, self.pend_lock,
            self.lags, self.flip_lags, self.flip_walls, self._on_remote_status,
        ) = lag_tracker()
        self.group_keys = group_keys_of(self.remote)
        self.flip_watch, self.run_sums = flip_watch_of(self.remote)
        self.count_watch, self.run_counts = count_watch_of(self.remote)
        # throttle key → its selector's group (spec churn rewrites the
        # count watch in place, keyed by this)
        self.thr_grp = {
            t.key: t.spec.selector.selector_terms[0].pod_selector.match_labels["grp"]
            for t in self.remote.list_throttles()
        }
        self._status_write_walls: List[float] = []

        def on_status(event):
            self._status_write_walls.append(time.perf_counter())
            self._on_remote_status(event)

        self._status_handler = on_status
        self.remote.add_event_handler("Throttle", on_status, replay=False)

        if self.regression:
            fault = self.plan.check("scenario.regression.flip_stall")
            if fault is not None:
                self.plan.rule("mock.status.delay", mode="delay", delay=fault.delay)

        # fingerprint anchors (the hunt's coverage signal): reflectors join
        # the plugin's /readyz component registry, the transition log and
        # the metric-family baseline reset AFTER convergence so everything
        # recorded from here on is run behavior, not startup noise
        self.session.register_health(self.plugin.health)
        if self.journal is not None:
            self.plugin.health.register("journal", self.journal.health_state)
        if self.snapshotter is not None:
            self.snapshotter.device_manager = self.plugin.device_manager
            self.plugin.health.register("snapshot", self.snapshotter.health_state)
        self._health_sample_every_s = 0.05
        self._last_health_sample = 0.0
        self.plugin.health.reset_transitions()
        self.sample_health(force=True)
        self._metric_baseline = self.metrics_registry.family_totals()

    def sample_health(self, force: bool = False) -> None:
        """Probe every /readyz component at most every 50 ms (replayer
        tick + quiesce loop) so Health's transition log approximates a
        continuous timeline of the run."""
        now = time.perf_counter()
        if not force and now - self._last_health_sample < self._health_sample_every_s:
            return
        self._last_health_sample = now
        try:
            self.plugin.health.snapshot()
        except Exception:
            logger.debug("health sample failed", exc_info=True)

    def fingerprint(self) -> Dict:
        """The structured, machine-readable run fingerprint: fired fault
        sites with hit counts, health-component state transitions, and
        metric-family deltas vs the post-convergence baseline. This is the
        hunt's coverage signal (scenarios/hunt/coverage.py) and the raw
        material for diff_reports — consumers read THIS, not report
        prose."""
        end = self.metrics_registry.family_totals()
        base = getattr(self, "_metric_baseline", {})
        families: Dict[str, Dict] = {}
        for name, (series, total) in sorted(end.items()):
            before = base.get(name)
            if before is None or before != (series, total):
                families[name] = {
                    "series": series,
                    "delta": round(total - (before[1] if before else 0.0), 6),
                }
        return {
            "fault_sites": {
                site: len(firings) for site, firings in self.plan.snapshot().items()
            },
            "health_transitions": [
                list(t) for t in self.plugin.health.transitions()
            ],
            "metric_families": families,
        }

    # -- quiesce + oracles --------------------------------------------------

    def quiesce(self, timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.sample_health()
            if self.session.ingest is not None:
                self.session.ingest.flush(timeout=5.0)
            target_rv = self.remote.latest_resource_version
            refl_ok = all(
                int(r.last_resource_version or 0) >= target_rv
                for r in self.session.reflectors.values()
            )
            wq_empty = (
                len(self.plugin.throttle_ctr.workqueue) == 0
                and len(self.plugin.cluster_throttle_ctr.workqueue) == 0
            )
            if refl_ok and wq_empty:
                self.session.status_committer.flush(timeout=5.0)
                if (
                    self.remote.latest_resource_version == target_rv
                    and len(self.plugin.throttle_ctr.workqueue) == 0
                    and len(self.plugin.cluster_throttle_ctr.workqueue) == 0
                ):
                    return True
            time.sleep(0.05)
        return False

    def verdict_sweep(self) -> Dict:
        serving = self.plugin.pre_filter_batch()
        sv = serving["schedulable"]
        oracle = _oracle_store(self.remote)
        oracle_plugin = None
        try:
            from ..plugin import KubeThrottler, decode_plugin_args

            oracle_plugin = KubeThrottler(
                decode_plugin_args(
                    {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
                ),
                oracle,
                use_device=True,
                start_workers=False,
            )
            ov = oracle_plugin.pre_filter_batch()["schedulable"]
            wrong = [k for k in ov if bool(sv.get(k)) is not bool(ov[k])]
            wrong += [k for k in sv if k not in ov]
            # seeded per-pod host-oracle spot check: independent of device
            # planes AND of pre_filter_batch on either side
            rng = random.Random(f"{self.scn.name}/{self.seed}/spot")
            pods = sorted(oracle.list_pods(), key=lambda p: p.key)
            sample = (
                pods
                if len(pods) <= _SPOT_CHECK_SAMPLE
                else [pods[rng.randrange(len(pods))] for _ in range(_SPOT_CHECK_SAMPLE)]
            )
            spot_wrong = host_spot_check(sv, oracle, sample)
            wrong = sorted(set(wrong) | set(spot_wrong))
            return {
                "wrong_verdicts": len(wrong),
                "wrong_examples": wrong[:10],
                "verdicts_checked": len(ov),
                "spot_checked": len(sample),
            }
        finally:
            if oracle_plugin is not None:
                oracle_plugin.stop()

    def leader_kill_episode(self) -> Optional[Dict]:
        fault = self.plan.check("scenario.leader.kill")
        if fault is None:
            return None
        import importlib.util

        root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        hatest_path = os.path.join(root, "tools", "hatest.py")
        if not os.path.exists(hatest_path):  # installed without the tools/ tree
            return {"skipped": "tools/hatest.py not present"}
        import sys

        if root not in sys.path:
            sys.path.insert(0, root)
        spec = importlib.util.spec_from_file_location("kt_scenario_hatest", hatest_path)
        hatest = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(hatest)
        ha_dir = os.path.join(self.workdir, f"ha-{self.scn.name}-s{self.seed}")
        os.makedirs(ha_dir, exist_ok=True)
        window = self.scn.slo.failover_window_s or 10.0
        try:
            report = hatest.run_ha_cycle(
                "ha.status.commit", self.seed, ha_dir, events=60, window_s=window
            )
            return {"window_s": report["window_s"], "epoch": report["epoch"]}
        except AssertionError as e:
            return {"failed": str(e)}

    def teardown(self) -> None:
        import gc
        import sys

        try:
            sys.setswitchinterval(self._prev_switch_interval)
        except Exception:
            pass
        try:
            if getattr(self, "_prev_gc_threshold", None) is not None:
                gc.set_threshold(*self._prev_gc_threshold)
                gc.unfreeze()
        except Exception:
            pass
        for step in (
            lambda: self.remote.remove_event_handler("Throttle", self._status_handler),
            lambda: self.plugin.stop(),
            lambda: self.session.stop(),
            lambda: self.server.stop(),
            lambda: self.journal.close() if self.journal is not None else None,
        ):
            try:
                step()
            except Exception:
                logger.debug("scenario teardown step failed", exc_info=True)


def _nominal_ops(scn: Scenario, n_ops: int) -> float:
    """Trace's nominal average rate: its own op count over its duration —
    the pace the replayer is judged against."""
    return n_ops / max(scn.duration_s, 1e-9)


def run_scenario(
    scn: Scenario,
    seed: int,
    workdir: str,
    regression: Optional[str] = None,
    registry=None,
    keep_stack: bool = False,
) -> Dict:
    """One full build → replay → quiesce → oracle → gates cycle. Returns
    the report dict (also written to ``<workdir>/report-<name>-s<seed>.json``)."""
    import numpy as np

    eng = _Engine(scn, seed, workdir, regression=regression, registry=registry)
    eng.build()
    try:
        replay = _Replayer(eng).run()
        converged = eng.quiesce()
        time.sleep(0.2)
        # let the resync pollers record the caught-up instants the quiesce
        # flush just made observable
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and any(
            s is not None and c is None
            for s, c in zip(eng.resync_times, eng.caughtup_times)
        ):
            time.sleep(0.05)

        lag_arr = np.asarray(eng.lags) if eng.lags else np.asarray([0.0])
        # partition flip samples: a sample whose [anchor, publication]
        # interval overlaps an apiserver outage window (restart → every
        # reflector resynced past the reset RV floor) could not have
        # published sooner no matter how healthy the pipeline — the
        # RECOVERY gate bounds that window; the flip gate judges steady
        # state. With no restarts every sample is steady.
        outages = []
        for t_r, t_s, t_c in zip(
            eng.restart_times, eng.resync_times, eng.caughtup_times
        ):
            end = t_c if t_c is not None else t_s
            outages.append((t_r, end if end is not None else float("inf")))

        def outage_affected(pub_wall: float, lag: float) -> bool:
            anchor = pub_wall - lag
            return any(anchor < end and pub_wall > start for start, end in outages)

        steady_flips: List[float] = []
        outage_flips: List[float] = []
        for lag, wall in zip(eng.flip_lags, eng.flip_walls):
            (outage_flips if outage_affected(wall, lag) else steady_flips).append(lag)
        flip_arr = np.asarray(steady_flips) if steady_flips else np.asarray([0.0])
        measurements: Dict = {
            "ops_fired": replay["ops_fired"],
            "ops_applied": replay["ops_applied"],
            "fire_window_s": round(replay["fire_window_s"], 3),
            "events_per_sec": replay["ops_applied"] / max(replay["fire_window_s"], 1e-9),
            "pace_frac": (
                (replay["ops_fired"] / max(replay["fire_window_s"], 1e-9))
                / max(_nominal_ops(scn, replay["ops_fired"]), 1e-9)
            ),
            "applied_frac": replay["ops_applied"] / max(replay["ops_fired"], 1),
            "converged": converged,
            "lag_p50_ms": float(np.percentile(lag_arr, 50)) * 1e3,
            "lag_p99_ms": float(np.percentile(lag_arr, 99)) * 1e3,
            "status_writes": len(eng.lags),
            "flip_lag_p50_ms": float(np.percentile(flip_arr, 50)) * 1e3,
            "flip_lag_p99_ms": float(np.percentile(flip_arr, 99)) * 1e3,
            "flip_samples": len(steady_flips),
            "flip_outage_samples": len(outage_flips),
            "flip_outage_max_ms": (
                max(outage_flips) * 1e3 if outage_flips else 0.0
            ),
            "flip_crossings": replay["crossings"],
            "restarts": len(eng.restart_times),
        }
        if eng.session.ingest is not None:
            st = eng.session.ingest.stats()
            measurements["ingest_dropped"] = st["dropped"]
            measurements["ingest_batches"] = st["batches"]
            measurements["ingest_max_batch"] = st["max_batch_seen"]
        commit_counter = eng.metrics_registry.counter_vec(
            "kube_throttler_remote_status_commit_total", "", ["kind", "result"]
        )
        measurements["commit_counts"] = {
            f"{k}:{r}": int(v) for (k, r), v in commit_counter.collect().items()
        }
        if eng.restart_times:
            # recovery covers the WHOLE bubble: reflectors resynced past
            # the reset RV floor, the wire backlog digested, and — when
            # anything was left to publish — the first post-resync status
            # write. A pipeline whose backlog fully published BEFORE the
            # resync finished is healthy-idle, not unrecovered.
            recoveries = []
            for t_r, t_sync, t_caught in zip(
                eng.restart_times, eng.resync_times, eng.caughtup_times
            ):
                if t_sync is None:
                    recoveries.append(None)  # reflectors never resynced
                    continue
                rec = (t_caught if t_caught is not None else t_sync) - t_r
                post = [w for w in eng._status_write_walls if w > t_sync]
                if post:
                    rec = max(rec, post[0] - t_r)
                recoveries.append(rec)
            worst = None
            if all(r is not None for r in recoveries):
                worst = max(recoveries)
            measurements["recovery_s"] = worst
        measurements.update(eng.verdict_sweep())
        ha = eng.leader_kill_episode()
        if ha is not None:
            measurements["leader_kill"] = ha
            measurements["failover_window_s"] = ha.get("window_s")

        gates = evaluate_gates(scn, measurements)
        eng.sample_health(force=True)  # final probe before the fingerprint
        report = {
            "scenario": scn.name,
            "seed": seed,
            "regression": regression,
            "trace_path": eng.trace_path,
            "trace_sha256": eng.trace_sha,
            "fault_plan_sha256": eng.header.get("fault_plan_sha256"),
            "all_pass": all(g["pass"] for g in gates.values()),
            "gates": gates,
            "measurements": measurements,
            "fault_history": eng.plan.snapshot(),
            "fingerprint": eng.fingerprint(),
        }
        _record_metrics(eng.metrics_registry, scn, report)
        path = os.path.join(workdir, f"report-{scn.name}-s{seed}.json")
        with open(path, "w") as f:
            json.dump(report, f, indent=2, default=str)
        report["report_path"] = path
        return report
    finally:
        if not keep_stack:
            eng.teardown()


def _record_metrics(registry, scn: Scenario, report: Dict) -> None:
    """Export the run's outcome as kube_throttler_scenario_* families on
    the stack's registry (METRIC_NAMES — the same names a long-running
    scenario soak would alert on)."""
    from ..metrics import register_scenario_metrics

    fams = register_scenario_metrics(registry)
    m = report["measurements"]
    fams["ops"].inc({"scenario": scn.name}, float(m["ops_applied"]))
    for site, firings in report["fault_history"].items():
        fams["faults"].inc({"scenario": scn.name, "site": site}, float(len(firings)))
    for gate, g in report["gates"].items():
        fams["gate"].set({"scenario": scn.name, "gate": gate}, 1.0 if g["pass"] else 0.0)
    if m.get("flip_samples", 0) > 0:
        fams["flip_p99"].set(
            {"scenario": scn.name}, m["flip_lag_p99_ms"] / 1e3
        )
    if m.get("recovery_s") is not None:
        fams["recovery"].set({"scenario": scn.name}, float(m["recovery_s"]))
