"""Sharded composed bad-day scenario: the PR 8 corpus' worst trace
replayed against the PR 9 multiprocess stack.

The single-process composed harness saturates near ~1k ev/s on one core
(docs/PERFORMANCE.md "What bounds each path"): the wire-in FIFO leg —
not the engine — is the knee, and ROADMAP item 1 names multiprocess
sharding as what raises it. This runner replays the deterministic
``bad_day`` trace (same ``build_trace`` bytes as the corpus) through
the scatter-gather front at a pace ABOVE that knee, SIGKILLs one shard
worker mid-replay (the kill-the-leader episode recast at the shard
layer: each worker runs its own fenced leadership), and gates:

- **knee lift**: the front sustains the target pace (default 1.4k ev/s,
  ~1.4× the composed single-process knee) within ``min_pace_frac``;
- **zero wrong verdicts**: after convergence, every pod's sharded
  ``pre_filter`` equals a single-process oracle rebuilt from the final
  state (code + normalized reasons);
- **bounded recovery**: the killed shard rejoins (restart + resync)
  within ``recovery_s``;
- **flip p99**: crossing-anchored flip publication (scenarios/measure.py
  anchors, measured on the FRONT store — routing + IPC + shard
  reconcile + status push included) within the bad-day bound, outage
  window excluded (the recovery gate bounds that instead).

Run: ``python -m kube_throttler_tpu.scenarios.sharded --shards 4``
(wired into ``make scenario-test``).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["run_sharded_bad_day"]

_OUTAGE_PAD_S = 0.25


def _build_stack(n_shards: int):
    from ..sharding.front import AdmissionFront
    from ..sharding.supervisor import ShardSupervisor

    front = AdmissionFront(n_shards)
    supervisor = ShardSupervisor(
        front,
        # device ON like the composed corpus daemon: the two-lane flip
        # path (batch flip-candidate detection → priority-lane promotion)
        # lives on the device mirror — without it flips ride the normal
        # refresh drains and the flip gate measures backlog, not the lane
        use_device=True,
        restart_backoff=0.3,
        env={**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"},
    )
    supervisor.start(ready_timeout=300.0)
    return front, supervisor


KNEE_LIFT_PACE_HZ = 1400.0  # > the ~1k ev/s composed single-process knee
UNDERSUBSCRIBED_PACE_HZ = 700.0  # 1-core fallback: protocol, not knee proof


def run_sharded_bad_day(
    n_shards: int = 4,
    seed: int = 0,
    pace_hz: Optional[float] = None,
    min_pace_frac: float = 0.75,
    recovery_s: float = 20.0,
    flip_p99_ms: float = 250.0,
    kill_at_frac: float = 0.45,
    scenario_name: str = "bad_day",
) -> Dict:
    from .corpus import get_scenario
    from .engine import _materialize_pod, _seed_remote_store
    from .measure import (
        count_watch_of,
        flip_watch_of,
        group_keys_of,
        lag_tracker,
    )
    from .trace import build_topology, build_trace, serialize_trace, trace_sha256

    host_cores = len(os.sched_getaffinity(0))
    undersubscribed = host_cores < n_shards + 1
    if pace_hz is None or pace_hz <= 0:
        # the knee-lift gate (1400 > the ~1k composed knee) presumes one
        # core per worker + the front; an undersubscribed host runs the
        # same trace at a pace its timesharing can sustain — the gates
        # still exercise the full protocol, they just don't prove the
        # knee lift (host_cores in the report says which run this was)
        pace_hz = UNDERSUBSCRIBED_PACE_HZ if undersubscribed else KNEE_LIFT_PACE_HZ
    scn = get_scenario(scenario_name)
    topology = build_topology(scn, seed)
    header, ops = build_trace(scn, seed)
    trace_sha = trace_sha256(serialize_trace(header, ops))
    front, supervisor = _build_stack(n_shards)
    report: Dict = {
        "scenario": f"sharded_{scenario_name}",
        "shards": n_shards,
        "seed": seed,
        "trace_sha256": trace_sha,
        "pace_hz": pace_hz,
        "host_cores": host_cores,
        "undersubscribed": undersubscribed,
        "knee_lift_proven": (not undersubscribed) and pace_hz >= KNEE_LIFT_PACE_HZ,
        "gates": {},
    }
    try:
        _seed_remote_store(front.store, scn, topology)
        front.drain(timeout=300.0)
        time.sleep(0.5)

        # crossing-anchored flip measurement on the front store (the same
        # anchors bench + the corpus use — scenarios/measure.py)
        pending, flip_pending, pend_lock, _lags, flip_lags, flip_walls, on_write = (
            lag_tracker()
        )
        group_keys = group_keys_of(front.store)
        flip_watch, run_sums = flip_watch_of(front.store)
        count_watch, run_counts = count_watch_of(front.store)
        front.store.add_event_handler("Throttle", on_write, replay=False)

        from ..engine.ingest import MicroBatchIngest

        pipeline = MicroBatchIngest(front.store, batch_policy="adaptive")
        kill_idx = int(len(ops) * kill_at_frac)
        killed_sid: Optional[int] = None
        outage: List[float] = []  # [t_kill, t_recovered]
        n_applied_target = 0
        t0 = time.perf_counter()
        for i, op in enumerate(ops):
            # trace order at OUR pace (the knee-lift gate's whole point:
            # the composed trace replayed faster than one core can)
            next_at = t0 + i / pace_hz
            delay = next_at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            if i == kill_idx and supervisor.procs:
                killed_sid = 0 if n_shards == 1 else 1
                proc = supervisor.procs.get(killed_sid)
                if proc is not None and proc.poll() is None:
                    os.kill(proc.pid, signal.SIGKILL)
                    outage.append(time.perf_counter())
            verb = op["verb"]
            now = time.perf_counter()
            grp = op.get("grp")
            with pend_lock:
                for key in group_keys.get(grp, ()):
                    pending.setdefault(key, now)
                if verb in ("update_pod", "create_pod", "delete_pod"):
                    watch = flip_watch.get(grp)
                    if watch:
                        s_old = run_sums.get(grp, 0)
                        s_new = s_old + op["cpu_m"] - op["prev_m"]
                        run_sums[grp] = s_new
                        for key, thr_mc in watch:
                            if (s_old >= thr_mc) != (s_new >= thr_mc):
                                flip_pending[key] = now
                    cwatch = count_watch.get(grp)
                    if cwatch and verb != "update_pod":
                        c_old = run_counts.get(grp, 0)
                        c_new = c_old + (1 if verb == "create_pod" else -1)
                        run_counts[grp] = c_new
                        for key, thr_n in cwatch:
                            if (c_old >= thr_n) != (c_new >= thr_n):
                                flip_pending[key] = now
            if verb == "update_pod" or verb == "create_pod":
                pod = _materialize_pod(
                    op["name"], op["grp"], op.get("node", "n0"), op["cpu_m"]
                )
                pipeline.submit("upsert", "Pod", pod)
                n_applied_target += 1
            elif verb == "delete_pod":
                pipeline.submit("delete", "Pod", f"default/{op['name']}")
                n_applied_target += 1
            elif verb == "update_throttle":
                # the composed trace's spec churn (pod-count class only);
                # routed like any other spec change
                try:
                    thr = front.store.get_throttle("default", op["name"])
                except Exception:  # noqa: BLE001
                    continue
                from dataclasses import replace as _replace

                from ..api.types import ResourceAmount

                front.store.update_throttle_spec(
                    _replace(
                        thr,
                        spec=_replace(
                            thr.spec,
                            threshold=ResourceAmount.of(
                                pod=op.get("pod_threshold", 10)
                            ),
                        ),
                    )
                )
        t_fired = time.perf_counter() - t0
        pipeline.flush(timeout=120.0)
        front.drain(timeout=300.0)
        # the sustain clock stops HERE: fire window + ingest drain. The
        # recovery wait and the settle sleeps below are gate bookkeeping,
        # not ingest.
        t_sustain = time.perf_counter() - t0
        # recovery: the killed shard must be back and clean
        rec_deadline = time.monotonic() + recovery_s
        recovered = False
        while time.monotonic() < rec_deadline:
            state, _ = front._shards_health()
            if state == "ok":
                recovered = True
                break
            time.sleep(0.1)
        if outage:
            outage.append(time.perf_counter())
        front.drain(timeout=300.0)
        time.sleep(1.5)
        pipe_stats = pipeline.stats()
        front.store.remove_event_handler("Throttle", on_write)
        pipeline.stop()

        sustained = pipe_stats["events_applied"] / t_sustain
        report["events"] = pipe_stats["events_applied"]
        report["fired_hz"] = round(len(ops) / t_fired, 1)
        report["sustained_hz"] = round(sustained, 1)
        report["dropped"] = pipe_stats["dropped"]
        report["gates"]["pace"] = {
            "pass": sustained >= pace_hz * min_pace_frac and pipe_stats["dropped"] == 0,
            "sustained_hz": round(sustained, 1),
            "target_hz": pace_hz,
            "min_frac": min_pace_frac,
        }
        report["gates"]["recovery"] = {
            "pass": recovered,
            "bound_s": recovery_s,
            "restarts": dict(supervisor.restarts),
            "killed_shard": killed_sid,
        }

        # flip p99, outage-excluded: a crossing STAMPED while its shard
        # was dark cannot publish before the restart+resync closes the
        # loop — those flips are the recovery gate's jurisdiction
        # (partition by anchor time = publication wall − lag, the same
        # restart-outage posture the composed engine takes)
        if outage and len(outage) == 2:
            lo, hi = outage[0] - _OUTAGE_PAD_S, outage[1] + _OUTAGE_PAD_S
            samples = [
                lag for lag, wall in zip(flip_lags, flip_walls)
                if not (lo <= (wall - lag) <= hi)
            ]
        else:
            samples = list(flip_lags)
        if samples:
            p50 = float(np.percentile(np.asarray(samples), 50)) * 1e3
            p99 = float(np.percentile(np.asarray(samples), 99)) * 1e3
        else:
            p50 = p99 = 0.0
        report["gates"]["flip_p99"] = {
            "pass": p99 <= flip_p99_ms,
            "p50_ms": round(p50, 1),
            "p99_ms": round(p99, 1),
            "bound_ms": flip_p99_ms,
            "samples": len(samples),
            "outage_excluded": max(0, len(flip_lags) - len(samples)),
        }

        # zero wrong verdicts vs the rebuilt oracle (tools/harness.py)
        import tools.harness as H
        from ..api.pod import Namespace
        from ..engine.store import Store

        oracle_store = Store()
        oracle_store.create_namespace(Namespace("default"))
        for thr in front.store.list_throttles():
            oracle_store.create_throttle(thr)
        for pod in front.store.list_pods():
            oracle_store.create_pod(pod)
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        wrong = []
        for pod in oracle_store.list_pods():
            got = front.pre_filter(pod)
            want = oracle.pre_filter(pod)
            if got.code != want.code or H.normalized_reasons(
                got.reasons
            ) != H.normalized_reasons(want.reasons):
                wrong.append(pod.key)
        report["gates"]["verdicts"] = {
            "pass": not wrong,
            "wrong": len(wrong),
            "checked": len(oracle_store.list_pods()),
            "examples": wrong[:5],
        }
        report["pass"] = all(g["pass"] for g in report["gates"].values())
        return report
    finally:
        supervisor.stop()
        front.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="scenarios.sharded")
    parser.add_argument("--shards", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--pace", type=float, default=0.0,
        help="replay pace in ev/s; 0 = auto (1400 knee-lift gate on a "
        ">=shards+1 core host, 700 protocol-check pace otherwise)",
    )
    parser.add_argument("--scenario", default="bad_day")
    parser.add_argument("--json", default="", help="write the report here too")
    args = parser.parse_args(argv)
    report = run_sharded_bad_day(
        n_shards=args.shards, seed=args.seed, pace_hz=args.pace,
        scenario_name=args.scenario,
    )
    print(json.dumps(report, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
