"""Sharded composed bad-day scenario: the PR 8 corpus' worst trace
replayed against the PR 9 multiprocess stack.

The single-process composed harness saturates near ~1k ev/s on one core
(docs/PERFORMANCE.md "What bounds each path"): the wire-in FIFO leg —
not the engine — is the knee, and ROADMAP item 1 names multiprocess
sharding as what raises it. This runner replays the deterministic
``bad_day`` trace (same ``build_trace`` bytes as the corpus) through
the scatter-gather front at a pace ABOVE that knee, SIGKILLs one shard
worker mid-replay (the kill-the-leader episode recast at the shard
layer: each worker runs its own fenced leadership), and gates:

- **knee lift**: the front sustains the target pace (default 1.4k ev/s,
  ~1.4× the composed single-process knee) within ``min_pace_frac``;
- **zero wrong verdicts**: after convergence, every pod's sharded
  ``pre_filter`` equals a single-process oracle rebuilt from the final
  state (code + normalized reasons);
- **bounded recovery**: the killed shard rejoins (restart + resync)
  within ``recovery_s``;
- **flip p99**: crossing-anchored flip publication (scenarios/measure.py
  anchors, measured on the FRONT store — routing + IPC + shard
  reconcile + status push included) within the bad-day bound, outage
  window excluded (the recovery gate bounds that instead).

Run: ``python -m kube_throttler_tpu.scenarios.sharded --shards 4``
(wired into ``make scenario-test``).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["run_sharded_bad_day", "run_sharded_program", "SHARD_TIER_PREFIXES"]

_OUTAGE_PAD_S = 0.25


def _build_stack(n_shards: int):
    from ..sharding.front import AdmissionFront
    from ..sharding.supervisor import ShardSupervisor

    front = AdmissionFront(n_shards)
    supervisor = ShardSupervisor(
        front,
        # device ON like the composed corpus daemon: the two-lane flip
        # path (batch flip-candidate detection → priority-lane promotion)
        # lives on the device mirror — without it flips ride the normal
        # refresh drains and the flip gate measures backlog, not the lane
        use_device=True,
        restart_backoff=0.3,
        env={**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"},
    )
    supervisor.start(ready_timeout=300.0)
    return front, supervisor


KNEE_LIFT_PACE_HZ = 1400.0  # > the ~1k ev/s composed single-process knee
UNDERSUBSCRIBED_PACE_HZ = 700.0  # 1-core fallback: protocol, not knee proof


def run_sharded_bad_day(
    n_shards: int = 4,
    seed: int = 0,
    pace_hz: Optional[float] = None,
    min_pace_frac: float = 0.75,
    recovery_s: float = 20.0,
    flip_p99_ms: float = 250.0,
    kill_at_frac: float = 0.45,
    scenario_name: str = "bad_day",
) -> Dict:
    from .corpus import get_scenario
    from .engine import _materialize_pod, _pod_fields, _seed_remote_store
    from .measure import (
        count_watch_of,
        flip_watch_of,
        group_keys_of,
        lag_tracker,
    )
    from .trace import build_topology, build_trace, serialize_trace, trace_sha256

    host_cores = len(os.sched_getaffinity(0))
    undersubscribed = host_cores < n_shards + 1
    if pace_hz is None or pace_hz <= 0:
        # the knee-lift gate (1400 > the ~1k composed knee) presumes one
        # core per worker + the front; an undersubscribed host runs the
        # same trace at a pace its timesharing can sustain — the gates
        # still exercise the full protocol, they just don't prove the
        # knee lift (host_cores in the report says which run this was)
        pace_hz = UNDERSUBSCRIBED_PACE_HZ if undersubscribed else KNEE_LIFT_PACE_HZ
    scn = get_scenario(scenario_name)
    topology = build_topology(scn, seed)
    header, ops = build_trace(scn, seed)
    trace_sha = trace_sha256(serialize_trace(header, ops))
    front, supervisor = _build_stack(n_shards)
    report: Dict = {
        "scenario": f"sharded_{scenario_name}",
        "shards": n_shards,
        "seed": seed,
        "trace_sha256": trace_sha,
        "pace_hz": pace_hz,
        "host_cores": host_cores,
        "undersubscribed": undersubscribed,
        "knee_lift_proven": (not undersubscribed) and pace_hz >= KNEE_LIFT_PACE_HZ,
        "gates": {},
    }
    try:
        _seed_remote_store(front.store, scn, topology)
        front.drain(timeout=300.0)
        time.sleep(0.5)

        # crossing-anchored flip measurement on the front store (the same
        # anchors bench + the corpus use — scenarios/measure.py)
        pending, flip_pending, pend_lock, _lags, flip_lags, flip_walls, on_write = (
            lag_tracker()
        )
        group_keys = group_keys_of(front.store)
        flip_watch, run_sums = flip_watch_of(front.store)
        count_watch, run_counts = count_watch_of(front.store)
        front.store.add_event_handler("Throttle", on_write, replay=False)

        from ..engine.ingest import MicroBatchIngest

        pipeline = MicroBatchIngest(front.store, batch_policy="adaptive")
        kill_idx = int(len(ops) * kill_at_frac)
        killed_sid: Optional[int] = None
        outage: List[float] = []  # [t_kill, t_recovered]
        n_applied_target = 0
        t0 = time.perf_counter()
        for i, op in enumerate(ops):
            # trace order at OUR pace (the knee-lift gate's whole point:
            # the composed trace replayed faster than one core can)
            next_at = t0 + i / pace_hz
            delay = next_at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            if i == kill_idx:
                killed_sid = 0 if n_shards == 1 else 1
                proc = supervisor.shard_proc(killed_sid)
                if proc is not None and proc.poll() is None:
                    os.kill(proc.pid, signal.SIGKILL)
                    outage.append(time.perf_counter())
            verb = op["verb"]
            now = time.perf_counter()
            grp = op.get("grp")
            with pend_lock:
                for key in group_keys.get(grp, ()):
                    pending.setdefault(key, now)
                if verb in ("update_pod", "create_pod", "delete_pod"):
                    watch = flip_watch.get(grp)
                    if watch:
                        s_old = run_sums.get(grp, 0)
                        s_new = s_old + op["cpu_m"] - op["prev_m"]
                        run_sums[grp] = s_new
                        for key, thr_mc in watch:
                            if (s_old >= thr_mc) != (s_new >= thr_mc):
                                flip_pending[key] = now
                    cwatch = count_watch.get(grp)
                    if cwatch and verb != "update_pod":
                        c_old = run_counts.get(grp, 0)
                        c_new = c_old + (1 if verb == "create_pod" else -1)
                        run_counts[grp] = c_new
                        for key, thr_n in cwatch:
                            if (c_old >= thr_n) != (c_new >= thr_n):
                                flip_pending[key] = now
            if verb == "update_pod" or verb == "create_pod":
                pod = _materialize_pod(
                    op["name"], op["grp"], op.get("node", "n0"), op["cpu_m"],
                    **_pod_fields(op),
                )
                pipeline.submit("upsert", "Pod", pod)
                n_applied_target += 1
            elif verb == "delete_pod":
                pipeline.submit("delete", "Pod", f"default/{op['name']}")
                n_applied_target += 1
            elif verb == "update_throttle":
                # the composed trace's spec churn (pod-count class only);
                # routed like any other spec change
                try:
                    thr = front.store.get_throttle("default", op["name"])
                except Exception:  # noqa: BLE001
                    continue
                from dataclasses import replace as _replace

                from ..api.types import ResourceAmount

                front.store.update_throttle_spec(
                    _replace(
                        thr,
                        spec=_replace(
                            thr.spec,
                            threshold=ResourceAmount.of(
                                pod=op.get("pod_threshold", 10)
                            ),
                        ),
                    )
                )
        t_fired = time.perf_counter() - t0
        pipeline.flush(timeout=120.0)
        front.drain(timeout=300.0)
        # the sustain clock stops HERE: fire window + ingest drain. The
        # recovery wait and the settle sleeps below are gate bookkeeping,
        # not ingest.
        t_sustain = time.perf_counter() - t0
        # recovery: the killed shard must be back and clean
        rec_deadline = time.monotonic() + recovery_s
        recovered = False
        while time.monotonic() < rec_deadline:
            state, _ = front._shards_health()
            if state == "ok":
                recovered = True
                break
            time.sleep(0.1)
        if outage:
            outage.append(time.perf_counter())
        front.drain(timeout=300.0)
        time.sleep(1.5)
        pipe_stats = pipeline.stats()
        front.store.remove_event_handler("Throttle", on_write)
        pipeline.stop()

        sustained = pipe_stats["events_applied"] / t_sustain
        report["events"] = pipe_stats["events_applied"]
        report["fired_hz"] = round(len(ops) / t_fired, 1)
        report["sustained_hz"] = round(sustained, 1)
        report["dropped"] = pipe_stats["dropped"]
        from .slo import _latency_gates_enforced

        enforced = _latency_gates_enforced()
        pace_ok = sustained >= pace_hz * min_pace_frac
        # dropped events are a correctness failure on any host; only the
        # sustained-rate comparison is host-speed-dependent
        report["gates"]["pace"] = {
            "pass": (pace_ok or not enforced) and pipe_stats["dropped"] == 0,
            "sustained_hz": round(sustained, 1),
            "target_hz": pace_hz,
            "min_frac": min_pace_frac,
        }
        if not enforced and not pace_ok:
            report["gates"]["pace"]["note"] = (
                "ADVISORY (host below latency core floor) — would FAIL"
            )
        report["gates"]["recovery"] = {
            "pass": recovered,
            "bound_s": recovery_s,
            "restarts": supervisor.restart_counts(),
            "killed_shard": killed_sid,
        }

        # flip p99, outage-excluded: a crossing STAMPED while its shard
        # was dark cannot publish before the restart+resync closes the
        # loop — those flips are the recovery gate's jurisdiction
        # (partition by anchor time = publication wall − lag, the same
        # restart-outage posture the composed engine takes)
        if outage and len(outage) == 2:
            lo, hi = outage[0] - _OUTAGE_PAD_S, outage[1] + _OUTAGE_PAD_S
            samples = [
                lag for lag, wall in zip(flip_lags, flip_walls)
                if not (lo <= (wall - lag) <= hi)
            ]
        else:
            samples = list(flip_lags)
        if samples:
            p50 = float(np.percentile(np.asarray(samples), 50)) * 1e3
            p99 = float(np.percentile(np.asarray(samples), 99)) * 1e3
        else:
            p50 = p99 = 0.0
        flip_ok = p99 <= flip_p99_ms
        report["gates"]["flip_p99"] = {
            "pass": flip_ok or not enforced,
            "p50_ms": round(p50, 1),
            "p99_ms": round(p99, 1),
            "bound_ms": flip_p99_ms,
            "samples": len(samples),
            "outage_excluded": max(0, len(flip_lags) - len(samples)),
        }
        if not enforced and not flip_ok:
            report["gates"]["flip_p99"]["note"] = (
                "ADVISORY (host below latency core floor) — would FAIL"
            )

        # zero wrong verdicts vs the rebuilt oracle (tools/harness.py)
        import tools.harness as H
        from ..api.pod import Namespace
        from ..engine.store import Store

        oracle_store = Store()
        oracle_store.create_namespace(Namespace("default"))
        for thr in front.store.list_throttles():
            oracle_store.create_throttle(thr)
        for pod in front.store.list_pods():
            oracle_store.create_pod(pod)
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        wrong = []
        for pod in oracle_store.list_pods():
            got = front.pre_filter(pod)
            want = oracle.pre_filter(pod)
            if got.code != want.code or H.normalized_reasons(
                got.reasons
            ) != H.normalized_reasons(want.reasons):
                wrong.append(pod.key)
        report["gates"]["verdicts"] = {
            "pass": not wrong,
            "wrong": len(wrong),
            "checked": len(oracle_store.list_pods()),
            "examples": wrong[:5],
        }
        report["pass"] = all(g["pass"] for g in report["gates"].values())
        return report
    finally:
        supervisor.stop()
        front.stop()


# --------------------------------------------------------------------------
# the hunt's sharded tier: arbitrary DSL programs through the real stack
# --------------------------------------------------------------------------

SHARD_TIER_PREFIXES = ("shard.", "reshard.", "net.")


def run_sharded_program(
    scn,
    seed: int,
    workdir: str = "",
    n_shards: int = 2,
    recovery_s: float = 60.0,
    prepare_ttl_s: float = 5.0,
) -> Dict:
    """Replay one DSL program (a hunt mutant) through the REAL
    multiprocess stack, arming the shard-tier fault sites the
    single-process engine can never fire:

    - ``shard.worker.kill`` → a ``--fault-site`` kill rule on one
      worker's first incarnation (monitor respawn + resync is the
      recovery under test);
    - ``reshard.handoff.torn`` → a torn-chunk rule on every worker (only
      a handoff SOURCE hits the site);
    - ``reshard.dest.crash`` (kill) → armed on the rescale's NEW worker;
    - ``reshard.{dest.crash(error),fence.race,front.crash}`` → in-process
      rules on the front's plan (the coordinator checks them).

    Whenever any ``reshard.*`` site is armed the run drives one live
    rescale ``n_shards → n_shards+1`` at ~40% of the trace, so the sites
    are reachable end to end. Virtual-time fault scheduling quantizes to
    hit counts in this tier (worker-side rules count routed batches /
    import chunks, not trace seconds) — the committed header still pins
    the program's canonical plan, so dedupe and shrinking stay sound.

    Gates are the DETERMINISTIC ones (verdicts, flips, orphans,
    recovery); flip latency is reported, not gated — three jax workers
    timeshare one hunt core, and a timing gate there would hunt the
    host, not the code. Writes the engine-schema report file
    (``report-<name>-s<seed>.json``) so the hunt's fresh-interpreter
    evaluator and the coverage fingerprint consume it unchanged."""
    from ..faults.plan import FaultPlan
    from ..sharding.front import AdmissionFront
    from ..sharding.supervisor import ShardSupervisor
    from .engine import _materialize_pod, _pod_fields, _seed_remote_store
    from .trace import build_topology, build_trace, serialize_trace, trace_sha256

    host_cores = len(os.sched_getaffinity(0))
    shard_faults = [
        f for f in scn.faults if f.site.startswith(SHARD_TIER_PREFIXES)
    ]
    kill_armed = [f for f in shard_faults if f.site == "shard.worker.kill"]
    torn_armed = [f for f in shard_faults if f.site == "reshard.handoff.torn"]
    dest_kill = [
        f for f in shard_faults
        if f.site == "reshard.dest.crash" and f.mode == "kill"
    ]
    inproc = [
        f for f in shard_faults
        if f.site in ("reshard.fence.race", "reshard.front.crash")
        or (f.site == "reshard.dest.crash" and f.mode != "kill")
    ]
    # net.* fires in the TCP framing layer: a program arming any of them
    # runs the fleet over transport="tcp" and arms the rules CLIENT-side
    # on one shard's handle (the same one-victim convention as
    # shard.worker.kill) — asymmetric by construction
    net_armed = [f for f in shard_faults if f.site.startswith("net.")]
    do_rescale = any(f.site.startswith("reshard.") for f in shard_faults)

    plan = FaultPlan(seed=seed)
    for f in inproc:
        plan.rule(f.site, mode=f.mode, times=f.times or 1)
    per_shard: Dict[int, List[str]] = {}
    if torn_armed:
        for sid in range(n_shards):
            per_shard[sid] = [
                "--fault-site", f"reshard.handoff.torn:{torn_armed[0].mode}:0",
            ]
    if kill_armed:
        sid = 1 if n_shards > 1 else 0
        per_shard[sid] = ["--fault-site", "shard.worker.kill:kill:5"]

    topology = build_topology(scn, seed)
    header, ops = build_trace(scn, seed)
    trace_sha = trace_sha256(serialize_trace(header, ops))
    pace_hz = min(scn.arrival.rate_hz, UNDERSUBSCRIBED_PACE_HZ)

    front = AdmissionFront(n_shards, faults=plan)
    supervisor = ShardSupervisor(
        front,
        use_device=True,
        restart_backoff=0.3,
        transport="tcp" if net_armed else "socketpair",
        worker_args=["--prepare-ttl", str(prepare_ttl_s)],
        per_shard_args=per_shard,
        env={**os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"},
    )
    supervisor.start(ready_timeout=300.0)

    net_plan: Optional[FaultPlan] = None
    net_t0: List[float] = [float("inf")]
    if net_armed:
        net_plan = FaultPlan(seed=seed)
        # DSL windows are virtual trace-seconds; the client-side plan runs
        # on the wall clock — scale by replay-time / trace-time (hit-count
        # rules pass through unscaled, same quantization posture as the
        # worker-side rules above)
        wall_per_virtual = (len(ops) / pace_hz) / max(scn.duration_s, 1e-9)
        for f in net_armed:
            window = None
            if f.window is not None:
                window = (
                    f.window[0] * wall_per_virtual,
                    f.window[1] * wall_per_virtual,
                )
            # an unbounded blackhole rule would hold the shard down past
            # every gate deadline and hunt the harness, not the code: a
            # windowless rule defaults to a small finite burst
            times = f.times if f.times is not None else (
                None if window is not None else 3
            )
            net_plan.rule(
                f.site, mode=f.mode, probability=f.probability,
                times=times, delay=f.delay, window=window,
            )
        net_plan.set_time_source(lambda: time.perf_counter() - net_t0[0])
        net_sid = 1 if n_shards > 1 else 0
        front.shards[net_sid].faults = net_plan
    report: Dict = {
        "scenario": scn.name,
        "tier": "sharded",
        "shards": n_shards,
        "seed": seed,
        "trace_sha256": trace_sha,
        "pace_hz": pace_hz,
        "host_cores": host_cores,
        "gates": {},
    }
    rescale_result: Dict = {}
    try:
        _seed_remote_store(front.store, scn, topology)
        front.drain(timeout=300.0)

        from ..engine.ingest import MicroBatchIngest

        pipeline = MicroBatchIngest(front.store, batch_policy="adaptive")

        def run_rescale() -> None:
            spawn_args = None
            if dest_kill:
                spawn_args = {
                    supervisor.n_shards: [
                        "--fault-site", "reshard.dest.crash:kill:1",
                    ]
                }
            try:
                rescale_result["report"] = supervisor.rescale(
                    n_shards + 1, handoff_deadline_s=120.0,
                    spawn_args=spawn_args,
                )
            except Exception as e:  # noqa: BLE001 — gate evidence below
                rescale_result["error"] = repr(e)

        rescale_thread: Optional[threading.Thread] = None
        rescale_idx = int(len(ops) * 0.4) if do_rescale else -1
        t0 = time.perf_counter()
        net_t0[0] = t0  # anchor the client-side net plan's wall clock
        for i, op in enumerate(ops):
            next_at = t0 + i / pace_hz
            delay = next_at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            if i == rescale_idx:
                rescale_thread = threading.Thread(
                    target=run_rescale, name="hunt-rescale", daemon=True
                )
                rescale_thread.start()
            verb = op["verb"]
            if verb in ("update_pod", "create_pod"):
                pipeline.submit(
                    "upsert", "Pod",
                    _materialize_pod(
                        op["name"], op["grp"], op.get("node", "n0"),
                        op["cpu_m"], **_pod_fields(op),
                    ),
                )
            elif verb == "delete_pod":
                pipeline.submit("delete", "Pod", f"default/{op['name']}")
        t_fired = time.perf_counter() - t0
        pipeline.flush(timeout=120.0)
        if rescale_thread is not None:
            rescale_thread.join(timeout=300.0)
        front.drain(timeout=300.0)

        # recovery: any armed kill must end with every shard back and ok
        rec_deadline = time.monotonic() + recovery_s
        recovered = False
        while time.monotonic() < rec_deadline:
            state, _ = front._shards_health()
            if state == "ok":
                recovered = True
                break
            time.sleep(0.2)
        front.drain(timeout=300.0)
        time.sleep(1.0)
        pipe_stats = pipeline.stats()
        pipeline.stop()

        crash_armed = any(f.site == "reshard.front.crash" for f in inproc)
        if crash_armed:
            # the orphaned handoff is cleaned by the shard-side TTL
            # reapers, not by anyone in-band — wait out the prepare TTL
            time.sleep(prepare_ttl_s + 2.0)

        restarts_total = sum(supervisor.restart_counts().values())
        report["measurements"] = {
            "events_per_sec": round(
                pipe_stats["events_applied"] / max(t_fired, 1e-9), 1
            ),
            "flip_lag_p99_ms": 0.0,
            "flip_samples": 0,
            "restarts": restarts_total,
            "recovery_s": None,
        }
        report["rescale"] = rescale_result.get("report") or {
            "error": rescale_result.get("error")
        }

        report["gates"]["recovery"] = {
            "pass": recovered,
            "bound_s": recovery_s,
            "restarts": supervisor.restart_counts(),
        }
        if do_rescale:
            ok = "report" in rescale_result or crash_armed
            report["gates"]["reshard"] = {
                "pass": bool(ok),
                "aborts": (rescale_result.get("report") or {}).get("aborts", 0),
                "error": rescale_result.get("error"),
                "crash_armed": crash_armed,
            }

        # oracle equivalence: verdicts + published flip flags
        import tools.harness as H
        from ..api.pod import Namespace
        from ..engine.store import Store

        oracle_store = Store()
        oracle_store.create_namespace(Namespace("default"))
        for thr in front.store.list_throttles():
            oracle_store.create_throttle(thr)
        for pod in front.store.list_pods():
            oracle_store.create_pod(pod)
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()
        wrong = []
        for pod in oracle_store.list_pods():
            got = front.pre_filter(pod)
            want = oracle.pre_filter(pod)
            if got.code != want.code or H.normalized_reasons(
                got.reasons
            ) != H.normalized_reasons(want.reasons):
                wrong.append(pod.key)
        report["gates"]["verdicts"] = {
            "pass": not wrong,
            "wrong": len(wrong),
            "checked": len(oracle_store.list_pods()),
            "examples": wrong[:5],
        }
        oracle_by_key = {t.key: t for t in oracle_store.list_throttles()}
        stale = [
            thr.key
            for thr in front.store.list_throttles()
            if (w := oracle_by_key.get(thr.key)) is not None
            and thr.status.throttled != w.status.throttled
        ]
        report["gates"]["flips"] = {
            "pass": not stale, "stale": len(stale), "examples": stale[:5],
        }

        audit_bad = []
        fenced_refused = 0
        for sid in range(front.n_shards):
            handle = front.shards.get(sid)
            if handle is None or not handle.alive:
                audit_bad.append(f"shard-{sid}: down")
                continue
            try:
                a = handle.request("reshard_audit", None, timeout=30.0)
            except Exception as e:  # noqa: BLE001 — a dark shard fails the gate
                audit_bad.append(f"shard-{sid}: {e}")
                continue
            fenced_refused += a.get("fenced_writes_refused", 0)
            if a["orphan_reservations"] or a["pending_handoffs"] or a["fenced_handoffs"]:
                audit_bad.append(f"shard-{sid}: {a}")
        report["gates"]["orphans"] = {"pass": not audit_bad, "bad": audit_bad}

        # coverage fingerprint: in-process firings from the plan history,
        # worker-side firings witnessed by their observable effects
        fp_sites = {site: len(v) for site, v in plan.snapshot().items()}
        if net_plan is not None:
            for site, v in net_plan.snapshot().items():
                fp_sites[site] = fp_sites.get(site, 0) + len(v)
        rep = rescale_result.get("report") or {}
        if kill_armed and restarts_total:
            fp_sites["shard.worker.kill"] = fp_sites.get(
                "shard.worker.kill", 0
            ) + 1
        if dest_kill and rep.get("aborts"):
            fp_sites["reshard.dest.crash"] = fp_sites.get(
                "reshard.dest.crash", 0
            ) + int(rep["aborts"])
        if torn_armed and (rep.get("aborts") or fenced_refused):
            fp_sites["reshard.handoff.torn"] = fp_sites.get(
                "reshard.handoff.torn", 0
            ) + max(int(rep.get("aborts", 0)), 1)
        report["fingerprint"] = {
            "fault_sites": fp_sites,
            "metric_families": {},
            "health_transitions": [],
        }
        report["all_pass"] = all(g["pass"] for g in report["gates"].values())
    finally:
        supervisor.stop()
        front.stop()
    if workdir:
        os.makedirs(workdir, exist_ok=True)
        path = os.path.join(workdir, f"report-{scn.name}-s{seed}.json")
        with open(path, "w") as f:
            json.dump(report, f, indent=2, default=str)
            f.write("\n")
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="scenarios.sharded")
    parser.add_argument("--shards", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--pace", type=float, default=0.0,
        help="replay pace in ev/s; 0 = auto (1400 knee-lift gate on a "
        ">=shards+1 core host, 700 protocol-check pace otherwise)",
    )
    parser.add_argument("--scenario", default="bad_day")
    parser.add_argument("--json", default="", help="write the report here too")
    args = parser.parse_args(argv)
    report = run_sharded_bad_day(
        n_shards=args.shards, seed=args.seed, pace_hz=args.pace,
        scenario_name=args.scenario,
    )
    print(json.dumps(report, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
