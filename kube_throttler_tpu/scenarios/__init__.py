"""Cluster-scale scenario engine: trace-driven storms, SLO gates, and a
replayable corpus (docs/scenarios.md).

Composition: a :class:`~.dsl.Scenario` (arrival process × object topology
× fault schedule) compiles to a committed byte-deterministic trace
(trace.py), replays through the real remote-mode stack — mock apiserver →
reflectors → micro-batched ingest → controllers → device planes → async
committer — (engine.py), and is judged by per-scenario SLO gates
(slo.py): flip p99, ingest sustain, bounded post-restart recovery, zero
wrong admission verdicts, bounded leader failover.

CLI: ``python -m kube_throttler_tpu.scenarios`` (``make scenario-test``
runs the corpus matrix). Heavy imports stay inside the submodules — this
package root is import-cheap for the analyzer and the test collector.
"""

from .dsl import Arrival, FaultSpec, Scenario, SloGates, Topology  # noqa: F401
from .corpus import SCENARIOS, corpus, get_scenario  # noqa: F401

__all__ = [
    "Arrival",
    "FaultSpec",
    "Scenario",
    "SloGates",
    "Topology",
    "SCENARIOS",
    "corpus",
    "get_scenario",
]
