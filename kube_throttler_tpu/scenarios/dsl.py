"""The scenario DSL: arrival process × object topology × fault schedule.

A :class:`Scenario` is a pure, declarative value — everything a run needs
is derived deterministically from ``(scenario, seed)``:

- the **arrival process** shapes WHEN workload ops fire (diurnal
  sinusoids, linear ramps, thundering-herd bursts, constant pacing);
- the **object topology** shapes WHAT exists (pod/throttle counts, label
  groups, the hot-key group one throttle matches at scale, nodes for
  drain waves) — built once before the trace starts;
- the **fault schedule** shapes WHAT BREAKS and WHEN, as
  :class:`FaultSpec` entries compiled onto one seeded
  :class:`~kube_throttler_tpu.faults.plan.FaultPlan` (virtual-time
  ``at_times``/``window`` rules — faults/plan.py) shared by the
  mockserver, the transport, and the engine's own ``scenario.*`` action
  sites (apiserver restart, continue-token expiry, churn stalls).

The composition is committed to a replayable trace file
(scenarios/trace.py) before anything runs; the SLO gates
(scenarios/slo.py) judge the replay. Corpus lives in scenarios/corpus.py.
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass, field
from typing import Dict, Optional, Tuple

__all__ = [
    "Arrival",
    "Topology",
    "FaultSpec",
    "SloGates",
    "Scenario",
    "arrival_rate",
    "compile_fault_rules",
    "scenario_to_dict",
    "scenario_from_dict",
]


@dataclass(frozen=True)
class Arrival:
    """Workload op rate over virtual time.

    ``kind``:
    - ``constant`` — ``rate_hz`` throughout;
    - ``ramp`` — linear ``start_frac·rate_hz`` → ``rate_hz`` over the run;
    - ``diurnal`` — sinusoid between ``trough_frac·rate_hz`` and
      ``rate_hz``, ``cycles`` full periods over the run (the compressed
      day/night traffic shape);
    - ``bursts`` — ``rate_hz`` during each ``burst_s`` window, near-idle
      (``trough_frac·rate_hz``) for ``idle_s`` between (thundering herd).
    """

    kind: str = "constant"
    rate_hz: float = 1000.0
    start_frac: float = 0.1
    trough_frac: float = 0.2
    cycles: float = 2.0
    burst_s: float = 0.5
    idle_s: float = 1.0


def arrival_rate(a: Arrival, t: float, duration_s: float) -> float:
    """Instantaneous op rate at virtual time ``t`` (pure; the trace
    builder integrates it into op timestamps)."""
    if a.kind == "constant":
        return a.rate_hz
    if a.kind == "ramp":
        frac = a.start_frac + (1.0 - a.start_frac) * min(1.0, t / max(duration_s, 1e-9))
        return a.rate_hz * frac
    if a.kind == "diurnal":
        # trough at t=0, peak mid-cycle: (1-cos)/2 sweeps 0→1→0 per cycle
        phase = (1.0 - math.cos(2.0 * math.pi * a.cycles * t / max(duration_s, 1e-9))) / 2.0
        return a.rate_hz * (a.trough_frac + (1.0 - a.trough_frac) * phase)
    if a.kind == "bursts":
        period = a.burst_s + a.idle_s
        return a.rate_hz if (t % period) < a.burst_s else a.rate_hz * a.trough_frac
    raise ValueError(f"unknown arrival kind {a.kind!r}")


@dataclass(frozen=True)
class Topology:
    """What exists before the trace starts (built deterministically from
    the seed). ``hot_frac`` > 0 routes that fraction of all pods into one
    ``hot`` label group matched by a single throttle — the hot-key shape
    where one throttle's matched-column set dominates the (N,K) device
    encoding. ``nodes`` spreads pods for the rolling-drain waves.

    Gang / heterogeneity axes (PR 7's admission paths, searchable by the
    hunt mutators): ``gang_size`` > 0 stamps the initial population with
    PodGroup annotations — each label group's pods join gangs of that
    size — so replay traffic exercises the gang ledger's member
    bookkeeping; ``accel_classes`` > 0 spreads pods over that many
    ``accel-class`` annotations, and ``class_threshold_frac`` > 0 gives
    the flip-band throttles per-class ``accelClassThresholds`` entries
    (class c's threshold scaled down by up to that fraction), so the
    class-resolved admission inequality diverges from the base one.

    Priority axis (PR 15's preemption & policy paths): ``priority_levels``
    > 0 spreads the population over that many ``priority`` annotations
    (0..levels-1), the preemption-shaped distribution the policy layer's
    ordered lanes and victim ranking read. All four default OFF —
    committed traces stay byte-identical."""

    pods: int = 5000
    throttles: int = 300
    groups: int = 150
    hot_frac: float = 0.0
    nodes: int = 8
    gang_size: int = 0
    accel_classes: int = 0
    class_threshold_frac: float = 0.0
    priority_levels: int = 0


@dataclass(frozen=True)
class FaultSpec:
    """One fault-schedule entry, compiled to a FaultPlan rule. ``t`` is a
    single virtual-time instant (``at_times=[t]``); ``window`` a virtual
    interval for probabilistic storms. Engine-action sites (``scenario.*``)
    use ``mode`` to pick the action: ``restart`` (apiserver restart with
    RV-window reset), ``expire_continues`` (continue-token expiry
    mid-pagination), ``delay`` (churn stall)."""

    site: str
    mode: str = "error"
    t: Optional[float] = None
    window: Optional[Tuple[float, float]] = None
    probability: float = 1.0
    times: Optional[int] = None
    delay: float = 0.0


@dataclass(frozen=True)
class SloGates:
    """Per-scenario SLO bounds. A gate with a None bound is not evaluated
    (e.g. recovery on scenarios that never restart the apiserver)."""

    flip_p99_ms: float = 150.0
    # optional p50 gate: the stable center for scenarios whose p99 rides
    # the 1-core harness's co-tenant noise (drain/herd membership churn)
    flip_p50_ms: Optional[float] = None
    min_flip_samples: int = 3  # fewer ⇒ the flip gate FAILS as unmeasurable
    # ingest sustain: the replayer must achieve this fraction of the
    # trace's nominal rate, and the pipeline must apply (not shed) at
    # least this fraction of what reached the apiserver
    min_pace_frac: float = 0.5
    min_applied_frac: float = 0.98
    recovery_s: Optional[float] = None
    max_wrong_verdicts: int = 0
    failover_window_s: Optional[float] = None


@dataclass(frozen=True)
class Scenario:
    """One corpus entry. ``pattern`` shapes the op stream the arrival
    process paces: ``churn`` (update-heavy mix), ``drain`` (rolling
    node-drain waves over background churn), ``herd`` (a deployment-sized
    create burst, later deleted, over background churn). ``leader_kill``
    appends the process-level kill-the-leader episode (tools/harness.py +
    tools/hatest.py, the PR 6 ha.* machinery) after the in-process
    replay. ``durable`` attaches the PR 4 durability stack (journal +
    size-triggered snapshots + compaction) to the serving store for the
    run — the long-horizon hunt tier's journal-compaction/snapshot-cycle
    pressure (scenarios/hunt/longhorizon.py)."""

    name: str
    description: str
    duration_s: float = 5.0
    arrival: Arrival = field(default_factory=Arrival)
    topology: Topology = field(default_factory=Topology)
    faults: Tuple[FaultSpec, ...] = ()
    slo: SloGates = field(default_factory=SloGates)
    pattern: str = "churn"
    # churn mix (update / create / delete / throttle-spec weights)
    mix: Tuple[Tuple[str, float], ...] = (
        ("update", 0.88), ("create", 0.05), ("delete", 0.04), ("spec", 0.03),
    )
    herd_size: int = 0
    leader_kill: bool = False
    durable: bool = False

    def mix_weights(self) -> Dict[str, float]:
        return dict(self.mix)


def compile_fault_rules(plan, scn: "Scenario") -> None:
    """Compile the scenario's fault schedule onto ``plan`` (one seeded
    FaultPlan shared by the mockserver, the transport, and the engine's
    scenario.* action sites). ONE implementation — the engine installs
    rules with it, the trace header commits the plan's canonical form
    through it (scenarios/trace.py), and the hunt dedupes mutants by that
    form — so the committed header can never drift from what actually
    runs."""
    for fs in scn.faults:
        plan.rule(
            fs.site,
            mode=fs.mode,
            probability=fs.probability,
            times=fs.times,
            delay=fs.delay,
            at_times=[fs.t] if fs.t is not None else None,
            window=fs.window,
        )
    if scn.leader_kill:
        plan.rule("scenario.leader.kill", mode="kill", times=1)


def scenario_to_dict(scn: "Scenario") -> Dict:
    """JSON-able program form (the hunt's corpus/promotion interchange and
    the ``run --file`` input). Pure dataclass data — round-trips through
    :func:`scenario_from_dict` losslessly."""
    return asdict(scn)


def scenario_from_dict(d: Dict) -> "Scenario":
    """Inverse of :func:`scenario_to_dict` (tuples rebuilt from JSON
    lists). Unknown keys are rejected — a promoted repro written by a
    newer DSL must fail loudly, not silently drop a program axis."""
    d = dict(d)
    arrival = Arrival(**d.pop("arrival", {}))
    topology = Topology(**d.pop("topology", {}))
    slo = SloGates(**d.pop("slo", {}))
    faults = []
    for f in d.pop("faults", ()) or ():
        f = dict(f)
        if f.get("window") is not None:
            f["window"] = (float(f["window"][0]), float(f["window"][1]))
        faults.append(FaultSpec(**f))
    mix = tuple((str(k), float(w)) for k, w in d.pop("mix", ()) or ())
    kwargs = dict(
        d,
        arrival=arrival,
        topology=topology,
        slo=slo,
        faults=tuple(faults),
    )
    if mix:
        kwargs["mix"] = mix
    return Scenario(**kwargs)
