"""Preemption-storm scenario: gang waves vs low-priority residents, with
a no-thrash victim-churn SLO gate.

The generic replay engine (scenarios/engine.py) drives reflectors and
controllers but no scheduler, so this scenario — like the sharded and
resharding chaos scenarios — owns a dedicated runner over the REAL
admission stack: store + plugin (policy with preemption enabled) +
embedded scheduler. The ``preempt_storm`` corpus entry
(scenarios/corpus.py) is the declarative program; this module interprets
its topology axes (gang_size, priority_levels) into the storm:

1. **Residents** — every label group's throttle is filled to its cpu
   threshold by priority-0/1 RUNNING pods; a fraction are gang-shaped
   (whole-gang eviction must fire, not just single-pod eviction).
2. **Waves** — per wave, high-priority gangs land Pending on saturated
   groups. Admission rejects them for capacity; the scheduler's
   preemption hook selects ranked victims (batched kernel ≡ sequential
   oracle), evicts whole units through delete-then-requeue, and the
   freed capacity admits the gang on the requeue. The wave's gangs then
   finish (delete) and their EVICTED victims are recreated Pending — the
   deployment-controller shape that makes churn measurable. Recreated
   victims readmit between waves; the rank order's age axis (oldest
   first) then steers the NEXT wave's selection away from them.

Gates (report JSON on stdout; nonzero exit on any failure):

- ``admitted``      — every high-priority gang of every wave admitted;
- ``no_half_gangs`` — no resident gang is ever left partially present
  (whole-gang eviction, checked after every wave AND at the end);
- ``victim_order``  — every evicted pod's priority sat below every
  preemptor's (the min_priority_gap contract);
- ``churn``         — the no-thrash SLO: evicted-then-readmitted-then-
  re-evicted rate ≤ ``MAX_REEVICT_FRAC`` of victims, and total victims
  ≤ ``MAX_VICTIM_FACTOR``× the storm's aggregate minimal need (the
  selector must stay near-minimal, not clear-cut whole groups);
- ``oracle``        — a final seeded kernel ≡ sequential-oracle sweep
  over synthetic selection problems (the in-situ twin of the tier-1
  equivalence tests).

Run: ``python -m kube_throttler_tpu.scenarios.preemption --seed 0``
(wired into ``make scenario-test``).
"""

from __future__ import annotations

import argparse
import json
import logging
import random
import sys
from typing import Dict, List, Set

__all__ = ["run_preemption_storm"]

logger = logging.getLogger(__name__)

N_WAVES = 3
GANGS_PER_WAVE = 2
# churn gates: re-evicting more than this fraction of evicted-and-
# readmitted victims is thrashing; selecting more than this multiple of
# the storm's aggregate minimal need is over-eviction
MAX_REEVICT_FRAC = 0.5
MAX_VICTIM_FACTOR = 2.0


def _build_stack(seed: int):
    from ..api.pod import Namespace
    from ..engine.store import Store
    from ..plugin import KubeThrottler, decode_plugin_args
    from ..scheduler import Node, Scheduler

    store = Store()
    store.create_namespace(Namespace("default"))
    plugin = KubeThrottler(
        decode_plugin_args(
            {
                "name": "kube-throttler",
                "targetSchedulerName": "my-scheduler",
                "policies": [
                    {
                        "name": "storm",
                        "preemptionEnabled": True,
                        "minPriorityGap": 1,
                        "maxVictimsPerCycle": 64,
                        "classWeights": [
                            {"accelClass": "gold", "weight": 2.0}
                        ],
                    }
                ],
            }
        ),
        store,
        use_device=True,
    )
    from ..scenarios.corpus import get_scenario

    scn = get_scenario("preempt_storm")
    nodes = [Node(f"n{i}") for i in range(max(scn.topology.nodes, 1))]
    sched = Scheduler(plugin, store, nodes=nodes)
    return store, plugin, sched, scn


def _make_throttle(name: str, grp: str, cpu_m: int):
    from ..api.types import (
        LabelSelector,
        ResourceAmount,
        Throttle,
        ThrottleSelector,
        ThrottleSelectorTerm,
        ThrottleSpec,
    )

    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": f"{cpu_m}m"}),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels={"grp": grp})),
                )
            ),
        ),
    )


def _gang_presence(store, members_of: Dict[str, Set[str]]) -> List[str]:
    """Resident gangs partially present: the half-evicted-gang violation
    list (empty = the whole-gang contract held)."""
    live = {p.key for p in store.list_pods("default")}
    violations = []
    for gang, members in members_of.items():
        present = members & live
        if present and present != members:
            violations.append(
                f"{gang}: {len(present)}/{len(members)} members present"
            )
    return violations


def _oracle_sweep(seed: int, cases: int = 25) -> bool:
    """Seeded kernel ≡ sequential-oracle equivalence over synthetic
    selection problems — the in-situ twin of the tier-1 sweep."""
    import numpy as np

    from ..ops.victim_select import victim_select
    from ..policy.victims import sequential_victim_select

    rng = random.Random(seed * 7919 + 11)
    for _ in range(cases):
        n = rng.randint(1, 24)
        m = rng.randint(1, 6)
        cap = rng.choice([0, 0, rng.randint(1, n)])
        contrib = np.array(
            [[rng.choice([0, 0, 1, 2, 100, 250]) for _ in range(m)] for _ in range(n)],
            dtype=np.int64,
        )
        deficit = np.array(
            [rng.choice([0, 1, 3, 200, 500]) for _ in range(m)], dtype=np.int64
        )
        ok_s, sel_s, _ = sequential_victim_select(deficit, contrib, max_victims=cap)
        sel_k, ok_k, _ = victim_select(contrib, deficit, max_victims=cap)
        if bool(np.asarray(ok_k)) != ok_s or list(
            np.nonzero(np.asarray(sel_k))[0]
        ) != sel_s:
            return False
    return True


def run_preemption_storm(seed: int = 0) -> Dict:
    from ..api.pod import make_pod

    store, plugin, sched, scn = _build_stack(seed)
    rng = random.Random(f"preempt_storm/{seed}")
    topo = scn.topology
    gang_size = max(topo.gang_size, 2)
    n_groups = max(topo.groups, 4)
    residents_per_group = max(topo.pods // n_groups, gang_size * 2)
    cpu_m = 100  # every pod requests 100m: deficits are exact multiples

    report: Dict = {
        "scenario": scn.name,
        "seed": seed,
        "groups": n_groups,
        "residents_per_group": residents_per_group,
        "waves": N_WAVES,
        "gates": {},
        "violations": [],
    }
    try:
        # one throttle per group, threshold == the resident sum: saturated
        for g in range(n_groups):
            store.create_throttle(
                _make_throttle(f"t{g}", f"g{g}", residents_per_group * cpu_m)
            )
        # residents: RUNNING low-priority pods; half the groups' pods are
        # gang-shaped so whole-gang eviction must fire
        resident_gangs: Dict[str, Set[str]] = {}
        resident_priority: Dict[str, int] = {}
        for g in range(n_groups):
            gangy = g % 2 == 0
            for i in range(residents_per_group):
                prio = rng.randrange(2)  # priority 0/1 — all below the waves'
                kwargs = {}
                if gangy:
                    gang_name = f"res-{g}-{i // gang_size}"
                    kwargs = {"group": gang_name, "group_size": gang_size}
                pod = make_pod(
                    f"res-{g}-{i}",
                    labels={"grp": f"g{g}"},
                    requests={"cpu": f"{cpu_m}m"},
                    node_name=f"n{(g + i) % max(topo.nodes, 1)}",
                    phase="Running",
                    priority=prio,
                    **kwargs,
                )
                store.create_pod(pod)
                resident_priority[pod.key] = prio
                if gangy:
                    resident_gangs.setdefault(
                        f"default/res-{g}-{i // gang_size}", set()
                    ).add(pod.key)
        sched.run_until_idle()  # statuses converge: every group saturated

        evicted_ever: Set[str] = set()
        reevicted: Set[str] = set()
        admitted_gangs = 0
        expected_gangs = 0
        min_need_total = 0
        preemptor_floor = 10**9
        coord = plugin.preempt

        # waves 0..N-2 hit FRESH groups; the final wave REVISITS wave 0's —
        # its residents now include readmitted ex-victims, so the rank
        # order's age axis (oldest first) is what keeps them from being
        # re-evicted: the churn gate measures exactly that
        fresh = rng.sample(range(n_groups), GANGS_PER_WAVE * (N_WAVES - 1))
        wave_plan = [
            fresh[w * GANGS_PER_WAVE : (w + 1) * GANGS_PER_WAVE]
            for w in range(N_WAVES - 1)
        ]
        wave_plan.append(wave_plan[0])
        for wave in range(N_WAVES):
            wave_groups = wave_plan[wave]
            wave_keys = []
            for j, g in enumerate(wave_groups):
                expected_gangs += 1
                min_need_total += gang_size  # gang_size * cpu_m over a full throttle
                prio = 5 + wave  # far above every resident
                preemptor_floor = min(preemptor_floor, prio)
                gang_name = f"hi-{wave}-{j}"
                for r in range(gang_size):
                    store.create_pod(
                        make_pod(
                            f"{gang_name}-r{r}",
                            labels={"grp": f"g{g}"},
                            requests={"cpu": f"{cpu_m}m"},
                            group=gang_name,
                            group_size=gang_size,
                            priority=prio,
                            accel_class="gold",
                        )
                    )
                wave_keys.append((gang_name, g))
            before = {p.key for p in store.list_pods("default")}
            sched.run_until_idle()
            after_pods = {p.key: p for p in store.list_pods("default")}
            newly_evicted = {
                k for k in before - set(after_pods)
                if k in resident_priority and k not in evicted_ever
            }
            re_evicted_now = {
                k for k in before - set(after_pods)
                if k in resident_priority and k in evicted_ever
            }
            reevicted |= re_evicted_now
            evicted_ever |= newly_evicted | re_evicted_now
            # gate data: admitted gangs = every rank bound
            for gang_name, _g in wave_keys:
                ranks = [
                    after_pods.get(f"default/{gang_name}-r{r}")
                    for r in range(gang_size)
                ]
                if all(p is not None and p.is_scheduled() for p in ranks):
                    admitted_gangs += 1
            half = _gang_presence(store, resident_gangs)
            if half:
                report["violations"].extend([f"wave {wave}: {v}" for v in half])
            # the wave's gangs finish; their evicted victims come back as
            # Pending recreations (the churn signal's raw material)
            for gang_name, _g in wave_keys:
                for r in range(gang_size):
                    try:
                        store.delete_pod("default", f"{gang_name}-r{r}")
                    except KeyError:
                        pass
            for key in sorted(newly_evicted | re_evicted_now):
                name = key.partition("/")[2]
                gang_of = next(
                    (gk for gk, mem in resident_gangs.items() if key in mem), None
                )
                kwargs = {}
                if gang_of is not None:
                    kwargs = {
                        "group": gang_of.partition("/")[2],
                        "group_size": gang_size,
                    }
                grp = name.split("-")[1]
                store.create_pod(
                    make_pod(
                        name,
                        labels={"grp": f"g{grp}"},
                        requests={"cpu": f"{cpu_m}m"},
                        priority=resident_priority[key],
                        **kwargs,
                    )
                )
            sched.run_until_idle()  # readmissions between waves

        # ---- gates -----------------------------------------------------
        victims_total = coord.victims_total
        churn_frac = len(reevicted) / max(len(evicted_ever), 1)
        report.update(
            {
                "admitted_gangs": admitted_gangs,
                "expected_gangs": expected_gangs,
                "victims_total": victims_total,
                "evicted_unique": len(evicted_ever),
                "reevicted": len(reevicted),
                "readmitted_total": coord.readmitted_total,
                "infeasible_total": coord.infeasible_total,
                "min_need_total": min_need_total,
                "churn_frac": round(churn_frac, 3),
            }
        )
        victim_order_ok = all(
            resident_priority[k] + 1 <= preemptor_floor for k in evicted_ever
        )
        final_half = _gang_presence(store, resident_gangs)
        if final_half:
            report["violations"].extend([f"final: {v}" for v in final_half])
        gates = {
            "admitted": admitted_gangs == expected_gangs,
            "no_half_gangs": not report["violations"],
            "victim_order": victim_order_ok,
            "churn": (
                churn_frac <= MAX_REEVICT_FRAC
                and victims_total <= int(min_need_total * MAX_VICTIM_FACTOR)
                + gang_size * N_WAVES  # whole-gang rounding slack
            ),
            "oracle": _oracle_sweep(seed),
        }
        report["gates"] = gates
        report["ok"] = all(gates.values())
        return report
    finally:
        sched.stop()
        plugin.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="scenarios.preemption")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    report = run_preemption_storm(seed=args.seed)
    print(json.dumps(report, indent=2, sort_keys=True))
    if not report.get("ok"):
        failed = [g for g, ok in report.get("gates", {}).items() if not ok]
        print(f"FAIL preempt_storm seed={args.seed}: gates {failed}", file=sys.stderr)
        return 1
    print(
        f"PASS preempt_storm seed={args.seed}: "
        f"{report['admitted_gangs']}/{report['expected_gangs']} gangs admitted, "
        f"{report['victims_total']} victim(s), churn {report['churn_frac']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
