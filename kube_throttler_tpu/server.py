"""HTTP daemon surface: the standalone throttler service.

The reference ships as a plugin living inside kube-scheduler's process (its
API surface is the scheduler framework + the CRDs on the apiserver). The
standalone TPU framework exposes the same operations over HTTP so any
scheduler (or test driver) can use it without embedding Python:

- ``GET  /healthz``                  liveness
- ``GET  /readyz``                   component readiness (device breaker,
                                     workqueue depths)
- ``GET  /metrics``                  Prometheus exposition (the 16 families)
- ``POST /v1/objects``               create-or-update a manifest
                                     (Pod / Namespace / Throttle / ClusterThrottle)
- ``DELETE /v1/objects/{kind}/{key}``
- ``GET  /v1/throttles`` ``/v1/clusterthrottles`` ``/v1/pods``  list + status
- ``POST /v1/prefilter``             {pod manifest | {"podKey": ...}} → status/reasons
- ``POST /v1/reserve`` ``/v1/unreserve``
- ``POST /v1/bind``                  {"podKey", "nodeName"} — scheduler-sim
                                     convenience: marks the pod scheduled+Running

Handlers are thin wrappers over the plugin's typed clientset + listers
(the client layer the reference reads/writes through, plugin.go:76-88);
concurrency is whatever the plugin already guarantees.
"""

from __future__ import annotations

import json
import threading
from dataclasses import replace
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from .utils.lockorder import make_lock
from .api.pod import Namespace
from .api.serialization import object_from_dict
from .api.types import ClusterThrottle, Throttle
from .utils import tracing
from .engine.store import NotFoundError, Store
from .plugin import KubeThrottler


def _throttle_to_dict(thr) -> dict:
    out = {
        "metadata": {"name": thr.name},
        "status": {
            "used": thr.status.used.to_dict(),
            "throttled": thr.status.throttled.to_dict(),
            "calculatedThreshold": {
                "threshold": thr.status.calculated_threshold.threshold.to_dict(),
                "calculatedAt": (
                    thr.status.calculated_threshold.calculated_at.isoformat()
                    if thr.status.calculated_threshold.calculated_at
                    else None
                ),
                "messages": list(thr.status.calculated_threshold.messages),
            },
        },
        "spec": {"threshold": thr.spec.threshold.to_dict()},
    }
    if isinstance(thr, Throttle):
        out["metadata"]["namespace"] = thr.namespace
        out["kind"] = "Throttle"
    else:
        out["kind"] = "ClusterThrottle"
    return out


class ThrottlerHTTPServer:
    def __init__(
        self,
        plugin: Optional[KubeThrottler],
        host: str = "127.0.0.1",
        port: int = 10259,
        remote: bool = False,
        ha=None,
        metrics_registry=None,
        replica_gate=None,
        owner_url: Optional[str] = None,
    ):
        """``remote=True`` (daemon synced from a real apiserver via
        reflectors) disables the local object-mutation endpoints: a local
        write to a reflector-owned kind would be silently reverted by the
        next watch event — mutate the real cluster instead. Admission
        endpoints (/v1/prefilter, reserve, unreserve) stay available.

        ``plugin=None`` + ``ha`` (an engine.replication.HaCoordinator) is
        STANDBY mode: the server answers /healthz (alive), reports role
        ``standby`` on /readyz (503 — probes must not route admission
        traffic here), and refuses every /v1 endpoint except the
        replication routes. :meth:`set_plugin` flips it to serving at
        promotion. A LEADER passes ``ha`` too: its replication source is
        served from ``/v1/replication/*`` so warm standbys can bootstrap
        and stream the journal tail.

        ``metrics_registry`` makes ``/metrics`` scrapeable BEFORE the
        plugin exists — a standby's replication lag is exactly the metric
        that only matters pre-promotion; falls back to the plugin's
        registry when absent (they are the same object in the daemon).

        ``replica_gate`` (an engine.replication.ReplicaGate) + ``owner_url``
        is READ-REPLICA mode: /v1/prefilter and /v1/prefilter-batch are
        served LOCALLY from the replicated mirror — gated on the staleness
        bound (503 when the replica cannot prove freshness) — and every
        write surface (/v1/objects, reserve/unreserve, bind, tick, DELETE)
        is transparently forwarded to the owner, so a client can point at
        either tier without caring which one it hit."""
        if plugin is None and ha is None:
            raise ValueError("plugin-less server requires an HA coordinator")
        if replica_gate is not None and (plugin is None or not owner_url):
            raise ValueError("replica mode requires a plugin and an owner URL")
        self.plugin = plugin
        self.remote = remote
        self.ha = ha
        self.replica_gate = replica_gate
        self.owner_url = owner_url
        self.metrics_registry = (
            metrics_registry
            if metrics_registry is not None
            else (plugin.metrics_registry if plugin is not None else None)
        )
        self.store = plugin.store if plugin is not None else None
        self.clientset = plugin.clientset if plugin is not None else None
        self.listers = plugin.listers if plugin is not None else None
        # serializes get-then-update pod mutations (re-apply, bind): the
        # handler pool is threaded and a lost update here silently unbinds
        # a running pod
        self._pod_write_lock = make_lock("server.pod_write")
        # graceful-shutdown flag (single writer: the SIGTERM path). While
        # set, /readyz answers 503 "down" so the load balancer / kubelet
        # drains this instance before the final snapshot + journal fsync;
        # /healthz stays 200 — the process is alive and must not be killed
        # mid-flush.
        self._draining = False
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, body, content_type="application/json"):
                data = (
                    body.encode() if isinstance(body, str) else json.dumps(body).encode()
                )
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length", "0"))
                if length == 0:
                    return {}
                return json.loads(self.rfile.read(length))

            def do_GET(self):
                try:
                    outer._get(self)
                except Exception as e:  # pragma: no cover
                    self._send(500, {"error": str(e)})

            def do_POST(self):
                try:
                    outer._post(self)
                except NotFoundError as e:
                    self._send(404, {"error": str(e)})
                except Exception as e:
                    self._send(400, {"error": str(e)})

            def do_DELETE(self):
                try:
                    outer._delete(self)
                except NotFoundError as e:
                    self._send(404, {"error": str(e)})
                except Exception as e:
                    self._send(400, {"error": str(e)})

            def do_PUT(self):
                try:
                    outer._put(self)
                except Exception as e:
                    self._send(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- handlers

    def _put(self, h) -> None:
        # dynamic verbosity — the scheduler's PUT /debug/flags/v analog
        # (reference Makefile:94-95: log-level / log-level-debug targets)
        if h.path == "/debug/flags/v":
            length = int(h.headers.get("Content-Length", "0"))
            raw = h.rfile.read(length).decode().strip() if length else ""
            level = int(raw)
            prev = tracing.set_verbosity(level)
            h._send(200, f"successfully set klog.logging.verbosity to {level} (was {prev})",
                    content_type="text/plain")
        else:
            h._send(404, {"error": f"unknown path {h.path}"})

    def _get(self, h) -> None:
        if self.ha is not None and self.ha.source is not None:
            from .engine.replication import handle_replication_get

            if handle_replication_get(h, self.ha.source, h.path):
                return
        if h.path == "/healthz":
            h._send(200, "ok", content_type="text/plain")
        elif h.path == "/metrics" and self.metrics_registry is not None:
            # served on a standby too (plugin still None): replication lag
            # is the one family operators need exactly while standing by
            h._send(
                200,
                self.metrics_registry.exposition(),
                content_type="text/plain; version=0.0.4",
            )
        elif self.plugin is None:
            # standby: alive but not serving — /readyz reports the role
            # (503 keeps admission traffic away until promotion) and every
            # other surface refuses
            if h.path == "/readyz":
                state, detail = self.ha.health_state()
                h._send(
                    503,
                    {
                        "ok": False,
                        "state": "standby",
                        "components": {"ha": {"state": state, **detail}},
                    },
                )
            else:
                h._send(503, {"error": "standby replica; not serving yet"})
        elif h.path == "/readyz":
            # component readiness via the health state machine (health.py):
            # 200 while serving is possible — ok AND degraded both serve
            # (an open device breaker is a latency regression, the host
            # oracle answers); 503 only when a component is down (e.g. a
            # reflector that never synced — verdicts would be fabricated
            # from an empty cache). Legacy keys (ok/device/workqueues) are
            # kept for existing probes.
            dm = self.plugin.device_manager
            snap = self.plugin.health.snapshot()
            if self._draining:
                snap["state"] = "down"
                snap["components"]["shutdown"] = {
                    "state": "down",
                    "reason": "draining (SIGTERM received)",
                }
            body = {
                "ok": snap["state"] != "down",
                "state": snap["state"],
                "components": snap["components"],
                "device": (
                    {"enabled": False}
                    if dm is None
                    else {
                        "enabled": True,
                        "available": dm.device_available(),
                        "breaker": dm.breaker_state(),
                    }
                ),
                # the sharded front has no local controllers — its
                # workqueues live in the worker processes (per-shard
                # depths come back on the shards component instead)
                "workqueues": (
                    {
                        "throttle": len(self.plugin.throttle_ctr.workqueue),
                        "clusterthrottle": len(
                            self.plugin.cluster_throttle_ctr.workqueue
                        ),
                    }
                    if hasattr(self.plugin, "throttle_ctr")
                    else {}
                ),
            }
            if self.ha is not None:
                body["role"] = self.ha.role
                body["epoch"] = self.ha.epoch.current()
            if self.replica_gate is not None:
                # the gate's component (registered on plugin.health by the
                # CLI) already drives state: a stale replica reports down,
                # so probes stop routing admission traffic here
                body["role"] = "replica"
            h._send(200 if snap["state"] != "down" else 503, body)
        elif h.path == "/v1/throttles":
            h._send(200, [_throttle_to_dict(t) for t in self.listers.throttles.list()])
        elif h.path == "/v1/clusterthrottles":
            h._send(
                200, [_throttle_to_dict(t) for t in self.listers.cluster_throttles.list()]
            )
        elif h.path == "/v1/pods":
            h._send(
                200,
                [
                    {
                        "key": p.key,
                        "nodeName": p.spec.node_name,
                        "phase": p.status.phase,
                        "labels": p.labels,
                    }
                    for p in self.listers.pods.list()
                ],
            )
        else:
            h._send(404, {"error": f"unknown path {h.path}"})

    def _resolve_pod(self, body: dict):
        if "podKey" in body:
            namespace, _, name = body["podKey"].partition("/")
            return self.store.get_pod(namespace, name)
        pod = object_from_dict(body)
        return pod

    _REMOTE_REFUSAL = (
        "this daemon mirrors a remote apiserver (kubeconfig mode); local "
        "object writes would be reverted by the watch stream — mutate the "
        "objects on the cluster instead"
    )

    _REPLICA_READ_PATHS = ("/v1/prefilter", "/v1/prefilter-batch")

    def _forward_to_owner(self, h, method: str, body: Optional[dict]) -> None:
        """Relay a write-surface request to the owner and stream its answer
        back verbatim. The replica adds one hop of latency to writes — the
        price of letting clients stay owner-oblivious; reads never forward."""
        from http.client import HTTPConnection, HTTPException
        from urllib.parse import urlsplit

        split = urlsplit(self.owner_url)
        conn = HTTPConnection(
            split.hostname or "127.0.0.1", split.port or 80, timeout=10.0
        )
        try:
            payload = json.dumps(body or {}).encode()
            conn.request(
                method,
                h.path,
                body=payload if method != "DELETE" else None,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            data = resp.read()
        except (OSError, HTTPException) as e:
            h._send(502, {"error": f"owner unreachable: {e}"})
            return
        finally:
            conn.close()
        h.send_response(resp.status)
        h.send_header(
            "Content-Type", resp.getheader("Content-Type") or "application/json"
        )
        h.send_header("Content-Length", str(len(data)))
        h.send_header("X-KT-Forwarded-By", "replica")
        h.end_headers()
        h.wfile.write(data)

    def _post(self, h) -> None:
        if self.plugin is None:
            h._send(503, {"error": "standby replica; not serving yet"})
            return
        body = h._body()
        if self.replica_gate is not None:
            if h.path not in self._REPLICA_READ_PATHS:
                # every write surface belongs to the owner — forward
                self._forward_to_owner(h, "POST", body)
                return
            if not self.replica_gate.admit():
                # staleness bound breached: refusing beats serving a
                # verdict that may predate a flip — the client retries
                # against the owner (or another replica)
                h._send(
                    503,
                    {
                        "error": "replica stale: replication lag exceeds "
                        "the staleness bound",
                        "maxLagSeconds": self.replica_gate.max_lag_s,
                    },
                )
                return
        if self.remote and h.path in ("/v1/objects", "/v1/bind"):
            h._send(409, {"error": self._REMOTE_REFUSAL})
            return
        if h.path == "/v1/objects":
            kind = body.get("kind", "")
            core = self.clientset.core_v1()
            schedule = self.clientset.schedule_v1alpha1()
            if kind == "Namespace":
                ns = Namespace(
                    name=body["metadata"]["name"],
                    labels=dict(body["metadata"].get("labels") or {}),
                )
                try:
                    core.namespaces().create(ns)
                except ValueError:
                    core.namespaces().update(ns)
                h._send(200, {"applied": f"namespace/{ns.name}"})
                return
            obj = object_from_dict(body)
            try:
                if kind == "Pod":
                    core.pods(obj.namespace).create(obj)
                elif kind == "Throttle":
                    schedule.throttles(obj.namespace).create(obj)
                else:
                    schedule.cluster_throttles().create(obj)
            except ValueError:
                if kind == "Pod":
                    # a manifest re-apply must not clobber server-owned state:
                    # nodeName (set by bind) and phase live on the stored pod
                    with self._pod_write_lock:
                        current = core.pods(obj.namespace).get(obj.name)
                        if not obj.spec.node_name:
                            obj = replace(obj, spec=replace(obj.spec, node_name=current.spec.node_name))
                        if "status" not in body:
                            obj = replace(obj, status=replace(current.status))
                        core.pods(obj.namespace).update(obj)
                elif kind == "Throttle":
                    # the clientset's update has main-resource semantics: the
                    # stored status is preserved (status subresource)
                    schedule.throttles(obj.namespace).update(obj)
                else:
                    schedule.cluster_throttles().update(obj)
            h._send(200, {"applied": getattr(obj, "key", obj.name)})
        elif h.path == "/v1/prefilter":
            pod = self._resolve_pod(body)
            status = self.plugin.pre_filter(pod)
            h._send(
                200,
                {"code": status.code.value, "reasons": list(status.reasons)},
            )
        elif h.path == "/v1/prefilter-batch":
            h._send(200, self.plugin.pre_filter_batch())
        elif h.path == "/v1/tick":
            # fused reconcile+PreFilter sweep over a device mesh;
            # body: {"devices": N?, "shape": [dp, tp]?}
            h._send(
                200,
                self.plugin.full_tick_sharded(
                    body.get("devices"), body.get("shape")
                ),
            )
        elif h.path == "/v1/reserve":
            pod = self._resolve_pod(body)
            status = self.plugin.reserve(pod)
            h._send(200, {"code": status.code.value, "reasons": list(status.reasons)})
        elif h.path == "/v1/unreserve":
            pod = self._resolve_pod(body)
            self.plugin.unreserve(pod)
            h._send(200, {"code": "Success"})
        elif h.path == "/v1/bind":
            namespace, _, name = body["podKey"].partition("/")
            with self._pod_write_lock:
                pod = self.store.get_pod(namespace, name)
                # replace status as a fresh object: dataclasses.replace is
                # shallow and mutating pod.status in place would alias the
                # store's live object outside its lock
                bound = replace(
                    pod,
                    spec=replace(pod.spec, node_name=body.get("nodeName", "node-1")),
                    status=replace(pod.status, phase="Running"),
                )
                self.store.update_pod(bound)
            h._send(200, {"bound": pod.key})
        else:
            h._send(404, {"error": f"unknown path {h.path}"})

    def _delete(self, h) -> None:
        if self.plugin is None:
            h._send(503, {"error": "standby replica; not serving yet"})
            return
        if self.replica_gate is not None:
            self._forward_to_owner(h, "DELETE", None)
            return
        if self.remote:
            h._send(409, {"error": self._REMOTE_REFUSAL})
            return
        parts = h.path.strip("/").split("/")
        if len(parts) < 3 or parts[0] != "v1" or parts[1] != "objects":
            h._send(404, {"error": f"unknown path {h.path}"})
            return
        kind = parts[2]
        key = "/".join(parts[3:])
        if kind == "pods":
            namespace, _, name = key.partition("/")
            self.clientset.core_v1().pods(namespace).delete(name)
        elif kind == "throttles":
            namespace, _, name = key.partition("/")
            self.clientset.schedule_v1alpha1().throttles(namespace).delete(name)
        elif kind == "clusterthrottles":
            self.clientset.schedule_v1alpha1().cluster_throttles().delete(key)
        else:
            h._send(404, {"error": f"unknown kind {kind}"})
            return
        h._send(200, {"deleted": f"{kind}/{key}"})

    # ------------------------------------------------------------ lifecycle

    def set_plugin(self, plugin: KubeThrottler) -> None:
        """Promotion flip: a standby server starts answering the full
        surface. Plain attribute rebinds — handler threads read them per
        request, and each is atomic in CPython (a request races only into
        seeing the old 503-standby behaviour, never a torn state)."""
        self.plugin = plugin
        self.store = plugin.store
        self.clientset = plugin.clientset
        self.listers = plugin.listers
        if self.metrics_registry is None:
            self.metrics_registry = plugin.metrics_registry

    def mark_draining(self) -> None:
        """Flip /readyz to 503 (graceful shutdown step 1) while keeping the
        server up: in-flight and stray requests still get answers during
        the drain window, but probes stop routing new traffic here."""
        self._draining = True

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()  # release the listening socket fd
        if self._thread:
            self._thread.join(timeout=2)
