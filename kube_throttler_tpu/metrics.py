"""Prometheus metrics — the reference's 16 gauge families, same names,
labels, and value semantics.

Mirrors pkg/controllers/{metrics_recorder,throttle_metrics,
clusterthrottle_metrics}.go:

- 8 families per kind: spec threshold / status throttled / status used /
  status calculatedThreshold, each × resourceCounts + resourceRequests;
- labels ``{namespace,name,uid,resource}`` for Throttle,
  ``{name,uid,resource}`` for ClusterThrottle;
- CPU quantities exported as **milli** (``Quantity.MilliValue()``), all
  other resources as whole values rounded up (``Quantity.Value()`` ceils) —
  metrics_recorder.go:38-46;
- nil resourceCounts records 0 (metrics_recorder.go:29-35); nil throttled
  request-flag maps record nothing (metrics_recorder.go:56-59).

Implemented with a minimal in-process registry + text exposition (the
reference registers into kube-scheduler's legacyregistry and serves on its
metrics endpoint; here ``Registry.exposition()`` backs the daemon's
``/metrics``).
"""

from __future__ import annotations

import math

from bisect import bisect_left as _bucket_index  # smallest i: buckets[i] >= v
from fractions import Fraction
from functools import lru_cache
from typing import Dict, Optional, Sequence, Tuple, Union

from .utils.lockorder import guard_attrs, make_lock
from .api.types import ClusterThrottle, IsResourceAmountThrottled, ResourceAmount, Throttle

# Every metric family this process may expose, declared in one place.
# The static analyzer's `registry` checker enforces that any literal name
# passed to gauge_vec/counter_vec/histogram_vec anywhere in the package is
# a member — an inline name that drifts from this set is a family no
# dashboard or alert will ever find. The per-kind families built from
# f-strings in _KindRecorder are enumerated explicitly below. Keep this a
# plain literal (the analyzer reads it from the AST without importing).
METRIC_NAMES = frozenset(
    {
        # _KindRecorder: 8 families x 2 kinds (f"{kind}_{suffix}")
        "throttle_spec_threshold_resourceCounts",
        "throttle_spec_threshold_resourceRequests",
        "throttle_status_throttled_resourceCounts",
        "throttle_status_throttled_resourceRequests",
        "throttle_status_used_resourceCounts",
        "throttle_status_used_resourceRequests",
        "throttle_status_calculated_threshold_resourceCounts",
        "throttle_status_calculated_threshold_resourceRequests",
        "clusterthrottle_spec_threshold_resourceCounts",
        "clusterthrottle_spec_threshold_resourceRequests",
        "clusterthrottle_status_throttled_resourceCounts",
        "clusterthrottle_status_throttled_resourceRequests",
        "clusterthrottle_status_used_resourceCounts",
        "clusterthrottle_status_used_resourceRequests",
        "clusterthrottle_status_calculated_threshold_resourceCounts",
        "clusterthrottle_status_calculated_threshold_resourceRequests",
        # two-lane status pipeline lag histograms (StatusLagMetrics)
        "kube_throttler_status_lag_seconds",
        "kube_throttler_status_flip_lag_seconds",
        # device circuit breaker (register_breaker_metrics)
        "kube_throttler_device_breaker_state",
        # watch fan-out health (register_watch_metrics)
        "kube_throttler_watch_streams_open",
        "kube_throttler_watch_queue_depth",
        "kube_throttler_watch_overflow_total",
        # micro-batched ingest (register_ingest_metrics / engine/ingest.py)
        "kube_throttler_ingest_batch_size",
        "kube_throttler_ingest_events_total",
        # reflector counters (client/transport.py ReflectorMetrics)
        "kube_throttler_reflector_lists_total",
        "kube_throttler_reflector_watches_total",
        "kube_throttler_reflector_events_total",
        "kube_throttler_reflector_gone_total",
        # async status committer (client/transport.py)
        "kube_throttler_remote_status_commit_total",
        # device-fallback counter (plugin/plugin.py)
        "kube_throttler_device_fallback_total",
        # phase-latency tracing histogram (utils/tracing.py)
        "kube_throttler_phase_duration_seconds",
        # crash-safety layer (register_recovery_metrics): snapshot cadence
        # + the last recovery's shape (engine/snapshot.py, engine/recovery.py)
        "kube_throttler_snapshot_total",
        "kube_throttler_snapshot_failures_total",
        "kube_throttler_snapshot_age_seconds",
        "kube_throttler_recovery_duration_seconds",
        "kube_throttler_recovery_journal_lines_replayed",
        "kube_throttler_recovery_divergence_total",
        # gang admission (register_gang_metrics / engine/gang.py): group
        # ledger population + outcomes, and the batched group-feasibility
        # kernel's dispatch latency (plugin.pre_filter_gang observes it)
        "kube_throttler_gang_groups_pending",
        "kube_throttler_gang_groups_admitted_total",
        "kube_throttler_gang_groups_rolled_back_total",
        "kube_throttler_gang_check_duration_seconds",
        # active/standby HA (register_ha_metrics / engine/replication.py)
        "kube_throttler_leader_state",
        "kube_throttler_failover_duration_seconds",
        "kube_throttler_replication_lag_bytes",
        "kube_throttler_replication_lag_events",
        "kube_throttler_stale_epoch_rejections_total",
        # scenario engine + SLO gates (register_scenario_metrics /
        # scenarios/engine.py): per-scenario outcome families a scenario
        # soak or CI gate dashboard alerts on
        "kube_throttler_scenario_ops_total",
        "kube_throttler_scenario_faults_total",
        "kube_throttler_scenario_slo_gate",
        "kube_throttler_scenario_flip_p99_seconds",
        "kube_throttler_scenario_recovery_seconds",
        # multiprocess keyspace sharding (register_shard_metrics /
        # sharding/front.py): per-shard ingest + liveness, the
        # scatter-gather fan-out latency, and the failure counters the
        # degraded-mode runbook watches
        "kube_throttler_shard_ingest_events_total",
        "kube_throttler_shard_up",
        "kube_throttler_shard_scatter_duration_seconds",
        "kube_throttler_shard_route_misses_total",
        "kube_throttler_shard_two_phase_aborts_total",
        # live elastic resharding (register_reshard_metrics /
        # sharding/reshard.py): ranges in flight, handoff volume, the
        # cutover-latency histogram the flip-SLO runbook reads, and the
        # abort counter the kill-mid-handoff matrix drives
        "kube_throttler_reshard_ranges_moving",
        "kube_throttler_reshard_handoff_bytes_total",
        "kube_throttler_reshard_handoff_events_total",
        "kube_throttler_reshard_cutover_duration_seconds",
        "kube_throttler_reshard_aborted_total",
        # adversarial scenario hunt (register_hunt_metrics /
        # scenarios/hunt/loop.py): search-loop progress a nightly soak
        # dashboard watches — mutants evaluated, coverage-map size, corpus
        # population, gate-failing mutants found, and shrink work
        "kube_throttler_hunt_iterations_total",
        "kube_throttler_hunt_coverage_size",
        "kube_throttler_hunt_corpus_size",
        "kube_throttler_hunt_findings_total",
        "kube_throttler_hunt_shrink_steps_total",
        # preemption & policy engine (register_preempt_metrics /
        # policy/preempt.py): cycle/victim counters, the no-progress
        # outcomes (infeasible), the crash/live rollback counter, the
        # evicted-then-readmitted churn counter the preemption-storm
        # scenario gates on, and the victim-selection latency histogram
        "kube_throttler_preempt_cycles_total",
        "kube_throttler_preempt_victims_total",
        "kube_throttler_preempt_infeasible_total",
        "kube_throttler_preempt_rolled_back_total",
        "kube_throttler_preempt_readmitted_total",
        "kube_throttler_preempt_select_duration_seconds",
        # columnar arena store (register_store_metrics / engine/columnar.py):
        # slot population/recycling, intern-pool growth, and how often the
        # lazy edge materializes full API objects
        "kube_throttler_store_arena_slots_live",
        "kube_throttler_store_arena_slots_recycled_total",
        "kube_throttler_store_intern_pool_size",
        "kube_throttler_store_materializations_total",
        # cross-host shard fleet (register_net_metrics / sharding/ipc.py
        # TcpShardClient): reconnect churn, RPCs that outran their
        # deadline budget, send-queue depth while partitioned, and
        # cumulative partition downtime — the partition runbook's four
        # signals (docs/robustness.md "Cross-host fleet")
        "kube_throttler_net_reconnects_total",
        "kube_throttler_net_rpc_deadline_exceeded_total",
        "kube_throttler_net_send_queue_depth",
        "kube_throttler_net_partition_seconds",
        # zero-copy shm event plane (register_shm_metrics /
        # sharding/shmring.py): per-shard ring occupancy, wrap and
        # counted-backpressure totals, frames pushed, and how many
        # batches fell back to the pickle socketpair — plus the worker
        # side's ingest counters (docs/PERFORMANCE.md "Zero-copy event
        # plane")
        "kube_throttler_shm_ring_depth",
        "kube_throttler_shm_ring_wraps_total",
        "kube_throttler_shm_backpressure_waits_total",
        "kube_throttler_shm_frames_total",
        "kube_throttler_shm_fallback_batches_total",
        "kube_throttler_shm_ingest_frames_total",
        "kube_throttler_shm_ingest_events_total",
        # interned-verdict cache (register_verdict_cache_metrics /
        # engine/verdictcache.py): probe outcomes, live entry count, and
        # explicit invalidation sweeps — hit-rate is the serving tier's
        # primary health signal (docs/PERFORMANCE.md "Verdict cache")
        "kube_throttler_verdict_cache_hits_total",
        "kube_throttler_verdict_cache_misses_total",
        "kube_throttler_verdict_cache_entries",
        "kube_throttler_verdict_cache_invalidations_total",
        # read-replica admission tier (register_replica_metrics /
        # engine/replication.py ReplicaGate): verdicts served by role and
        # requests refused for breaching the staleness bound — the
        # replica-lag SLO's two signals
        "kube_throttler_replica_verdicts_total",
        "kube_throttler_replica_lag_events_total",
        "kube_throttler_replica_lag_seconds",
        # rolling-upgrade safety (register_build_metrics / version.py):
        # this build's identity + per-shard negotiated protocol rows,
        # the typed incompatible-major refusal counter, and the
        # crash-loop guard's current per-shard restart backoff
        "kube_throttler_build_info",
        "kube_throttler_shard_version_mismatch_total",
        "kube_throttler_shard_restart_backoff_seconds",
    }
)


@guard_attrs
class GaugeVec:
    GUARDED_BY = {"_values": "self._lock"}

    def __init__(self, name: str, help_text: str, label_names: Sequence[str]):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = make_lock(f"metrics.family.{name}")
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, labels: Dict[str, str], value: float) -> None:
        self.set_key(tuple(labels[n] for n in self.label_names), value)

    def set_key(self, key: Tuple[str, ...], value: float) -> None:
        """Hot-path setter for a precomputed label-value tuple (order must
        match ``label_names``); skips the per-call dict→tuple rebuild."""
        with self._lock:
            self._values[key] = float(value)

    def get(self, labels: Dict[str, str]) -> Optional[float]:
        key = tuple(labels[n] for n in self.label_names)
        with self._lock:
            return self._values.get(key)

    def collect(self) -> Dict[Tuple[str, ...], float]:
        """Raw values snapshot. Families fed by deferred recorders are
        stale until ``Registry.flush()`` (or ``exposition()``) runs."""
        with self._lock:
            return dict(self._values)


class CounterVec(GaugeVec):
    """Monotonic counter family (exposition TYPE counter; use the
    _total naming convention). ``inc`` is atomic under the family lock."""

    def inc(self, labels: Dict[str, str], delta: float = 1.0) -> None:
        key = tuple(labels[n] for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta


@guard_attrs
class HistogramVec:
    """Prometheus histogram family: cumulative buckets + _sum/_count per
    label set. Backs the per-phase latency tracing (SURVEY §5's TPU-native
    tracing equivalent — the reference has only klog levels)."""

    GUARDED_BY = {"_series": "self._lock"}

    # le boundaries tuned for scheduling-phase latencies: 10µs .. 10s
    DEFAULT_BUCKETS = (
        1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
        1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    )

    def __init__(
        self,
        name: str,
        help_text: str,
        label_names: Sequence[str],
        buckets: Optional[Sequence[float]] = None,
    ):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self.buckets = tuple(buckets) if buckets is not None else self.DEFAULT_BUCKETS
        self._lock = make_lock(f"metrics.family.{name}")
        # key -> (bucket counts, sum, count)
        self._series: Dict[Tuple[str, ...], list] = {}

    def observe(self, labels: Dict[str, str], value: float) -> None:
        self.observe_key(tuple(labels[n] for n in self.label_names), value)

    def observe_key(self, key: Tuple[str, ...], value: float) -> None:
        """Hot-path observe for a precomputed label tuple. Buckets store
        RAW (non-cumulative) counts — one bisect instead of a walk over
        every boundary; collect() cumsums at scrape time (observes
        outnumber scrapes by ~1e6 on the serving path)."""
        i = _bucket_index(self.buckets, value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._series[key] = s
            s[0][i] += 1
            s[1] += value
            s[2] += 1

    def snapshot(self, labels: Dict[str, str]) -> Optional[Tuple[float, int]]:
        """(sum, count) for one label set, or None."""
        key = tuple(labels[n] for n in self.label_names)
        with self._lock:
            s = self._series.get(key)
            return (s[1], s[2]) if s else None

    def collect(self) -> Dict[Tuple[str, ...], tuple]:
        """Series snapshot with CUMULATIVE bucket counts (the prometheus
        exposition shape; storage is raw per-bucket — see observe_key)."""
        out = {}
        with self._lock:
            for k, s in self._series.items():
                cum, running = [], 0
                for c in s[0][: len(self.buckets)]:
                    running += c
                    cum.append(running)
                out[k] = (cum, s[1], s[2])
        return out


@guard_attrs
class Registry:
    GUARDED_BY = {
        "_gauges": "self._lock",
        "_counters": "self._lock",
        "_histograms": "self._lock",
        "_pre_expose": "self._lock",
    }

    def __init__(self) -> None:
        self._lock = make_lock("metrics.registry")
        self._gauges: Dict[str, GaugeVec] = {}
        self._counters: Dict[str, CounterVec] = {}
        self._histograms: Dict[str, HistogramVec] = {}
        # scrape-time collectors (deferred recorders flush here): gauges
        # only need to be correct when read, so hot paths may buffer
        self._pre_expose: list = []

    def register_pre_expose(self, fn) -> None:
        with self._lock:
            self._pre_expose.append(fn)

    def flush(self) -> None:
        """Run the deferred recorders' flush hooks without rendering.

        Gauge families fed by deferred recorders (ThrottleMetricsRecorder
        et al. buffer per-label snapshots and flush at scrape) are only
        current after a flush: a consumer reading ``GaugeVec.collect()``
        directly — tests, in-process introspection — must call this first
        (``exposition()`` does it implicitly)."""
        with self._lock:
            hooks = list(self._pre_expose)
        for fn in hooks:
            fn()

    def gauge_vec(self, name: str, help_text: str, label_names: Sequence[str]) -> GaugeVec:
        with self._lock:
            if name in self._gauges:
                return self._gauges[name]
            g = GaugeVec(name, help_text, label_names)
            self._gauges[name] = g
            return g

    def counter_vec(self, name: str, help_text: str, label_names: Sequence[str]) -> CounterVec:
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            c = CounterVec(name, help_text, label_names)
            self._counters[name] = c
            return c

    def histogram_vec(
        self,
        name: str,
        help_text: str,
        label_names: Sequence[str],
        buckets: Optional[Sequence[float]] = None,
    ) -> HistogramVec:
        with self._lock:
            if name in self._histograms:
                return self._histograms[name]
            h = HistogramVec(name, help_text, label_names, buckets)
            self._histograms[name] = h
            return h

    def family_totals(self) -> Dict[str, Tuple[int, float]]:
        """``family name → (series count, value sum)`` across gauges,
        counters, and histograms (histograms contribute their observation
        counts). Flushes deferred recorders first so scrape-time families
        are current. This is the scenario hunt's metric-coverage signal:
        comparing two snapshots tells you which families a run *touched*
        without parsing exposition text."""
        self.flush()
        out: Dict[str, Tuple[int, float]] = {}
        with self._lock:
            gauges = list(self._gauges.values())
            counters = list(self._counters.values())
            histograms = list(self._histograms.values())
        for fam in gauges + counters:
            values = fam.collect()
            if values:
                out[fam.name] = (len(values), float(sum(values.values())))
        for h in histograms:
            series = h.collect()
            if series:
                out[h.name] = (
                    len(series),
                    float(sum(count for _, _, count in series.values())),
                )
        return out

    def exposition(self) -> str:
        """Prometheus text format (flushes deferred recorders first)."""
        self.flush()

        def esc(v: str) -> str:
            # label-value escaping per the exposition format: \ " and newline
            return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

        def fmt(value: float) -> str:
            return str(int(value)) if value == int(value) else str(value)

        lines = []
        with self._lock:
            gauges = list(self._gauges.values())
            counters = list(self._counters.values())
            histograms = list(self._histograms.values())
        for family, ptype in [(gauges, "gauge"), (counters, "counter")]:
            for g in family:
                lines.append(f"# HELP {g.name} {g.help}")
                lines.append(f"# TYPE {g.name} {ptype}")
                for key, value in sorted(g.collect().items()):
                    labels = ",".join(
                        f'{n}="{esc(v)}"' for n, v in zip(g.label_names, key)
                    )
                    brace = f"{{{labels}}}" if labels else ""
                    lines.append(f"{g.name}{brace} {fmt(value)}")
        for h in histograms:
            lines.append(f"# HELP {h.name} {h.help}")
            lines.append(f"# TYPE {h.name} histogram")
            for key, (counts, total, count) in sorted(h.collect().items()):
                base = [f'{n}="{esc(v)}"' for n, v in zip(h.label_names, key)]
                for le, c in zip(h.buckets, counts):
                    labels = ",".join(base + [f'le="{le}"'])
                    lines.append(f"{h.name}_bucket{{{labels}}} {c}")
                labels = ",".join(base + ['le="+Inf"'])
                lines.append(f"{h.name}_bucket{{{labels}}} {count}")
                sep = ",".join(base)
                brace = f"{{{sep}}}" if sep else ""
                lines.append(f"{h.name}_sum{brace} {total}")
                lines.append(f"{h.name}_count{brace} {count}")
        return "\n".join(lines) + "\n"


@lru_cache(maxsize=8192)
def _quantity_metric_value(resource: str, q: Fraction) -> float:
    if resource == "cpu":
        # MilliValue: ceil to integer milli (metrics_recorder.go:40-41)
        return float(math.ceil(q * 1000))
    # Value(): ceil to integer units
    return float(math.ceil(q))


@guard_attrs
class _KindRecorder:
    """One kind's 8 gauge families."""

    GUARDED_BY = {"_pending": "self._pending_lock"}

    def __init__(self, kind_prefix: str, label_names: Sequence[str], registry: Registry):
        mk = registry.gauge_vec
        k = kind_prefix
        assert tuple(label_names)[-1] == "resource"  # set_key relies on this order
        self._base_names = tuple(label_names)[:-1]
        # deferred-record buffer: latest object per label set, flushed by
        # the registry's pre-exposition hook (see record())
        self._pending: Dict[Tuple[str, ...], object] = {}
        self._pending_lock = make_lock(f"metrics.pending.{kind_prefix}")
        self._flush_lock = make_lock(f"metrics.flush.{kind_prefix}")
        registry.register_pre_expose(self._flush)
        self.spec_counts = mk(
            f"{k}_spec_threshold_resourceCounts",
            f"threshold on specific resourceCounts of the {k}",
            label_names,
        )
        self.spec_requests = mk(
            f"{k}_spec_threshold_resourceRequests",
            f"threshold on specific resourceRequests of the {k}",
            label_names,
        )
        self.throttled_counts = mk(
            f"{k}_status_throttled_resourceCounts",
            f"resourceCounts of the {k} is throttled or not on specific resource (1=throttled, 0=not throttled)",
            label_names,
        )
        self.throttled_requests = mk(
            f"{k}_status_throttled_resourceRequests",
            f"resourceRequests of the {k} is throttled or not on specific resource (1=throttled, 0=not throttled)",
            label_names,
        )
        self.used_counts = mk(
            f"{k}_status_used_resourceCounts",
            f"used resource counts of the {k}",
            label_names,
        )
        self.used_requests = mk(
            f"{k}_status_used_resourceRequests",
            f"used amount of resource requests of the {k}",
            label_names,
        )
        self.calculated_counts = mk(
            f"{k}_status_calculated_threshold_resourceCounts",
            f"calculated threshold on specific resourceCounts of the {k}",
            label_names,
        )
        self.calculated_requests = mk(
            f"{k}_status_calculated_threshold_resourceRequests",
            f"calculated threshold on specific resourceRequests of the {k}",
            label_names,
        )

    def _record_counts(self, gauge: GaugeVec, base: Tuple[str, ...], counts: Optional[int]) -> None:
        gauge.set_key(base + ("pod",), 0.0 if counts is None else float(counts))

    def _record_requests(self, gauge: GaugeVec, base: Tuple[str, ...], amount: ResourceAmount) -> None:
        for resource, q in (amount.resource_requests or {}).items():
            gauge.set_key(base + (resource,), _quantity_metric_value(resource, q))

    def _record_flags(self, base: Tuple[str, ...], flags: IsResourceAmountThrottled) -> None:
        self.throttled_counts.set_key(
            base + ("pod",), 1.0 if flags.resource_counts_pod else 0.0
        )
        for resource, throttled in (flags.resource_requests or {}).items():
            self.throttled_requests.set_key(
                base + (resource,), 1.0 if throttled else 0.0
            )

    def record(self, labels: Dict[str, str], thr: Union[Throttle, ClusterThrottle]) -> None:
        # DEFERRED: ~7-15 gauge writes per status update would land on the
        # reconcile hot path (~23µs/key — measured as ~25% of the per-key
        # drain cost under cfg5 max rate). Gauges only need to be correct
        # at scrape time, so record() just buffers the latest object per
        # label set and the Registry's pre-exposition hook flushes.
        base = tuple(labels[n] for n in self._base_names)
        with self._pending_lock:
            self._pending[base] = thr

    def _flush(self) -> None:
        # two locks: _pending_lock guards only the buffer swap so the hot
        # record() path never waits behind gauge writes (a post-sweep flush
        # can be T×~7 set_keys), while _flush_lock serializes whole flushes
        # so two concurrent scrapes cannot interleave writes and pin gauges
        # at an older snapshot
        with self._flush_lock:
            with self._pending_lock:
                items = list(self._pending.items())
                self._pending.clear()
            self._write_items(items)

    def _write_items(self, items) -> None:
        for base, thr in items:
            self._record_counts(self.spec_counts, base, thr.spec.threshold.resource_counts)
            self._record_requests(self.spec_requests, base, thr.spec.threshold)
            self._record_flags(base, thr.status.throttled)
            self._record_counts(self.used_counts, base, thr.status.used.resource_counts)
            self._record_requests(self.used_requests, base, thr.status.used)
            calc = thr.status.calculated_threshold.threshold
            self._record_counts(self.calculated_counts, base, calc.resource_counts)
            self._record_requests(self.calculated_requests, base, calc)


class StatusLagMetrics:
    """The two-lane status pipeline's latency histograms.

    - ``kube_throttler_status_lag_seconds`` — event → publication for EVERY
      status write (total lag: the time from the store/watch event that
      made a key dirty to its status being visible — written to the local
      store, or the PUT completing on the wire);
    - ``kube_throttler_status_flip_lag_seconds`` — the same lag restricted
      to FLIP publications: statuses whose ``throttled`` flags or
      ``calculatedThreshold`` changed. Flips are the only status bits that
      change admission verdicts, so their tail is the one that bounds how
      stale scheduling decisions can be (the reference publishes per-key
      inside reconcile, throttle_controller.go:157-173, so its flip lag IS
      its total lag; ours diverge because refreshes batch).

    ``path`` distinguishes the local batched store commit (``local``) from
    the remote async committer's PUT completion (``remote``)."""

    # status publication spans ~100µs (local batch write) to multi-second
    # backlog tails; anchor the buckets around the <150ms flip target
    BUCKETS = (
        1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
        0.1, 0.15, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    )

    def __init__(self, registry: Registry, path: str):
        self._path = path
        self.total = registry.histogram_vec(
            "kube_throttler_status_lag_seconds",
            "event to status-publication lag (all status writes)",
            ["kind", "path"],
            buckets=self.BUCKETS,
        )
        self.flip = registry.histogram_vec(
            "kube_throttler_status_flip_lag_seconds",
            "event to status-publication lag for throttled/calculatedThreshold flips",
            ["kind", "path"],
            buckets=self.BUCKETS,
        )

    def observe(self, kind: str, lag_s: float, flip: bool) -> None:
        key = (kind, self._path)
        self.total.observe_key(key, lag_s)
        if flip:
            self.flip.observe_key(key, lag_s)


_BREAKER_STATE_VALUES = {"closed": 0.0, "open": 1.0, "half-open": 2.0}


def register_breaker_metrics(registry: Registry, device_manager) -> GaugeVec:
    """Device circuit-breaker state gauge (0=closed, 1=open, 2=half-open),
    refreshed at scrape time from the manager's state machine — the
    operator-facing answer to "is admission serving device- or host-side
    right now, and is a probe pending?"."""
    gauge = registry.gauge_vec(
        "kube_throttler_device_breaker_state",
        "device circuit breaker state (0=closed, 1=open, 2=half-open)",
        [],
    )
    registry.register_pre_expose(
        lambda: gauge.set_key(
            (), _BREAKER_STATE_VALUES.get(device_manager.breaker_state(), 0.0)
        )
    )
    return gauge


def register_recovery_metrics(
    registry: Registry, snapshot_manager=None, recovery_manager=None
) -> None:
    """Crash-safety observability: snapshot cadence (age/written/failed)
    from the SnapshotManager and the last recovery's duration, replayed
    journal lines, and plane divergences from the RecoveryManager. All fed
    at scrape time from the managers' single-writer stats — the snapshot
    and recovery paths never touch a metric family on their own."""
    snap_total = registry.counter_vec(
        "kube_throttler_snapshot_total", "snapshots written by this process", []
    )
    snap_failed = registry.counter_vec(
        "kube_throttler_snapshot_failures_total",
        "snapshot writes that failed (journal remains the recovery source)",
        [],
    )
    snap_age = registry.gauge_vec(
        "kube_throttler_snapshot_age_seconds",
        "seconds since the last snapshot written by this process "
        "(-1 before the first one)",
        [],
    )
    rec_duration = registry.gauge_vec(
        "kube_throttler_recovery_duration_seconds",
        "wall time of the startup recovery (snapshot restore + journal replay)",
        [],
    )
    rec_lines = registry.gauge_vec(
        "kube_throttler_recovery_journal_lines_replayed",
        "journal events replayed by the startup recovery",
        [],
    )
    rec_divergence = registry.counter_vec(
        "kube_throttler_recovery_divergence_total",
        "published-plane vs restored-status mismatches found (and repaired) "
        "by the recovery reconcile",
        [],
    )

    def flush() -> None:
        if snapshot_manager is not None:
            snap_total.set_key((), float(snapshot_manager.snapshots_written))
            snap_failed.set_key((), float(snapshot_manager.snapshot_failures))
            age = snapshot_manager.snapshot_age_seconds()
            snap_age.set_key((), -1.0 if age is None else age)
        if recovery_manager is not None:
            r = recovery_manager.report
            rec_duration.set_key((), r.duration_s)
            rec_lines.set_key((), float(r.journal_lines_replayed))
            rec_divergence.set_key((), float(r.divergences))

    registry.register_pre_expose(flush)


def register_gang_metrics(registry: Registry, ledger) -> "HistogramVec":
    """Gang-admission observability (engine/gang.py): ledger population
    (groups reserved but not yet fully admitted) plus the all-or-nothing
    outcome counters, sampled from the ledger at scrape time. Returns the
    group-feasibility latency histogram the plugin observes per
    ``pre_filter_gang`` dispatch (inline — scrape-time sampling would miss
    the distribution)."""
    pending_g = registry.gauge_vec(
        "kube_throttler_gang_groups_pending",
        "groups holding an all-or-nothing reserve, not yet fully admitted",
        [],
    )
    admitted_c = registry.counter_vec(
        "kube_throttler_gang_groups_admitted_total",
        "groups whose every member was observed admitted",
        [],
    )
    rolled_c = registry.counter_vec(
        "kube_throttler_gang_groups_rolled_back_total",
        "groups rolled back (member failure, deletion, TTL expiry, or an "
        "explicit unreserve) — all member reservations released together",
        [],
    )
    check_h = registry.histogram_vec(
        "kube_throttler_gang_check_duration_seconds",
        "batched group-feasibility evaluation latency (one dispatch per "
        "scheduling tick, both kinds fused)",
        [],
        buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
    )

    def flush() -> None:
        pending_g.set_key((), float(ledger.pending_groups()))
        admitted_c.set_key((), float(ledger.groups_admitted_total))
        rolled_c.set_key((), float(ledger.groups_rolled_back_total))

    registry.register_pre_expose(flush)
    return check_h


def register_preempt_metrics(registry: Registry, coordinator) -> "HistogramVec":
    """Preemption & policy observability (policy/preempt.py): cycle and
    victim counters sampled from the coordinator at scrape time, plus the
    victim-selection latency histogram the coordinator observes inline
    per cycle (returned, like the gang check histogram). The readmitted
    counter is the victim-churn signal the preemption-storm scenario's
    no-thrash SLO gate reads."""
    cycles_c = registry.counter_vec(
        "kube_throttler_preempt_cycles_total",
        "preemption cycles that evicted at least one victim",
        [],
    )
    victims_c = registry.counter_vec(
        "kube_throttler_preempt_victims_total",
        "victim pods evicted (whole gangs count every member)",
        [],
    )
    infeasible_c = registry.counter_vec(
        "kube_throttler_preempt_infeasible_total",
        "cycles that evicted NOTHING because no victim set could admit "
        "the group (member-exceeds, no eligible victims, or insufficient "
        "eligible capacity)",
        [],
    )
    rolled_c = registry.counter_vec(
        "kube_throttler_preempt_rolled_back_total",
        "evictions rolled back to zero victims (live mid-eviction failure; "
        "crash rollbacks surface via the recovery report instead)",
        [],
    )
    readmitted_c = registry.counter_vec(
        "kube_throttler_preempt_readmitted_total",
        "evicted pods readmitted within the churn window — the thrash "
        "signal the preemption-storm scenario bounds",
        [],
    )
    select_h = registry.histogram_vec(
        "kube_throttler_preempt_select_duration_seconds",
        "deficit derivation + candidate gathering + ranked victim "
        "selection latency per cycle (batched kernel or host oracle)",
        [],
        buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
    )

    def flush() -> None:
        cycles_c.set_key((), float(coordinator.cycles_total))
        victims_c.set_key((), float(coordinator.victims_total))
        infeasible_c.set_key((), float(coordinator.infeasible_total))
        rolled_c.set_key((), float(coordinator.rolled_back_total))
        readmitted_c.set_key((), float(coordinator.readmitted_total))

    registry.register_pre_expose(flush)
    return select_h


def register_ha_metrics(registry: Registry, coordinator) -> None:
    """Active/standby HA observability (engine/replication.py), fed at
    scrape time from the coordinator: role (1=leader, 0=standby), the last
    failover's duration, replication lag (bytes behind the leader's
    journal position + events applied so far), and the stale-epoch write
    rejections the fencing gates have refused — the counter that must stay
    at ZERO on a healthy pair and moves exactly when a deposed leader
    tries to write."""
    leader_g = registry.gauge_vec(
        "kube_throttler_leader_state",
        "replica role (1=leader, 0=standby)",
        [],
    )
    failover_g = registry.gauge_vec(
        "kube_throttler_failover_duration_seconds",
        "tail fast-forward + epoch bump time of the last promotion "
        "(-1 before any failover)",
        [],
    )
    lag_bytes_g = registry.gauge_vec(
        "kube_throttler_replication_lag_bytes",
        "journal bytes the standby still has to stream (0 on the leader)",
        [],
    )
    lag_events_g = registry.gauge_vec(
        "kube_throttler_replication_lag_events",
        "events applied from the replication stream so far "
        "(0 on a never-standby leader)",
        [],
    )
    stale_c = registry.counter_vec(
        "kube_throttler_stale_epoch_rejections_total",
        "writes refused because this replica's fencing epoch went stale "
        "(journal appends + snapshot cuts)",
        [],
    )

    def flush() -> None:
        leader_g.set_key((), 1.0 if coordinator.role == "leader" else 0.0)
        failover_g.set_key(
            (),
            -1.0
            if coordinator.failover_duration_s is None
            else coordinator.failover_duration_s,
        )
        rep = coordinator.replicator
        lag_bytes_g.set_key((), float(rep.lag_bytes()) if rep is not None else 0.0)
        lag_events_g.set_key(
            (), float(rep.events_applied) if rep is not None else 0.0
        )
        stale_c.set_key((), float(coordinator.stale_epoch_rejections()))

    registry.register_pre_expose(flush)


def register_scenario_metrics(registry: Registry) -> Dict[str, object]:
    """Scenario-engine outcome families (scenarios/engine.py): ops
    replayed and faults fired per scenario, each SLO gate's last verdict
    (1 = pass), and the headline gate measurements (flip p99, post-restart
    recovery). Written inline per run — a scenario run IS the scrape."""
    return {
        "ops": registry.counter_vec(
            "kube_throttler_scenario_ops_total",
            "trace ops replayed against the apiserver per scenario",
            ["scenario"],
        ),
        "faults": registry.counter_vec(
            "kube_throttler_scenario_faults_total",
            "fault-plan firings per scenario and site (the schedule's witness)",
            ["scenario", "site"],
        ),
        "gate": registry.gauge_vec(
            "kube_throttler_scenario_slo_gate",
            "last SLO gate verdict per scenario (1=pass, 0=fail)",
            ["scenario", "gate"],
        ),
        "flip_p99": registry.gauge_vec(
            "kube_throttler_scenario_flip_p99_seconds",
            "crossing-anchored flip-publication p99 of the last run",
            ["scenario"],
        ),
        "recovery": registry.gauge_vec(
            "kube_throttler_scenario_recovery_seconds",
            "worst post-restart time to the next landed status publication",
            ["scenario"],
        ),
    }


def register_hunt_metrics(registry: Registry) -> Dict[str, object]:
    """Adversarial-hunt progress families (scenarios/hunt/loop.py): the
    nightly soak's dashboard surface. Iterations/findings/shrink-steps are
    counters (monotone across a soak process); coverage and corpus size
    are gauges sampled by the loop after every iteration."""
    return {
        "iterations": registry.counter_vec(
            "kube_throttler_hunt_iterations_total",
            "mutants generated and evaluated by the hunt loop",
            [],
        ),
        "coverage": registry.gauge_vec(
            "kube_throttler_hunt_coverage_size",
            "distinct coverage keys observed (fault sites × hit buckets, "
            "metric families touched, health transitions, gate outcomes)",
            [],
        ),
        "corpus": registry.gauge_vec(
            "kube_throttler_hunt_corpus_size",
            "programs retained in the novelty-weighted hunt corpus",
            [],
        ),
        "findings": registry.counter_vec(
            "kube_throttler_hunt_findings_total",
            "gate-failing mutants discovered (pre-shrink)",
            [],
        ),
        "shrink_steps": registry.counter_vec(
            "kube_throttler_hunt_shrink_steps_total",
            "accepted shrink transformations across all findings",
            [],
        ),
    }


def register_shard_metrics(registry: Registry, front) -> Dict[str, object]:
    """Multiprocess-sharding observability (sharding/front.py): per-shard
    ingest throughput and liveness sampled at scrape time from the shard
    handles, plus the inline-observed scatter-gather fan-out latency and
    the two failure counters (route misses to a down shard, two-phase
    reserve aborts) the degraded-mode runbook alerts on."""
    ingest_c = registry.counter_vec(
        "kube_throttler_shard_ingest_events_total",
        "events routed to and accepted by each shard's ingest pipeline",
        ["shard"],
    )
    up_g = registry.gauge_vec(
        "kube_throttler_shard_up",
        "shard worker liveness (1=alive, 0=down) as the front sees it",
        ["shard"],
    )
    scatter_h = registry.histogram_vec(
        "kube_throttler_shard_scatter_duration_seconds",
        "scatter-gather fan-out latency per RPC op (request fan-out to "
        "last shard answer, merge excluded)",
        ["op"],
    )
    misses_c = registry.counter_vec(
        "kube_throttler_shard_route_misses_total",
        "events that could not be delivered because the owning shard was "
        "down (repaired by the restart resync)",
        [],
    )
    aborts_c = registry.counter_vec(
        "kube_throttler_shard_two_phase_aborts_total",
        "two-phase reserves aborted by the front after a prepare failure",
        [],
    )

    def flush() -> None:
        for sid in range(front.n_shards):
            handle = front.shards.get(sid)
            alive = handle is not None and handle.alive
            up_g.set_key((str(sid),), 1.0 if alive else 0.0)
            if handle is not None:
                ingest_c.set_key((str(sid),), float(handle.events_sent))

    registry.register_pre_expose(flush)
    return {"scatter": scatter_h, "aborts": aborts_c, "misses": misses_c}


def register_net_metrics(registry: Registry, front) -> Dict[str, object]:
    """Cross-host fleet transport observability (sharding/ipc.py
    ``TcpShardClient``), sampled at scrape time from the shard handles.
    Socketpair/local handles report zeros for the TCP-only families, so
    one dashboard covers mixed fleets. The four signals the partition
    runbook watches: reconnect churn (a flapping link keeps the counter
    moving), deadline-exceeded RPCs (a slow link that has not yet died),
    send-queue depth (events parked behind a partition — shed pressure),
    and cumulative partition downtime per shard."""
    reconnects_c = registry.counter_vec(
        "kube_throttler_net_reconnects_total",
        "shard transport re-establishments after a connection loss",
        ["shard"],
    )
    deadline_c = registry.counter_vec(
        "kube_throttler_net_rpc_deadline_exceeded_total",
        "shard RPCs abandoned because their per-op deadline budget "
        "(--shard-rpc-deadline) elapsed",
        ["shard"],
    )
    depth_g = registry.gauge_vec(
        "kube_throttler_net_send_queue_depth",
        "events queued at the front awaiting transport to the shard "
        "(bounded; overflow sheds pod upserts and marks dirty)",
        ["shard"],
    )
    partition_g = registry.gauge_vec(
        "kube_throttler_net_partition_seconds",
        "cumulative seconds the shard's primary connection has been "
        "down, including the outage in progress",
        ["shard"],
    )

    def flush() -> None:
        for sid in range(front.n_shards):
            handle = front.shards.get(sid)
            if handle is None:
                continue
            key = (str(sid),)
            reconnects_c.set_key(key, float(getattr(handle, "reconnects", 0)))
            deadline_c.set_key(
                key, float(getattr(handle, "deadline_exceeded", 0))
            )
            depth_g.set_key(key, float(handle.pending_events()))
            outage = getattr(handle, "outage_seconds", None)
            partition_g.set_key(key, outage() if outage is not None else 0.0)

    registry.register_pre_expose(flush)
    return {
        "reconnects": reconnects_c,
        "deadline_exceeded": deadline_c,
        "queue_depth": depth_g,
        "partition_seconds": partition_g,
    }


def register_shm_metrics(registry: Registry, front) -> Dict[str, object]:
    """Zero-copy event-plane observability (sharding/shmring.py),
    sampled at scrape time from each shard handle's ``shm_lane``.
    Handles without a lane (TCP fleets, ``KT_SHM_RING=0``, masked
    ``evt-shm`` capability) report zeros, so one dashboard covers mixed
    fleets. The signals the ring runbook watches: occupancy (a reader
    that stopped draining), counted backpressure (the writer waited for
    slots — never a silent drop), wraps (normal steady-state churn),
    and fallback batches (events that rode the pickle socketpair
    instead — nonzero means the fast path is off for that shard)."""
    depth_g = registry.gauge_vec(
        "kube_throttler_shm_ring_depth",
        "event frames committed to the shard's shm ring, not yet "
        "consumed by the worker",
        ["shard"],
    )
    wraps_c = registry.counter_vec(
        "kube_throttler_shm_ring_wraps_total",
        "arena wraparounds on the shard's shm ring",
        ["shard"],
    )
    backpressure_c = registry.counter_vec(
        "kube_throttler_shm_backpressure_waits_total",
        "writer waits for ring capacity (counted backpressure; "
        "non-sheddable ops are never silently dropped)",
        ["shard"],
    )
    frames_c = registry.counter_vec(
        "kube_throttler_shm_frames_total",
        "columnar event frames pushed to the shard over shared memory",
        ["shard"],
    )
    fallback_c = registry.counter_vec(
        "kube_throttler_shm_fallback_batches_total",
        "event batches sent over the pickle socketpair while an shm "
        "lane existed (capability masked, barrier pending, or lane dead)",
        ["shard"],
    )

    def flush() -> None:
        for sid in range(front.n_shards):
            handle = front.shards.get(sid)
            if handle is None:
                continue
            key = (str(sid),)
            lane = getattr(handle, "shm_lane", None)
            stats = lane.stats() if lane is not None else {}
            depth_g.set_key(key, float(stats.get("depth", 0)))
            wraps_c.set_key(key, float(stats.get("wraps", 0)))
            backpressure_c.set_key(key, float(stats.get("backpressure", 0)))
            frames_c.set_key(key, float(stats.get("frames", 0)))
            fallback_c.set_key(
                key, float(getattr(handle, "shm_fallback_batches", 0))
            )

    registry.register_pre_expose(flush)
    return {
        "depth": depth_g,
        "wraps": wraps_c,
        "backpressure": backpressure_c,
        "frames": frames_c,
        "fallback": fallback_c,
    }


def register_shm_worker_metrics(registry: Registry, core, shard_id: int) -> None:
    """Worker-side half of the shm event plane: frames/events ingested
    off the ring by this worker's pump thread, plus the ring depth as
    the READER sees it (the two depth gauges disagreeing for long means
    a stalled pump). Sampled from ``core.shm_pump`` at scrape; a worker
    running plain pickle registers nothing."""
    frames_c = registry.counter_vec(
        "kube_throttler_shm_ingest_frames_total",
        "columnar event frames this worker decoded off its shm ring",
        ["shard"],
    )
    events_c = registry.counter_vec(
        "kube_throttler_shm_ingest_events_total",
        "events this worker applied from shm frames",
        ["shard"],
    )
    depth_g = registry.gauge_vec(
        "kube_throttler_shm_ring_depth",
        "event frames committed to the shm ring, not yet consumed "
        "(reader's view)",
        ["shard"],
    )

    def flush() -> None:
        pump = getattr(core, "shm_pump", None)
        if pump is None:
            return
        key = (str(shard_id),)
        frames_c.set_key(key, float(pump.frames))
        events_c.set_key(key, float(pump.events))
        depth_g.set_key(key, float(pump.depth()))

    registry.register_pre_expose(flush)


def register_build_metrics(
    registry: Registry, role: str = "front", front=None,
) -> Dict[str, object]:
    """Rolling-upgrade observability (kube_throttler_tpu/version.py).
    ``kube_throttler_build_info`` is a constant-1 gauge whose labels are
    the data — one row for this process (role, build id, protocol it
    speaks) and, when ``front`` is given, one row per shard with the
    hello-negotiated version + capability intersection, so a dashboard
    shows exactly which fleet members still ride the old minor mid-roll.
    The mismatch counter moves when a worker refuses an incompatible
    MAJOR (typed ``VersionMismatch`` — degraded, never a crash loop);
    the backoff gauge samples the supervisor's crash-loop guard (the
    per-shard restart delay, 0 when healthy) via the ``supervisor_ref``
    the supervisor pins on its front."""
    from .version import BUILD_ID, local_proto_version

    build_g = registry.gauge_vec(
        "kube_throttler_build_info",
        "build identity and negotiated wire protocol (value is always "
        "1; the labels carry the data)",
        ["role", "shard", "build", "proto", "caps"],
    )
    mismatch_c = registry.counter_vec(
        "kube_throttler_shard_version_mismatch_total",
        "handshakes the shard refused for an incompatible protocol "
        "MAJOR (typed VersionMismatch refusals)",
        ["shard"],
    )
    backoff_g = registry.gauge_vec(
        "kube_throttler_shard_restart_backoff_seconds",
        "the supervisor crash-loop guard's most recent restart delay "
        "per shard (jittered-exponential; 0 when healthy)",
        ["shard"],
    )
    own_proto = "%d.%d" % local_proto_version()

    def flush() -> None:
        build_g.set_key((role, "", BUILD_ID, own_proto, ""), 1.0)
        if front is None:
            return
        for sid in range(front.n_shards):
            handle = front.shards.get(sid)
            if handle is None:
                continue
            proto = getattr(handle, "negotiated_proto", None)
            caps = getattr(handle, "negotiated_caps", None) or ()
            build_g.set_key(
                (
                    role,
                    str(sid),
                    getattr(handle, "peer_build", None) or "",
                    "" if proto is None else "%d.%d" % tuple(proto),
                    ",".join(sorted(caps)),
                ),
                1.0,
            )
            mismatch_c.set_key(
                (str(sid),), float(getattr(handle, "version_mismatches", 0))
            )
        supervisor = getattr(front, "supervisor_ref", None)
        if supervisor is not None:
            for sid, delay in supervisor.backoff_seconds().items():
                backoff_g.set_key((str(sid),), float(delay))

    registry.register_pre_expose(flush)
    return {"build": build_g, "mismatches": mismatch_c, "backoff": backoff_g}


def register_reshard_metrics(registry: Registry, front) -> Dict[str, object]:
    """Live-resharding observability (sharding/reshard.py drives the
    counters/histogram; the gauge samples the front's transition state at
    scrape time). Ranges-moving > 0 for longer than a handoff SLO means a
    stuck transition — the dual-ring router keeps serving correctly, but
    the fleet is not at its target shape."""
    moving_g = registry.gauge_vec(
        "kube_throttler_reshard_ranges_moving",
        "keyspace ranges currently in flight (mirroring or pending) in a "
        "live reshard; 0 when no transition is active",
        [],
    )
    bytes_c = registry.counter_vec(
        "kube_throttler_reshard_handoff_bytes_total",
        "verified slice bytes streamed source→destination across all "
        "handoffs (the StandbyReplicator chunk contract over IPC)",
        [],
    )
    events_c = registry.counter_vec(
        "kube_throttler_reshard_handoff_events_total",
        "objects (throttles + pods) and ledger entries transferred in "
        "handoff slices",
        [],
    )
    cutover_h = registry.histogram_vec(
        "kube_throttler_reshard_cutover_duration_seconds",
        "per-range fence→activate cutover window (the interval a moving "
        "range's flips ride the re-publication path instead of the live "
        "stream)",
        [],
    )
    aborts_c = registry.counter_vec(
        "kube_throttler_reshard_aborted_total",
        "handoffs aborted back to the source (torn stream, destination "
        "crash, fence race, or TTL reap)",
        [],
    )

    def flush() -> None:
        state = front.reshard_state()
        if state is None:
            moving_g.set({}, 0.0)
        else:
            moving_g.set(
                {}, float(state["pending"]) + float(state["mirroring"])
            )

    registry.register_pre_expose(flush)
    return {
        "moving": moving_g,
        "bytes": bytes_c,
        "events": events_c,
        "cutover": cutover_h,
        "aborts": aborts_c,
    }


def register_ingest_metrics(registry: Registry, pipeline) -> None:
    """Micro-batched ingest observability (engine/ingest.py), exported on
    whatever registry the daemon serves — local standalone and remote mode
    both build their pipeline with the process registry, so the families
    appear on both paths. The batch-size histogram is observed inline by
    the dispatcher (one observe per drain — scrape-time sampling would
    miss the distribution); the events counter moves with it."""
    pipeline._batch_hist = registry.histogram_vec(
        "kube_throttler_ingest_batch_size",
        "events applied per micro-batch drain (1 = the unloaded "
        "single-event path; growth means the adaptive batcher is absorbing "
        "backlog)",
        [],
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
    )
    pipeline._events_ctr = registry.counter_vec(
        "kube_throttler_ingest_events_total",
        "events ingested through the micro-batch pipeline",
        [],
    )


def register_store_metrics(registry: Registry, store) -> None:
    """Columnar arena observability (engine/columnar.py), sampled from the
    arena's counters at scrape time. Slots-live tracks the pod population;
    recycled_total moving means delete churn is reusing slots (no arena
    growth); intern-pool size growing without population growth means
    label/value cardinality is climbing; materializations_total is the
    lazy-edge hydration rate (the whole point of the arena is that this
    stays proportional to API/serialization traffic, not event churn).
    No-op for a frozen-dict reference store (no arena)."""
    arena = getattr(store, "pod_arena", None)
    if arena is None:
        return
    live_g = registry.gauge_vec(
        "kube_throttler_store_arena_slots_live",
        "pods resident in the columnar arena (slots occupied)",
        [],
    )
    recycled_c = registry.counter_vec(
        "kube_throttler_store_arena_slots_recycled_total",
        "arena slots freed by pod deletion and returned to the free list",
        [],
    )
    intern_g = registry.gauge_vec(
        "kube_throttler_store_intern_pool_size",
        "distinct strings in the shared intern pool (names, namespaces, "
        "uids, label keys+values)",
        [],
    )
    mat_c = registry.counter_vec(
        "kube_throttler_store_materializations_total",
        "full API objects built at the lazy serialization/API edge",
        [],
    )

    def flush() -> None:
        live_g.set_key((), float(len(arena)))
        recycled_c.set_key((), float(arena.recycled_total))
        intern_g.set_key((), float(len(arena.pool)))
        mat_c.set_key((), float(arena.materializations_total))

    registry.register_pre_expose(flush)


def register_verdict_cache_metrics(registry: Registry, cache) -> None:
    """Interned-verdict cache observability (engine/verdictcache.py),
    sampled from the cache's racy counters at scrape time. Hit-rate
    (hits / (hits+misses)) is the serving tier's primary health signal:
    a collapse under steady traffic means epoch churn is outrunning the
    degenerate-shape assumption. Entries is bounded by the configured
    capacity; invalidations counts explicit full drops (policy swaps),
    not epoch-superseded entries (those die silently by construction)."""
    if cache is None:
        return
    hits_c = registry.counter_vec(
        "kube_throttler_verdict_cache_hits_total",
        "pre_filter verdicts served straight from the interned-verdict cache",
        [],
    )
    miss_c = registry.counter_vec(
        "kube_throttler_verdict_cache_misses_total",
        "cache probes that fell through to a full plane walk "
        "(cold key, epoch-superseded entry, or uncacheable verdict)",
        [],
    )
    entries_g = registry.gauge_vec(
        "kube_throttler_verdict_cache_entries",
        "live entries across both cache generations (bounded by capacity)",
        [],
    )
    inval_c = registry.counter_vec(
        "kube_throttler_verdict_cache_invalidations_total",
        "explicit whole-cache invalidation sweeps (policy hot-swaps, "
        "replica re-bootstraps) — epoch-superseded entries are not counted",
        [],
    )

    def flush() -> None:
        hits, misses, entries, invalidations, _ = cache.stats()
        hits_c.set_key((), float(hits))
        miss_c.set_key((), float(misses))
        entries_g.set_key((), float(entries))
        inval_c.set_key((), float(invalidations))

    registry.register_pre_expose(flush)


def register_replica_metrics(registry: Registry, gate) -> None:
    """Read-replica serving observability (engine/replication.py
    ReplicaGate), sampled at scrape time. Verdicts are labeled by outcome
    ("served" vs "refused") so the SLO dashboard reads refusal-rate
    directly; lag_events counts requests refused for breaching the
    staleness bound; lag_seconds is the replica's current journal-tail
    age (the quantity the bound is enforced against)."""
    verdicts_c = registry.counter_vec(
        "kube_throttler_replica_verdicts_total",
        "pre_filter verdicts handled by this read replica",
        ["outcome"],
    )
    lag_events_c = registry.counter_vec(
        "kube_throttler_replica_lag_events_total",
        "serving refusals because replication lag exceeded the staleness bound",
        [],
    )
    lag_g = registry.gauge_vec(
        "kube_throttler_replica_lag_seconds",
        "seconds since the replica last confirmed it was caught up with "
        "the leader's journal tail",
        [],
    )

    def flush() -> None:
        verdicts_c.set_key(("served",), float(gate.served_total))
        verdicts_c.set_key(("refused",), float(gate.refused_total))
        lag_events_c.set_key((), float(gate.lag_events_total))
        lag_g.set_key((), float(gate.current_lag()))

    registry.register_pre_expose(flush)


def register_watch_metrics(registry: Registry) -> None:
    """Watch-queue depth/overflow families, fed at scrape time from the
    Watch class aggregates (client/watch.py): queue depth climbing means a
    consumer is falling behind; the overflow counter moving means events
    were shed and that consumer must relist."""
    from .client.watch import Watch

    open_g = registry.gauge_vec(
        "kube_throttler_watch_streams_open", "live Watch streams", []
    )
    depth_g = registry.gauge_vec(
        "kube_throttler_watch_queue_depth",
        "events queued across live Watch streams (slow-consumer lag)",
        [],
    )
    dropped_c = registry.counter_vec(
        "kube_throttler_watch_overflow_total",
        "events shed by bounded Watch queues (drop-oldest policy); a "
        "consumer that overflowed has a gap and must relist",
        [],
    )

    def flush() -> None:
        stats = Watch.stats()
        open_g.set_key((), float(stats["open"]))
        depth_g.set_key((), float(stats["depth"]))
        # the class-level total is already monotonic: expose it directly
        dropped_c.set_key((), float(stats["dropped_total"]))

    registry.register_pre_expose(flush)


class ThrottleMetricsRecorder:
    """throttle_metrics.go:94-197. The registry is explicit — there is no
    module-global default, so recorded series are always reachable from
    whatever serves that registry's /metrics."""

    def __init__(self, registry: Registry):
        self._rec = _KindRecorder(
            "throttle", ("namespace", "name", "uid", "resource"), registry
        )

    def record(self, thr: Throttle) -> None:
        self._rec.record(
            {"namespace": thr.namespace, "name": thr.name, "uid": thr.uid}, thr
        )


class ClusterThrottleMetricsRecorder:
    """clusterthrottle_metrics.go:224-326."""

    def __init__(self, registry: Registry):
        self._rec = _KindRecorder(
            "clusterthrottle", ("name", "uid", "resource"), registry
        )

    def record(self, thr: ClusterThrottle) -> None:
        self._rec.record({"name": thr.name, "uid": thr.uid}, thr)
