"""Runtime lock-order assassin: instrumented locks for tests and soaks.

Opt-in via ``KT_LOCK_ASSERT=1`` (tests/conftest.py turns it on for the
whole suite). When off, the factories return plain ``threading``
primitives — zero overhead on the serving path. When on, every lock
created through :func:`make_lock`/:func:`make_rlock` is wrapped so that:

- each thread's acquisition stack is tracked; acquiring B while holding A
  records the order edge ``A -> B`` in a process-global graph, and an
  acquisition that would close a cycle (some thread previously acquired
  in the opposite order) raises :class:`LockOrderViolation` immediately —
  with the current stack and the first-seen stack of the conflicting
  edge — instead of deadlocking two chaos threads sometime later;
- re-acquiring a non-reentrant lock from its own holder raises instead of
  silently deadlocking;
- :func:`assert_held` lets ``*_locked`` helpers enforce their "caller
  holds the lock" contract;
- per-lock HOLD-TIME budgets (:func:`set_hold_budget`, fnmatch patterns
  over lock names, or a global default via ``KT_LOCK_HOLD_BUDGET``
  seconds): a release after holding longer than the budget raises
  :class:`LockHoldBudgetExceeded` — the runtime twin of the static
  ``blocking`` checker, keeping ``blocking_allow.txt`` honest: a waived
  "intended hold" that silently grows past its budget fails the suite
  instead of surfacing as a flip-p99 regression two PRs later;
- :func:`guard_attrs` (a class decorator) turns a class's ``GUARDED_BY``
  table — the same one the static analyzer reads — into a ``__setattr__``
  check: rebinding a guarded attribute after ``__init__`` without holding
  its lock raises :class:`LockAssertionError`. (Only rebinding is
  checked; in-place mutation of a guarded container is invisible to
  ``__setattr__`` and remains the static checker's job.)

The edge graph is cumulative across the process: two threads never need
to collide in time for an inversion to be caught — each order only has
to be *observed* once.
"""

from __future__ import annotations

import fnmatch
import functools
import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "LockOrderViolation",
    "LockAssertionError",
    "LockHoldBudgetExceeded",
    "enabled",
    "lock_assert_enabled",
    "race_detect_enabled",
    "make_lock",
    "make_rlock",
    "make_condition",
    "assert_held",
    "held_by_me",
    "held_names",
    "guard_attrs",
    "reset_graph",
    "set_hold_budget",
    "clear_hold_budgets",
]


class LockOrderViolation(RuntimeError):
    """Two locks were acquired in both orders (potential deadlock)."""


class LockAssertionError(RuntimeError):
    """A lock-holding contract was violated (lock not held / wrong owner)."""


class LockHoldBudgetExceeded(LockAssertionError):
    """A lock was held longer than its configured hold-time budget."""


def lock_assert_enabled() -> bool:
    return os.environ.get("KT_LOCK_ASSERT", "") == "1"


def race_detect_enabled() -> bool:
    # mirror of racedetect.enabled() read here directly: the lockset
    # detector needs instrumented locks for thread-held identity, and
    # importing racedetect from this module would cycle
    return os.environ.get("KT_RACE_DETECT", "") == "1"


def enabled() -> bool:
    """Instrumentation master switch: the lock assassin
    (``KT_LOCK_ASSERT=1``) or the Eraser lockset detector
    (``KT_RACE_DETECT=1``) — race detection implies instrumented locks."""
    return lock_assert_enabled() or race_detect_enabled()


_tls = threading.local()

# order graph: name -> set of names acquired while holding it; guarded by
# _graph_lock for writes (reads are GIL-consistent snapshots — a stale
# read only delays edge insertion to the locked path below)
_graph_lock = threading.Lock()
_edges: Dict[str, Set[str]] = {}
# (outer, inner) -> trimmed stack at first sighting, for diagnostics
_edge_sites: Dict[Tuple[str, str], str] = {}


def reset_graph() -> None:
    """Clear the cumulative order graph (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _edge_sites.clear()


# ------------------------------------------------------- hold-time budgets

# (fnmatch pattern over lock names, seconds); first match wins. Seeded
# from KT_LOCK_HOLD_BUDGET (a global default budget in seconds) when set.
_hold_budgets: List[Tuple[str, float]] = []
_budget_epoch = 0  # bumped on every change so per-lock caches invalidate


_env_budget_cache: List[Optional[float]] = []  # [] = unread; [x] = cached


def _env_default_budget() -> Optional[float]:
    # read once per process: this sits on EVERY instrumented release, and
    # os.environ.get per release measurably taxed the armed soak tiers
    if not _env_budget_cache:
        raw = os.environ.get("KT_LOCK_HOLD_BUDGET", "")
        val: Optional[float] = None
        if raw:
            try:
                val = float(raw)
            except ValueError:
                val = None
        _env_budget_cache.append(val)
    return _env_budget_cache[0]


def set_hold_budget(pattern: str, seconds: float) -> None:
    """Arm a hold-time budget for every lock whose name fnmatches
    ``pattern`` (``"journal"``, ``"shard.*"``, ``"*"``). Releasing a lock
    after holding it longer than its budget raises
    :class:`LockHoldBudgetExceeded` — AFTER the release, so the failure
    cannot wedge other threads. First matching pattern wins; re-arming a
    pattern replaces its budget. Test-tier only (inert when
    instrumentation is off)."""
    global _budget_epoch
    with _graph_lock:
        _hold_budgets[:] = [(p, s) for p, s in _hold_budgets if p != pattern]
        _hold_budgets.append((str(pattern), float(seconds)))
        _budget_epoch += 1


def clear_hold_budgets() -> None:
    global _budget_epoch
    with _graph_lock:
        _hold_budgets.clear()
        _budget_epoch += 1


def _budget_for(name: str) -> Optional[float]:
    for pattern, seconds in _hold_budgets:
        if fnmatch.fnmatch(name, pattern):
            return seconds
    return _env_default_budget()


def _held() -> List["_InstrumentedLock"]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def held_names() -> Tuple[str, ...]:
    """Names of the instrumented locks the calling thread holds right
    now — the lockset the race detector intersects per access."""
    return tuple(lock.name for lock in _held())


def held_frozenset():
    """Frozenset form of :func:`held_names`, cached per thread and
    invalidated on every acquire/release — the race detector's per-access
    read. Identity is meaningful: two calls returning the SAME object
    mean the lockset did not change in between (the detector skips the
    intersection entirely then)."""
    fs = getattr(_tls, "held_fs", None)
    if fs is None:
        fs = _tls.held_fs = frozenset(lock.name for lock in _held())
    return fs


def _invalidate_held_fs() -> None:
    _tls.held_fs = None


def _site(limit: int = 8) -> str:
    return "".join(traceback.format_stack(limit=limit)[:-2])


def _reachable(src: str, dst: str) -> bool:
    """dst reachable from src in the edge graph (iterative DFS)."""
    seen = {src}
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        for m in _edges.get(n, ()):
            if m not in seen:
                seen.add(m)
                stack.append(m)
    return False


def _note_acquisition(lock: "_InstrumentedLock") -> None:
    held = _held()
    if not held:
        return
    for outer in held:
        a, b = outer.name, lock.name
        if a == b:
            continue
        s = _edges.get(a)
        if s is not None and b in s:
            continue  # known-good order, fast path
        with _graph_lock:
            s = _edges.setdefault(a, set())
            if b in s:
                continue
            # inserting a->b: would b ->* a close a cycle?
            if _reachable(b, a):
                prior = _edge_sites.get((b, a)) or next(
                    (
                        _edge_sites[e]
                        for e in _edge_sites
                        if e[0] == b and _reachable(e[1], a)
                    ),
                    "<site not recorded>",
                )
                raise LockOrderViolation(
                    f"lock-order inversion: acquiring '{b}' while holding "
                    f"'{a}', but the opposite order '{b}' -> ... -> '{a}' "
                    f"was previously observed.\n--- current acquisition "
                    f"(thread {threading.current_thread().name}) ---\n"
                    f"{_site()}--- first sighting of the opposite order ---\n"
                    f"{prior}"
                )
            s.add(b)
            _edge_sites[(a, b)] = _site()


class _InstrumentedLock:
    """Lock/RLock replacement with owner tracking + order recording.

    Built on a plain ``threading.Lock`` with reentrancy managed here, so
    one implementation serves both kinds and Condition's
    ``_release_save``/``_acquire_restore`` protocol can keep the held
    bookkeeping exact across ``wait()``."""

    __slots__ = ("name", "reentrant", "_inner", "_owner", "_count", "_t0")

    def __init__(self, name: str, reentrant: bool):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.Lock()
        self._owner: Optional[int] = None
        self._count = 0
        self._t0 = 0.0  # monotonic instant the current hold began

    # -- core protocol ----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._owner == me:
            if not self.reentrant:
                raise LockOrderViolation(
                    f"non-reentrant lock '{self.name}' re-acquired by its "
                    f"holder (guaranteed deadlock)\n{_site()}"
                )
            self._count += 1
            return True
        _note_acquisition(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._owner = me
            self._count = 1
            self._t0 = time.monotonic()
            _held().append(self)
            _invalidate_held_fs()
        return ok

    def release(self) -> None:
        me = threading.get_ident()
        if self._owner != me:
            raise LockAssertionError(
                f"lock '{self.name}' released by a thread that does not "
                f"hold it\n{_site()}"
            )
        self._count -= 1
        if self._count == 0:
            held_for = time.monotonic() - self._t0
            self._owner = None
            h = _held()
            if self in h:
                h.remove(self)
            _invalidate_held_fs()
            self._inner.release()
            # budget check AFTER the release: the raise must report the
            # over-hold, never extend it (or wedge the other threads)
            budget = _budget_for(self.name)
            if budget is not None and held_for > budget:
                raise LockHoldBudgetExceeded(
                    f"lock '{self.name}' held {held_for * 1e3:.1f}ms, over "
                    f"its {budget * 1e3:.1f}ms hold budget\n{_site()}"
                )

    def __enter__(self) -> "_InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition integration -------------------------------------------

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        me = threading.get_ident()
        if self._owner != me:
            raise LockAssertionError(
                f"cond.wait() on '{self.name}' without holding it"
            )
        saved = self._count
        self._count = 0
        self._owner = None
        h = _held()
        if self in h:
            h.remove(self)
        _invalidate_held_fs()
        self._inner.release()
        return saved

    def _acquire_restore(self, saved) -> None:
        _note_acquisition(self)
        self._inner.acquire()
        self._owner = threading.get_ident()
        self._count = saved
        # the wait()ed stretch does not count against the hold budget —
        # a fresh hold starts when the condition hands the lock back
        self._t0 = time.monotonic()
        _held().append(self)
        _invalidate_held_fs()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        state = f"held by {self._owner} x{self._count}" if self._owner else "unlocked"
        return f"<InstrumentedLock {self.name!r} {state}>"


def make_lock(name: str):
    """A ``threading.Lock`` — instrumented under ``KT_LOCK_ASSERT=1``.
    ``name`` should be globally descriptive (``"devicestate.main"``)."""
    if enabled():
        return _InstrumentedLock(name, reentrant=False)
    return threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock`` — instrumented under ``KT_LOCK_ASSERT=1``."""
    if enabled():
        return _InstrumentedLock(name, reentrant=True)
    return threading.RLock()


def make_condition(lock=None):
    """``threading.Condition`` over a (possibly instrumented) lock.
    Instrumented locks implement the full owner/save/restore protocol, so
    ``wait()`` keeps the held-stack bookkeeping exact."""
    return threading.Condition(lock)


def held_by_me(lock) -> Optional[bool]:
    """True/False when determinable, None for plain primitives that do not
    expose ownership (an un-instrumented ``threading.Lock``)."""
    if isinstance(lock, _InstrumentedLock):
        return lock._is_owned()
    is_owned = getattr(lock, "_is_owned", None)
    if callable(is_owned):  # plain RLock / Condition
        try:
            return bool(is_owned())
        except Exception:  # pragma: no cover - exotic lock types
            return None
    return None


def assert_held(lock, what: str = "") -> None:
    """Enforce a ``*_locked`` helper's contract. No-op when the primitive
    cannot answer (plain Lock) or instrumentation is off — the call is
    then documentation; under ``KT_LOCK_ASSERT=1`` it bites."""
    if not isinstance(lock, _InstrumentedLock):
        # instrumentation off (production): every make_lock/make_rlock hands
        # out plain primitives — asking a plain RLock ``_is_owned()`` here
        # measured ~10µs/event across the ingest hot path's *_locked
        # helpers, pure overhead for a check that only bites when
        # instrumented. The suite runs KT_LOCK_ASSERT=1 (instrumented
        # locks), so the contract is still enforced where it matters.
        return
    owned = held_by_me(lock)
    if owned is False:
        name = getattr(lock, "name", repr(lock))
        raise LockAssertionError(
            f"{what or 'a _locked helper'} requires lock '{name}' held by "
            f"the calling thread\n{_site()}"
        )


def _guard_lock_names(spec) -> Tuple[str, ...]:
    if isinstance(spec, str):
        spec = (spec,)
    out = []
    for s in spec:
        s = s.strip()
        if s.startswith("self."):
            s = s[5:]
        out.append(s.split("(")[0].split("[")[0])
    return tuple(out)


def guard_attrs(cls):
    """Class decorator: enforce the class's ``GUARDED_BY`` table at
    runtime. Inert unless instrumentation is on at class decoration
    time. Arms after ``__init__`` returns, so construction writes stay
    free. Two independent layers share the table:

    - ``KT_LOCK_ASSERT=1`` — rebind-time ``__setattr__`` check (original
      behavior: rebinding a guarded attribute without its lock raises);
    - ``KT_RACE_DETECT=1`` — a data descriptor per guarded attribute
      funnels reads AND writes into the Eraser lockset detector
      (``utils/racedetect.py``), catching the in-place-mutation and
      read-side races the rebind check cannot see."""
    if not enabled():
        return cls
    table = getattr(cls, "GUARDED_BY", None)
    if not table:
        return cls
    guards = {attr: _guard_lock_names(spec) for attr, spec in table.items()}
    orig_setattr = cls.__setattr__
    orig_init = cls.__init__
    check_rebind = lock_assert_enabled()

    def __setattr__(self, name, value):
        if check_rebind and name in guards and self.__dict__.get("_kt_guard_armed", False):
            ok = False
            for lock_name in guards[name]:
                lock = self.__dict__.get(lock_name)
                owned = held_by_me(lock) if lock is not None else None
                if owned is not False:  # held, or can't tell -> allow
                    ok = True
                    break
            if not ok:
                raise LockAssertionError(
                    f"guarded attribute '{name}' of {type(self).__name__} "
                    f"rebound without holding "
                    f"{' or '.join(guards[name])}\n{_site()}"
                )
        orig_setattr(self, name, value)

    @functools.wraps(orig_init)
    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        self.__dict__["_kt_guard_armed"] = True

    cls.__setattr__ = __setattr__
    cls.__init__ = __init__
    if race_detect_enabled():
        from . import racedetect

        racedetect.install_descriptors(cls, guards.keys())
    return cls
