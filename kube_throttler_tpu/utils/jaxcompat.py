"""Version-drift shims for the installed jax.

``shard_map`` moved from ``jax.experimental.shard_map`` to the top-level
``jax`` namespace (and the experimental module was later removed). The
multi-chip paths (parallel/sharded.py, parallel/ring.py, and the tick
orchestration in engine/devicestate.py) must compile against whichever
spelling the installed jax provides, so they import the symbol from here
instead of hard-coding either location.
"""

from __future__ import annotations

import jax

try:
    # modern spelling; getattr would trip jax's accelerated-deprecation
    # shim on versions where the name is only a stub, so import eagerly
    # and fall back on AttributeError either way
    shard_map = jax.shard_map  # type: ignore[attr-defined]
except AttributeError:
    try:
        from jax.experimental.shard_map import shard_map  # type: ignore
    except ImportError:  # pragma: no cover - neither spelling available
        shard_map = None

HAS_SHARD_MAP = shard_map is not None


def require_shard_map():
    """The installed jax's shard_map, or a clear error naming both
    spellings (callers otherwise surface an AttributeError deep inside a
    compile cache miss)."""
    if shard_map is None:  # pragma: no cover - env-dependent
        raise RuntimeError(
            "this jax provides neither jax.shard_map nor "
            "jax.experimental.shard_map.shard_map; multi-chip sharded "
            "paths need one of them"
        )
    return shard_map
