"""Eraser-style lockset race detector — the dynamic half of gen-3.

The GUARDED_BY machinery catches an unguarded write at runtime only
when it *rebinds* the attribute (``guard_attrs``' ``__setattr__``
check), and the static ``guarded`` checker reasons lexically. What
neither sees: actual *reads* racing actual writes under the locks each
thread really held. This module closes that gap with the classic
Eraser algorithm (Savage et al., SOSP '97) over exactly the attributes
the GUARDED_BY tables already declare shared:

- per (object, attribute) a **candidate lockset** ``C(v)`` is refined
  by intersection with the acquiring thread's instrumented-lock set at
  every access once a second thread touches the attribute;
- the read-share/write-exclusive state machine suppresses the benign
  patterns: ``Virgin → Exclusive`` (single-owner init, no lockset
  ops), ``Exclusive → Shared`` on a second-thread *read* (reads refine
  C(v) but an empty C(v) does not report), ``→ Shared-Modified`` on
  any second-thread write or a write in Shared (empty C(v) reports);
- a race is reported at **first observation** — the access whose
  intersection empties the candidate set — with both access sites,
  both locksets, and both threads. Two threads never need to collide
  in time; the interleaving only has to be *observed* once, which is
  what makes the planted-race gate deterministic.

Arming: ``KT_RACE_DETECT=1`` (tests/conftest.py arms it suite-wide,
like ``KT_LOCK_ASSERT``). ``utils/lockorder.guard_attrs`` then installs
a data descriptor per guarded attribute (reads AND writes funnel
through it at native cost for every *other* attribute — no
``__getattribute__`` tax), storing values under the attribute's own
``__dict__`` key so pickling/vars() are unchanged. Lock identity comes
from the instrumented ``make_lock``/``make_rlock`` primitives — race
mode implies lock instrumentation even when ``KT_LOCK_ASSERT`` is
unset.

Reports collect in a process-global list; the conftest sessionfinish
gate fails the suite on any unwaived report. Vetted benign races go in
``kube_throttler_tpu/analysis/race_allow.txt`` keyed
``module.Class.attr`` with a **mandatory justification** (the PR 10
convention: an entry with no justification, or naming an attribute
that no longer exists in any GUARDED_BY table, is itself an error —
tests/test_racedetect.py enforces both statically, so waiver rot fails
the suite without depending on which tests ran).
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Tuple

__all__ = [
    "enabled",
    "note_read",
    "note_write",
    "reports",
    "reset",
    "capture",
    "fired_waivers",
    "load_allow",
    "default_allow_path",
    "install_descriptors",
    "RaceReport",
]


def enabled() -> bool:
    return os.environ.get("KT_RACE_DETECT", "") == "1"


# ------------------------------------------------------------------- states

_VIRGIN = 0
_EXCLUSIVE = 1
_SHARED = 2
_SHARED_MOD = 3

_STATE_NAMES = {
    _VIRGIN: "virgin",
    _EXCLUSIVE: "exclusive",
    _SHARED: "read-shared",
    _SHARED_MOD: "shared-modified",
}


class _VarState:
    __slots__ = (
        "state",
        "owner",
        "lockset",
        "last_site",
        "last_ident",
        "last_name",
        "last_held",
        "last_write",
        "reported",
    )

    def __init__(self) -> None:
        self.state = _VIRGIN
        self.owner: Optional[int] = None
        self.lockset: Optional[FrozenSet[str]] = None
        self.last_site: Tuple[Tuple[str, int], ...] = ()
        self.last_ident = 0
        self.last_name = ""
        self.last_held: FrozenSet[str] = frozenset()
        self.last_write = False
        self.reported = False


@dataclass
class RaceReport:
    """First observation of an empty candidate lockset."""

    qual: str  # module.Class.attr — the waiver key
    attr: str
    kind: str  # "write/write" | "read/write" | "write/read"
    state: str  # state-machine state at detection
    thread: str
    held: Tuple[str, ...]
    site: str  # full stack of the detecting access
    prior_thread: str
    prior_held: Tuple[str, ...]
    prior_site: str  # compact file:line chain of the prior access
    line: str = ""  # file:line of the detecting access (first frame)

    def render(self) -> str:
        return (
            f"race on {self.qual} [{self.kind}, {self.state}]: candidate "
            f"lockset emptied at {self.line}\n"
            f"--- this access (thread {self.thread}, holding "
            f"{list(self.held) or '{}'}) ---\n{self.site}"
            f"--- prior access (thread {self.prior_thread}, holding "
            f"{list(self.prior_held) or '{}'}) ---\n  {self.prior_site}\n"
        )


# ------------------------------------------------------------------ globals

_mu = threading.Lock()  # plain on purpose: never enters the order graph
_reports: List[RaceReport] = []
_reported_quals: set = set()
_fired_waivers: set = set()
_allow_cache: Optional[Dict[str, str]] = None
_tls = threading.local()


def default_allow_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "analysis",
        "race_allow.txt",
    )


def load_allow(path: Optional[str] = None) -> Dict[str, str]:
    """``module.Class.attr  # justification`` lines -> {qual: why}."""
    out: Dict[str, str] = {}
    path = path or default_allow_path()
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            line = raw.rstrip("\n")
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            if "  #" in line:
                key, _, just = line.partition("  #")
                out[key.strip()] = just.strip()
            else:
                out[line.strip()] = ""
    return out


def _allowed(qual: str) -> bool:
    global _allow_cache
    if _allow_cache is None:
        _allow_cache = load_allow()
    return qual in _allow_cache


def reports() -> List[RaceReport]:
    with _mu:
        return list(_reports)


def fired_waivers() -> set:
    with _mu:
        return set(_fired_waivers)


def reset() -> None:
    """Clear reports, fired waivers, and the waiver cache (test isolation).
    Per-object var states live on the objects and die with them."""
    global _allow_cache
    with _mu:
        _reports.clear()
        _reported_quals.clear()
        _fired_waivers.clear()
        _allow_cache = None


class capture:
    """Context manager: redirect reports to a local list so planted-race
    fixtures never leak into the suite-wide sessionfinish gate."""

    def __init__(self) -> None:
        self.reports: List[RaceReport] = []

    def __enter__(self) -> "capture":
        self._saved: List[RaceReport] = []
        with _mu:
            self._saved = list(_reports)
            _reports.clear()
            self._saved_quals = set(_reported_quals)
        return self

    def __exit__(self, *exc) -> None:
        with _mu:
            self.reports = list(_reports)
            _reports[:] = self._saved
            _reported_quals.clear()
            _reported_quals.update(self._saved_quals)


# ------------------------------------------------------------- access notes


# this module and lockorder, by exact path: an endswith() filter would
# also swallow tests/test_racedetect.py frames. Raw and abspath forms so
# per-frame comparison stays a set lookup on co_filename as-is.
_SELF_FILES = {
    __file__,
    os.path.abspath(__file__),
    os.path.join(os.path.dirname(__file__), "lockorder.py"),
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "lockorder.py"),
}


def _compact_frames(depth: int = 4, skip: int = 2) -> Tuple[Tuple[str, int], ...]:
    """(filename, lineno) chain of the caller — recorded on every access,
    so no string formatting here (format only at report time)."""
    out: List[Tuple[str, int]] = []
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ()
    while f is not None and len(out) < depth:
        fn = f.f_code.co_filename
        if fn not in _SELF_FILES:
            out.append((fn, f.f_lineno))
        f = f.f_back
    return tuple(out)


def _fmt_frames(frames: Tuple[Tuple[str, int], ...]) -> str:
    return " <- ".join(f"{fn}:{ln}" for fn, ln in frames) or "<unknown>"


def _full_site(limit: int = 10) -> str:
    return "".join(traceback.format_stack(limit=limit)[:-3])


_held_frozenset = None  # resolved lazily (lockorder import would cycle)
_get_ident = threading.get_ident


def _held_fs() -> FrozenSet[str]:
    global _held_frozenset
    f = _held_frozenset
    if f is None:
        from . import lockorder

        f = _held_frozenset = lockorder.held_frozenset
    return f()


def _note(obj, attr: str, qual: str, is_write: bool) -> None:
    d = obj.__dict__
    vars_map = d.get("_kt_race_vars")
    if vars_map is None:
        vars_map = d["_kt_race_vars"] = {}
    vs0 = vars_map.get(attr)
    me = _get_ident()
    if vs0 is not None:
        if vs0.reported:
            return  # first observation already recorded for this var
        if vs0.state == _EXCLUSIVE and vs0.owner == me:
            # single-owner hot path (the overwhelmingly common case):
            # no lockset ops, no mutex — just enough context for the
            # eventual transition report (prior lockset/site of a FIRED
            # report always comes from a cross-thread access, which
            # takes the slow path). A concurrent transition by a second
            # thread only races these bookkeeping fields, never the
            # state machine itself (that runs under _mu below).
            vs0.last_ident = me
            vs0.last_write = is_write
            return
        held = _held_fs()
        if (
            vs0.lockset is not None
            and (vs0.state == _SHARED_MOD or not is_write)
            and vs0.lockset.issubset(held)
        ):
            # steady shared hot path: C ⊆ H means the intersection
            # leaves C unchanged — no state transition (write-in-Shared
            # excluded above), and a fire is impossible (an empty C in
            # Shared-Modified would already have reported) — mutex
            # skipped. This includes the post-handoff read-only pattern
            # (C emptied in read-share, every later read is free).
            vs0.last_ident = me
            vs0.last_write = is_write
            vs0.last_held = held
            return
    if getattr(_tls, "busy", False):
        return
    _tls.busy = True
    try:
        held = _held_fs()
        with _mu:
            vs = vars_map.get(attr)
            if vs is None:
                vs = vars_map[attr] = _VarState()
            race_kind: Optional[str] = None
            if vs.state == _VIRGIN:
                vs.state = _EXCLUSIVE
                vs.owner = me
            elif vs.state == _EXCLUSIVE:
                if vs.owner == me:
                    pass  # still single-owner: no lockset ops
                else:
                    # second thread: leave Exclusive, C(v) := held
                    vs.lockset = held
                    if is_write:
                        vs.state = _SHARED_MOD
                        if not vs.lockset:
                            race_kind = (
                                "write/write" if vs.last_write else "read/write"
                            )
                    else:
                        vs.state = _SHARED
            elif vs.state == _SHARED:
                if vs.lockset is None:
                    vs.lockset = held
                elif held is not vs.last_held:  # identity: same fs ⇒ C∩H==C
                    vs.lockset = vs.lockset & held
                if is_write:
                    vs.state = _SHARED_MOD
                    if not vs.lockset:
                        race_kind = "read/write"
            else:  # _SHARED_MOD
                if vs.lockset is None:
                    vs.lockset = held
                elif held is not vs.last_held:
                    vs.lockset = vs.lockset & held
                if not vs.lockset and not vs.reported:
                    race_kind = (
                        "write/write"
                        if (is_write and vs.last_write)
                        else ("read/write" if vs.last_write or is_write else None)
                    )
                    # two reads can empty C(v) only after a write put the
                    # var in Shared-Modified; attribute it to that write
                    race_kind = race_kind or "write/read"
            fire = race_kind is not None and not vs.reported
            if fire:
                vs.reported = True
                fire = qual not in _reported_quals
                if fire:
                    _reported_quals.add(qual)
            prior = (vs.last_name, vs.last_held, vs.last_site)
            # the frame walk and thread-name lookup are the per-access
            # cost centers; record them only when the accessing thread
            # CHANGED (prior-access context in a report always describes
            # the most recent cross-thread access — the conflict partner)
            if me != vs.last_ident or fire:
                vs.last_site = _compact_frames()
                vs.last_name = threading.current_thread().name
            vs.last_ident = me
            vs.last_held = held
            vs.last_write = is_write
        if fire:
            if _allowed(qual):
                with _mu:
                    _fired_waivers.add(qual)
                return
            site = _full_site()
            line = _fmt_frames(_compact_frames(depth=1))
            rep = RaceReport(
                qual=qual,
                attr=attr,
                kind=race_kind,
                state=_STATE_NAMES[_SHARED_MOD],
                thread=threading.current_thread().name,
                held=tuple(sorted(held)),
                site=site,
                prior_thread=prior[0] or "<none>",
                prior_held=tuple(sorted(prior[1])),
                prior_site=_fmt_frames(prior[2]) if prior[2] else "<first access>",
                line=line,
            )
            with _mu:
                _reports.append(rep)
            if os.environ.get("KT_RACE_RAISE", "") == "1":
                raise RaceDetected(rep.render())
    finally:
        _tls.busy = False


class RaceDetected(RuntimeError):
    """Raised at the detection site under ``KT_RACE_RAISE=1`` (debug aid;
    the default is collect-and-gate so one report never cascades)."""


def note_read(obj, attr: str, qual: str) -> None:
    _note(obj, attr, qual, is_write=False)


def note_write(obj, attr: str, qual: str) -> None:
    _note(obj, attr, qual, is_write=True)


# ---------------------------------------------- mutation-aware access kinds

# At the attribute level, an in-place mutation (``self._items.append(x)``,
# ``self._map[k] = v``) reaches the descriptor as a *load* — classifying
# it as a read would blind the write-exclusive half of the state machine
# to exactly the accesses ``guard_attrs``' rebind check already cannot
# see. So each load site is classified ONCE from the caller's bytecode
# (then cached by (code, lasti)): a load feeding a known mutator method
# or a subscript store/delete within the next few instructions is a
# WRITE access.

_MUTATORS = {
    "append",
    "extend",
    "insert",
    "remove",
    "pop",
    "popitem",
    "popleft",
    "appendleft",
    "clear",
    "update",
    "add",
    "discard",
    "setdefault",
    "sort",
    "reverse",
    "fill",
    "put",
    "put_nowait",
    "itemset",
    "resize",
}
_STORE_OPS = {"STORE_SUBSCR", "DELETE_SUBSCR"}
_LOAD_OPS = {"LOAD_ATTR", "LOAD_METHOD"}

_site_kind: Dict[Tuple[object, int], bool] = {}


def _classify_site(frame) -> bool:
    """True when the attribute load at frame.f_lasti feeds a mutation."""
    import dis

    key = (frame.f_code, frame.f_lasti)
    hit = _site_kind.get(key)
    if hit is not None:
        return hit
    is_write = False
    try:
        instrs = list(dis.get_instructions(frame.f_code))
        idx = next(
            (i for i, ins in enumerate(instrs) if ins.offset == frame.f_lasti),
            None,
        )
        if idx is not None:
            for ins in instrs[idx + 1 : idx + 7]:
                if ins.opname in _STORE_OPS:
                    is_write = True
                    break
                if ins.opname in _LOAD_OPS and ins.argval in _MUTATORS:
                    is_write = True
                    break
                # any consumer that ends this expression's use of the
                # loaded value: calls, stores, jumps/branch tests, loop
                # setup, returns — stop before misreading a LATER
                # statement's store as ours
                if ins.opname.startswith(
                    ("STORE_", "CALL", "RETURN", "POP_JUMP", "JUMP", "COMPARE_OP")
                ) or ins.opname in ("POP_TOP", "GET_ITER", "FOR_ITER", "UNPACK_SEQUENCE"):
                    break
    except Exception:  # pragma: no cover - dis is total on live code
        pass
    if len(_site_kind) > 65536:
        _site_kind.clear()
    _site_kind[key] = is_write
    return is_write


# -------------------------------------------------------------- descriptors

_MISSING = object()


class _TrackedAttr:
    """Data descriptor over one guarded attribute. Storage stays under
    the attribute's own ``__dict__`` key (data descriptors shadow the
    instance dict on lookup, so reads/writes funnel here while
    ``vars()``/pickling see exactly the usual shape). Tracking arms with
    ``_kt_guard_armed`` — construction writes stay free, like
    ``guard_attrs``."""

    __slots__ = ("name", "qual", "default")

    def __init__(self, name: str, qual: str, default=_MISSING):
        self.name = name
        self.qual = qual
        self.default = default

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        d = obj.__dict__
        val = d.get(self.name, _MISSING)
        if val is _MISSING:
            if self.default is _MISSING:
                raise AttributeError(self.name)
            return self.default
        if d.get("_kt_guard_armed", False):
            # inline single-owner fast path: the descriptor is on every
            # hot read, so the steady case must cost dict gets only
            vm = d.get("_kt_race_vars")
            vs = vm.get(self.name) if vm is not None else None
            if vs is not None and vs.state == _EXCLUSIVE and vs.owner == _get_ident():
                # single-owner reads don't even classify: last_write
                # keeps the value from the last slow-path access (the
                # first access classified this site family already;
                # kind labels on an eventual report tolerate that)
                vs.last_ident = vs.owner
                return val
            if vs is not None and vs.reported:
                return val
            # a load feeding an in-place mutation IS a write — classified
            # from the caller's bytecode (cached per site)
            _note(obj, self.name, self.qual, _classify_site(sys._getframe(1)))
        return val

    def __set__(self, obj, value) -> None:
        d = obj.__dict__
        d[self.name] = value
        if d.get("_kt_guard_armed", False):
            vm = d.get("_kt_race_vars")
            vs = vm.get(self.name) if vm is not None else None
            if vs is not None and vs.state == _EXCLUSIVE and vs.owner == _get_ident():
                vs.last_ident = vs.owner
                vs.last_write = True
                return
            if vs is not None and vs.reported:
                return
            _note(obj, self.name, self.qual, True)

    def __delete__(self, obj) -> None:
        try:
            del obj.__dict__[self.name]
        except KeyError:
            raise AttributeError(self.name) from None


def install_descriptors(cls, attrs) -> None:
    """Install a tracking descriptor per guarded attribute. Called from
    ``lockorder.guard_attrs`` when race detection is armed. Classes
    relying on ``__slots__`` for a guarded attr are skipped (no guarded
    class does today; slotted helpers stay untouched)."""
    slots = getattr(cls, "__slots__", None)
    if slots is not None and "__dict__" not in slots:
        return
    qual_base = f"{cls.__module__.removeprefix('kube_throttler_tpu.')}.{cls.__qualname__}"
    for attr in attrs:
        existing = getattr(cls, attr, _MISSING)
        if isinstance(existing, _TrackedAttr):
            continue
        default = existing if existing is not _MISSING else _MISSING
        if callable(default) or isinstance(default, property):
            # a method/property sharing the name would be shadowed;
            # guarded attrs are data, never callables — skip defensively
            continue
        setattr(cls, attr, _TrackedAttr(attr, f"{qual_base}.{attr}", default))
