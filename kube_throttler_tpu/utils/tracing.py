"""Tracing / profiling — the TPU build's observability beyond gauges.

The reference's only tracing is klog verbosity levels V(2)-V(5) plus a
dynamic log-level endpoint (SURVEY §5: plugin.go:157,
reserved_resource_amounts.go:197, Makefile:94-95). The TPU-native
equivalent here is richer, per the survey's prescription:

- :class:`PhaseTracer` — per-phase wall-clock histograms
  (``kube_throttler_phase_duration_seconds{phase=...}``) exported through
  the same registry that serves ``/metrics``; phases cover the scheduling
  hot path (prefilter/reserve/unreserve), the async state engine
  (reconcile), and host↔device sync.
- klog-style verbosity: :func:`set_verbosity` / :func:`v_enabled` /
  :func:`vlog` map V-levels onto the stdlib logger the way klog maps them
  onto --v (V(2)≈INFO detail … V(5)≈trace). The daemon's
  ``PUT /debug/flags/v`` analog calls ``set_verbosity`` at runtime.
- :func:`device_trace` — context manager around ``jax.profiler.trace``
  for Perfetto/XProf kernel traces (jax imported lazily; no-op when
  profiling is unavailable).
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, Iterator, Optional

from .lockorder import make_lock

logger = logging.getLogger("kube_throttler_tpu")

_verbosity_lock = make_lock("tracing.verbosity")
_verbosity = 0


def set_verbosity(level: int) -> int:
    """Set the global V-level (klog --v / PUT /debug/flags/v analog).
    Returns the previous level."""
    global _verbosity
    with _verbosity_lock:
        prev, _verbosity = _verbosity, int(level)
    return prev


def get_verbosity() -> int:
    return _verbosity


def v_enabled(level: int) -> bool:
    """klog ``klog.V(level).Enabled()``."""
    return _verbosity >= level


def vlog(level: int, msg: str, *args) -> None:
    """klog ``klog.V(level).Infof`` — emits at INFO when the global
    verbosity admits the level, else drops (lazily formatted)."""
    if _verbosity >= level:
        logger.info(msg, *args)


class PhaseTracer:
    """Per-phase wall-clock histograms over a metrics Registry.

    One family, labeled by phase, so dashboards slice p50/p99 per phase:
    ``kube_throttler_phase_duration_seconds_bucket{phase="prefilter",...}``.
    """

    FAMILY = "kube_throttler_phase_duration_seconds"

    def __init__(self, registry) -> None:
        self._hist = registry.histogram_vec(
            self.FAMILY,
            "Wall-clock duration of kube-throttler phases (scheduling hot "
            "path, reconcile, device sync)",
            ["phase"],
        )

    def trace(self, phase: str) -> "_Trace":
        # a slotted context object, not @contextmanager: the generator
        # protocol costs ~3µs per enter/exit and the serving hot path
        # crosses 6+ trace scopes per decision
        return _Trace(self._hist, phase)

    def observe(self, phase: str, seconds: float) -> None:
        self._hist.observe({"phase": phase}, seconds)

    def snapshot(self, phase: str) -> Optional[Dict[str, float]]:
        """{"sum": s, "count": n, "mean": s/n} or None if never observed."""
        snap = self._hist.snapshot({"phase": phase})
        if snap is None:
            return None
        total, count = snap
        return {"sum": total, "count": count, "mean": total / count if count else 0.0}


class _Trace:
    """Slotted timing scope: observes phase duration into the histogram
    family on exit (plus V(5) logging when enabled)."""

    __slots__ = ("_hist", "_phase", "_start")

    def __init__(self, hist, phase: str) -> None:
        self._hist = hist
        self._phase = phase

    def __enter__(self) -> None:
        self._start = time.perf_counter()

    def __exit__(self, exc_type, exc, tb) -> None:
        elapsed = time.perf_counter() - self._start
        self._hist.observe_key((self._phase,), elapsed)
        if v_enabled(5):
            vlog(5, "phase %s took %.6fs", self._phase, elapsed)


class _NoopHist:
    def observe(self, labels, value) -> None:
        pass

    def observe_key(self, key, value) -> None:
        pass

    def snapshot(self, labels):
        return None


class NoopTracer(PhaseTracer):
    """Tracer that records nothing (for callers constructed without a
    registry)."""

    def __init__(self) -> None:  # deliberately no super().__init__
        self._hist = _NoopHist()


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a JAX profiler trace (XProf/Perfetto) for the enclosed
    block. No-op if the profiler cannot start (e.g. unsupported backend)."""
    started = False
    try:
        import jax

        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # pragma: no cover — backend-dependent
        logger.warning("device_trace unavailable: %s", e)
    try:
        yield
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover
                logger.warning("stop_trace failed: %s", e)
