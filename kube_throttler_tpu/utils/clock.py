"""Injectable clock (the reference threads k8s.io/utils/clock through its
controllers for exactly this reason — deterministic override-boundary tests,
plugin.go:97/109)."""

from __future__ import annotations

import time as _time
from datetime import datetime, timedelta, timezone

from .lockorder import guard_attrs, make_condition, make_lock


class Clock:
    def now(self) -> datetime:  # pragma: no cover — interface
        raise NotImplementedError

    def monotonic(self) -> float:
        """Seconds on a monotonic axis — elapsed-time math (lease renew
        deadlines, staleness windows) must use THIS, never deltas of
        ``now()``: wall-clock NTP steps would stretch or shrink an
        interval measured in ``datetime`` space."""
        raise NotImplementedError  # pragma: no cover — interface

    def subscribe(self, callback) -> None:
        """Register a zero-arg callback fired when the clock jumps (FakeClock
        advance/set). Real time never jumps, so the default is a no-op —
        deadline waiters compute exact timeouts instead of polling."""

    def unsubscribe(self, callback) -> None:
        """Remove a subscribed callback (no-op when absent) so a shut-down
        waiter doesn't stay referenced by a long-lived clock."""


class RealClock(Clock):
    def now(self) -> datetime:
        return datetime.now(timezone.utc)

    def monotonic(self) -> float:
        return _time.monotonic()


@guard_attrs
class FakeClock(Clock):
    """Settable clock for tests; ``advance`` wakes subscribed waiters."""

    GUARDED_BY = {
        "_now": "self._cond",
        "_mono": "self._cond",
        "_listeners": "self._cond",
    }

    def __init__(self, start: datetime):
        self._now = start
        self._mono = 0.0
        self._cond = make_condition(make_lock("utils.fakeclock"))
        self._listeners = []

    def now(self) -> datetime:
        with self._cond:
            return self._now

    def monotonic(self) -> float:
        with self._cond:
            return self._mono

    def subscribe(self, callback) -> None:
        with self._cond:
            self._listeners.append(callback)

    def unsubscribe(self, callback) -> None:
        with self._cond:
            try:
                self._listeners.remove(callback)
            except ValueError:
                pass

    def _notify(self) -> None:
        # listeners run OUTSIDE the clock lock: a listener typically takes
        # its own lock (e.g. the workqueue condition) whose holders call
        # back into now() — calling under the clock lock would be an
        # ABBA deadlock
        with self._cond:
            self._cond.notify_all()
            listeners = list(self._listeners)
        for cb in listeners:
            cb()

    def advance(self, delta: timedelta) -> None:
        """Time passes: wall AND monotonic move together."""
        with self._cond:
            self._now += delta
            self._mono += delta.total_seconds()
        self._notify()

    def set(self, t: datetime) -> None:
        """Wall-clock JUMP (an NTP step): ``now()`` moves, ``monotonic()``
        does not — elapsed-time consumers must be unaffected."""
        with self._cond:
            self._now = t
        self._notify()

    def advance_monotonic(self, seconds: float) -> None:
        """Monotonic-only advance (a frozen wall clock that still ticks
        elapsed time — the inverse skew case)."""
        with self._cond:
            self._mono += float(seconds)
        self._notify()
