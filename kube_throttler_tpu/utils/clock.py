"""Injectable clock (the reference threads k8s.io/utils/clock through its
controllers for exactly this reason — deterministic override-boundary tests,
plugin.go:97/109)."""

from __future__ import annotations

import threading
from datetime import datetime, timedelta, timezone


class Clock:
    def now(self) -> datetime:  # pragma: no cover — interface
        raise NotImplementedError


class RealClock(Clock):
    def now(self) -> datetime:
        return datetime.now(timezone.utc)


class FakeClock(Clock):
    """Settable clock for tests; ``advance`` wakes pollers via condition."""

    def __init__(self, start: datetime):
        self._now = start
        self._cond = threading.Condition()

    def now(self) -> datetime:
        with self._cond:
            return self._now

    def advance(self, delta: timedelta) -> None:
        with self._cond:
            self._now += delta
            self._cond.notify_all()

    def set(self, t: datetime) -> None:
        with self._cond:
            self._now = t
            self._cond.notify_all()
