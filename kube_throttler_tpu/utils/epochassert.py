"""Verdict-coherence assassin — the runtime half of the gen-4 ``epochs``
checker (``KT_EPOCH_ASSERT=1``, armed suite-wide by tests/conftest.py
like ``KT_LOCK_ASSERT``/``KT_RACE_DETECT``).

The static checker proves every *visible* write to a verdict plane is
dominated by an epoch bump; ``epoch_allow.txt`` waives the sites it
cannot prove. What neither can see: a waiver that is simply wrong, a
mutation reached through a path the AST resolution missed, or a future
plane that never made it into the registry. This module closes that gap
the way hold budgets keep ``blocking_allow.txt`` honest — by checking
the invariant the whole discipline exists to protect, at the exact
place it pays out:

- every Nth VerdictCache **hit** (sampled — ``KT_EPOCH_ASSERT_SAMPLE``,
  default 7) is shadow-recomputed through the uncached oracle route
  (``_pre_filter_uncached``, side-effect-free);
- a divergence means a verdict-affecting mutation landed WITHOUT
  bumping a covered epoch: the fingerprint still matches
  (``cached esum == current esum`` — that equality is the smoking gun)
  while the recomputed truth moved. A :class:`StaleVerdict` is raised
  at **first observation** with both epochs, both verdicts, and the
  file:line of the most recent covered mutations (devicestate's
  ``_note_thr_col`` reports them via :func:`note_mutation` when armed)
  — i.e. the mutation that should have bumped.

Production cost is one ``os.environ`` read at import: everything here
is behind the cached arming flag.
"""

from __future__ import annotations

import os
import sys
import threading
from collections import deque
from typing import Deque, List, Optional, Tuple

__all__ = [
    "enabled",
    "should_check",
    "check_hit",
    "note_mutation",
    "reports",
    "reset",
    "set_sample",
    "StaleVerdict",
]


def enabled() -> bool:
    return os.environ.get("KT_EPOCH_ASSERT", "") == "1"


def _sample_rate() -> int:
    try:
        n = int(os.environ.get("KT_EPOCH_ASSERT_SAMPLE", "7"))
    except ValueError:
        n = 7  # malformed override must not kill serving
    return max(1, n)


_lock = threading.Lock()
_sample = _sample_rate()
_hits = 0
_reports: List[str] = []
_fired_keys: set = set()
# (file, line, function) of recent covered mutations, newest last
_recent_mutations: Deque[Tuple[str, int, str]] = deque(maxlen=8)


class StaleVerdict(AssertionError):
    """A cache hit served a verdict the oracle no longer agrees with at
    an UNCHANGED epoch sum — some covered mutation skipped its bump."""


def set_sample(n: int) -> None:
    """Override the sampling rate (tests: 1 = shadow-check every hit)."""
    global _sample
    _sample = max(1, int(n))


def reset() -> None:
    global _hits, _sample
    with _lock:
        _hits = 0
        _reports.clear()
        _fired_keys.clear()
        _recent_mutations.clear()
    _sample = _sample_rate()


def reports() -> List[str]:
    return list(_reports)


def should_check() -> bool:
    """Deterministic counter sampling: True on every Nth cache hit."""
    global _hits
    with _lock:
        _hits += 1
        return _hits % _sample == 0


def note_mutation(depth: int = 2) -> None:
    """Record the call site of a covered verdict-plane mutation
    (devicestate ``_note_thr_col`` calls this when armed; ``depth``
    skips the noting helper so the recorded frame is the mutator)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        frame = sys._getframe()
    site = (
        frame.f_code.co_filename,
        frame.f_lineno,
        frame.f_code.co_name,
    )
    with _lock:
        _recent_mutations.append(site)


def _normalize(status) -> Tuple:
    return (status.code, tuple(sorted(status.reasons)))


def check_hit(plugin, pod, key: tuple, esum: int, cached) -> None:
    """Shadow-recompute a sampled cache hit through the uncached oracle
    route and raise :class:`StaleVerdict` on first-observed divergence."""
    fresh = plugin._pre_filter_uncached(pod, emit_events=False)
    from ..plugin.framework import StatusCode

    if fresh.code is StatusCode.ERROR:
        return  # transient oracle error — not coherence evidence
    if _normalize(fresh) == _normalize(cached):
        return
    with _lock:
        if key in _fired_keys:
            return  # first observation already reported for this key
        _fired_keys.add(key)
        current = plugin.device_manager.verdict_fingerprint(pod)
        cur_esum = current[1] if current is not None else None
        sites = "\n".join(
            f"    {f}:{ln} in {fn}()" for f, ln, fn in _recent_mutations
        ) or "    <none recorded — mutation predates arming or bypassed _note_thr_col>"
        report = (
            "StaleVerdict: cache hit diverges from the oracle at an "
            "unchanged epoch sum (a verdict-affecting mutation skipped "
            "its bump)\n"
            f"  key={key!r}\n"
            f"  cached esum={esum} current esum={cur_esum}"
            f"{' (UNCHANGED)' if cur_esum == esum else ''}\n"
            f"  cached verdict: code={cached.code} reasons={cached.reasons!r}\n"
            f"  oracle verdict: code={fresh.code} reasons={fresh.reasons!r}\n"
            "  recent covered mutations (the bump that should have "
            "happened belongs at one of these):\n"
            f"{sites}"
        )
        _reports.append(report)
    raise StaleVerdict(report)
