"""Runtime retrace budget: no XLA recompiles after warmup.

The static ``retrace`` checker (analysis/retrace.py) pins the shape
discipline at call sites it can see; this module counts what the
compiler *actually did*. Every jit entry in ``ops/``/``parallel/``
registers itself (``register_all(globals(), __name__)`` at module
bottom); the per-entry compile-cache size (``PjitFunction._cache_size``)
is a monotone count of distinct compiled programs.

Arming: ``KT_JIT_RETRACE_BUDGET=<n>`` — after ``KT_JIT_RETRACE_WARMUP``
ticks (default 3; the padding ladders legitimately compile a handful of
rungs while capacities settle), a tick during which the total compile
count across entries grows by more than ``n`` (cumulatively since
warmup) raises :class:`RetraceBudgetExceeded` naming each entry with
its compile delta. ``n=0`` is the steady-state contract: one padded
dispatch per tick, zero recompiles. Unset disables (production default
— the check belongs to tests, soaks, and the bench's warm sections).

``DeviceStateManager.aggregate_used_for`` calls :func:`on_tick` once
per drain — the tick boundary the budget is defined over. Tests and the
bench can call :func:`snapshot`/:func:`on_tick` directly.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "RetraceBudgetExceeded",
    "register",
    "register_all",
    "registered",
    "cache_sizes",
    "budget",
    "warmup_ticks",
    "on_tick",
    "reset",
    "snapshot",
]


class RetraceBudgetExceeded(RuntimeError):
    """A tick recompiled a jit entry after warmup (budget exhausted)."""


_mu = threading.Lock()
_registry: Dict[str, object] = {}
_tick = 0
_baseline: Optional[Dict[str, int]] = None


def register(name: str, fn) -> object:
    """Track one jit entry. Returns ``fn`` so it can wrap a def site."""
    if hasattr(fn, "_cache_size"):
        with _mu:
            _registry[name] = fn
    return fn


def register_all(namespace: Dict[str, object], modname: str) -> int:
    """Register every jit entry in a module's globals (call at module
    bottom: ``register_all(globals(), __name__)``). Returns the count."""
    short = modname.rsplit("kube_throttler_tpu.", 1)[-1]
    n = 0
    for attr, obj in list(namespace.items()):
        if attr.startswith("_"):
            continue
        if hasattr(obj, "_cache_size") and callable(obj):
            register(f"{short}.{attr}", obj)
            n += 1
    return n


def registered() -> Tuple[str, ...]:
    with _mu:
        return tuple(sorted(_registry))


def cache_sizes() -> Dict[str, int]:
    """Entry -> count of distinct compiled programs, right now."""
    out: Dict[str, int] = {}
    with _mu:
        items = list(_registry.items())
    for name, fn in items:
        try:
            out[name] = int(fn._cache_size())
        except Exception:  # pragma: no cover - backend-dependent internals
            continue
    return out


def budget() -> Optional[int]:
    raw = os.environ.get("KT_JIT_RETRACE_BUDGET", "")
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None  # malformed override must not arm OR crash (envguard)


def warmup_ticks() -> int:
    try:
        return int(os.environ.get("KT_JIT_RETRACE_WARMUP", "3"))
    except ValueError:
        return 3


def reset() -> None:
    global _tick, _baseline
    with _mu:
        _tick = 0
        _baseline = None


def snapshot() -> Dict[str, int]:
    """Pin the current per-entry compile counts as the warm baseline
    (what ``on_tick`` does automatically at the end of warmup)."""
    global _baseline
    sizes = cache_sizes()
    with _mu:
        _baseline = dict(sizes)
    return sizes


def on_tick() -> None:
    """Advance the tick counter; after warmup, fail the tick if compile
    counts grew past the budget since the warm baseline."""
    global _tick, _baseline
    b = budget()
    if b is None:
        return
    with _mu:
        _tick += 1
        tick = _tick
        baseline = _baseline
    warm = warmup_ticks()
    if tick <= warm or baseline is None:
        if tick >= warm or baseline is None:
            snapshot()
        return
    sizes = cache_sizes()
    grew: List[str] = []
    total_delta = 0
    for name, n in sizes.items():
        d = n - baseline.get(name, 0)
        if d > 0:
            grew.append(f"{name}: +{d} (now {n})")
            total_delta += d
    if total_delta > b:
        raise RetraceBudgetExceeded(
            f"tick {tick} recompiled after warmup ({warm} ticks, budget "
            f"{b}): {'; '.join(grew)} — a shape/static-arg leaked past the "
            "padding ladder (see analysis/retrace.py and "
            "docs/STATIC_ANALYSIS.md gen-3)"
        )
