"""Leader election for the standalone daemon.

The reference inherits leader election from the embedded kube-scheduler
(the ``leaderElection`` block of KubeSchedulerConfiguration —
deploy/config.yaml in both repos; client-go leaderelection over a
coordination.k8s.io Lease); a standby replica blocks until the lease is
free. Two backends here:

- :class:`FileLeaseElector` — exclusive ``flock`` on a file in a private
  runtime directory; single-host scope, crash-safe (the OS drops the lock
  on process death).
- :class:`HttpLeaseElector` — a Lease object on the control-plane
  apiserver (`/apis/coordination.k8s.io/v1/.../leases/`), renewed on a
  heartbeat and taken over when ``renewTime`` goes stale — client-go's
  LeaderElector loop. Multi-host capable: replicas coordinate through the
  shared apiserver exactly like the reference.
"""

from __future__ import annotations

import fcntl
import logging
import os
import threading
import time
from datetime import datetime, timezone
from pathlib import Path
from typing import Optional, Tuple

from .clock import Clock, RealClock

logger = logging.getLogger(__name__)


def default_lease_path(name: str) -> str:
    """Default flock lease location: a per-user 0700 runtime dir —
    NOT world-writable /tmp, where a predictable filename invites a
    pre-create / symlink squat (ADVICE r2 item 1)."""
    base = os.environ.get("XDG_RUNTIME_DIR") or os.environ.get("XDG_CACHE_HOME")
    root = Path(base) if base else Path.home() / ".cache"
    d = root / "kube-throttler-tpu"
    d.mkdir(mode=0o700, parents=True, exist_ok=True)
    return str(d / f"{name}.lock")


class FileLeaseElector:
    """Blocking file-lock lease: ``acquire`` polls flock(LOCK_EX|LOCK_NB)
    until it wins or ``stop`` is set; the OS releases the lease on process
    death, so a crashed leader frees its standby automatically."""

    def __init__(self, lock_path: str, retry_period: float = 2.0):
        self.lock_path = lock_path
        self.retry_period = retry_period
        self._fd: Optional[int] = None

    @property
    def is_leader(self) -> bool:
        return self._fd is not None

    def try_acquire(self) -> bool:
        if self._fd is not None:
            return True
        try:
            # O_NOFOLLOW: refuse a symlink planted at the lease path
            fd = os.open(
                self.lock_path, os.O_CREAT | os.O_RDWR | os.O_NOFOLLOW, 0o600
            )
        except OSError as e:
            # unusable path (missing dir, permission-denied) is a config
            # error, not a held lease — fail loudly instead of retrying
            raise RuntimeError(
                f"cannot open leadership lease {self.lock_path}: {e}"
            ) from e
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        except BaseException:
            # anything else (KeyboardInterrupt between open and flock, a
            # monkeypatched flock raising in tests) must not leak the fd:
            # a leaked descriptor HOLDS the flock for the process lifetime,
            # wedging every future acquire on this host
            os.close(fd)
            raise
        self._fd = fd  # leadership is held from here even if the pid write fails
        try:
            os.ftruncate(fd, 0)
            os.write(fd, str(os.getpid()).encode())
        except OSError:
            pass  # the pid note is advisory only
        return True

    def acquire(self, stop: Optional[threading.Event] = None) -> bool:
        """Block until leadership is acquired (True) or ``stop`` fires
        (False)."""
        waiting_logged = False
        while True:
            if self.try_acquire():
                logger.info("acquired leadership lease %s", self.lock_path)
                return True
            if not waiting_logged:
                logger.info(
                    "lease %s held by another replica; standing by", self.lock_path
                )
                waiting_logged = True
            if stop is not None:
                if stop.wait(self.retry_period):
                    return False
            else:
                time.sleep(self.retry_period)

    def release(self) -> None:
        """Idempotent: a double release (or a release after a failed
        acquire) is a no-op — the fd is nulled FIRST so even an unlock
        error cannot leave a half-released elector that a second call
        would double-close (closing a reused fd number belonging to
        someone else)."""
        fd, self._fd = self._fd, None
        if fd is None:
            return
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        except OSError:
            pass  # close() drops the lock regardless
        finally:
            os.close(fd)
        logger.info("released leadership lease %s", self.lock_path)


def _rfc3339(dt: datetime) -> str:
    return dt.astimezone(timezone.utc).isoformat().replace("+00:00", "Z")


class HttpLeaseElector:
    """client-go-style leader election over a coordination.k8s.io Lease on
    the apiserver (the backend the reference's embedded kube-scheduler
    uses). Multi-host: any number of replicas, on any hosts, coordinate
    through the shared control plane.

    Protocol (leaderelection.go semantics):
    - create the Lease if absent (win by creation);
    - if held by someone else, take over only when ``renewTime`` is older
      than ``lease_duration`` (the holder died or lost connectivity);
    - while leading, renew every ``renew_period`` by PUT with the last
      resourceVersion — a 409 means another replica wrote the Lease, so
      re-read and possibly demote (leadership loss is observable via
      ``is_leader``).
    """

    def __init__(
        self,
        client,  # client.transport.ApiClient
        name: str,
        identity: str,
        namespace: str = "kube-system",
        lease_duration: float = 15.0,
        renew_period: float = 5.0,
        retry_period: float = 2.0,
        renew_deadline: Optional[float] = None,
        on_lost=None,
        clock: Optional[Clock] = None,
    ):
        """``on_lost``: zero-arg callback fired when held leadership is LOST
        (renew conflict won by another replica, or the renew deadline
        passing without a successful write). The reference's embedded
        kube-scheduler exits the process here — wire ``on_lost`` to the
        daemon's stop event for the same fail-fast behavior.

        ``renew_deadline`` must be STRICTLY less than ``lease_duration``
        (client-go defaults 10s vs 15s): the demoting side gives up before
        a standby's takeover clock expires, so there is never a window with
        two leaders. Defaults to 2/3 of ``lease_duration``.

        ``clock``: staleness and renew-deadline math run on
        ``clock.monotonic()`` (client-go's observedTime semantics) — the
        holder's ``renewTime`` string is treated as an opaque heartbeat
        value, and takeover happens only after OUR monotonic clock sees it
        unchanged for a full ``lease_duration``. Wall-clock skew between
        replicas or an NTP step on either side can therefore neither
        trigger a premature takeover nor wedge a stale lease."""
        self.client = client
        self.clock = clock or RealClock()
        self.name = name
        self.identity = identity
        # create is POST to the COLLECTION, read/update to the named
        # resource — the real apiserver 405s a POST to a named path
        self.collection_path = (
            f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        )
        self.path = f"{self.collection_path}/{name}"
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period
        self.renew_deadline = (
            renew_deadline if renew_deadline is not None else lease_duration * 2 / 3
        )
        if self.renew_deadline >= lease_duration:
            raise ValueError("renewDeadline must be < leaseDuration")
        self.on_lost = on_lost
        self._leader = False
        self._rv = ""
        self._stop = threading.Event()
        self._renewer: Optional[threading.Thread] = None
        # last observed (holder, renewTime string) + the monotonic instant
        # we FIRST saw that exact pair — the takeover clock (see __init__)
        self._observed: Optional[Tuple[str, str, float]] = None

    @property
    def is_leader(self) -> bool:
        return self._leader

    # -- lease document ----------------------------------------------------

    def _spec(self, acquire_time: Optional[str] = None) -> dict:
        now = _rfc3339(datetime.now(timezone.utc))
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration),
            "acquireTime": acquire_time or now,
            "renewTime": now,
        }

    def _doc(self, spec: dict, rv: str = "") -> dict:
        meta = {"name": self.name}
        if rv:
            meta["resourceVersion"] = rv
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": meta,
            "spec": spec,
        }

    def try_acquire(self) -> bool:
        """One acquisition attempt (non-blocking). Races and held leases
        return False quietly; unexpected errors are LOGGED (an auth or URL
        misconfiguration must not masquerade as 'lease held')."""
        from ..client.transport import ApiError
        from ..engine.store import ConflictError, NotFoundError

        try:
            current = self.client.get(self.path)
        except NotFoundError:
            try:
                created = self.client.post(
                    self.collection_path, self._doc(self._spec())
                )
                self._rv = str((created.get("metadata") or {}).get("resourceVersion", ""))
                self._won()
                return True
            except ConflictError:
                return False  # another replica created it first
            except (ApiError, OSError) as e:
                logger.warning("lease create on %s failed: %s", self.collection_path, e)
                return False
        except (ApiError, OSError) as e:
            logger.warning("lease read on %s failed: %s", self.path, e)
            return False  # apiserver unreachable: not leader

        spec = current.get("spec") or {}
        rv = str((current.get("metadata") or {}).get("resourceVersion", ""))
        holder = spec.get("holderIdentity") or ""
        renew_raw = str(spec.get("renewTime") or "")
        duration = float(spec.get("leaseDurationSeconds") or self.lease_duration)
        # staleness on OUR monotonic clock, not wall-clock renewTime deltas:
        # the heartbeat string is opaque — any CHANGE restarts the takeover
        # window; only the same (holder, renewTime) pair observed for a full
        # lease_duration of local monotonic time means the holder is dead.
        # An NTP step (local or on the holder) changes neither condition.
        mono = self.clock.monotonic()
        if self._observed is None or self._observed[:2] != (holder, renew_raw):
            self._observed = (holder, renew_raw, mono)
            expired = not renew_raw  # a never-renewed lease is free game
        else:
            expired = (mono - self._observed[2]) > duration
        if holder == self.identity or expired or not holder:
            acquire = (
                spec.get("acquireTime") if holder == self.identity else None
            )
            try:
                updated = self.client.put(self.path, self._doc(self._spec(acquire), rv))
            except ConflictError:
                return False  # raced another replica; retry later
            except (ApiError, OSError) as e:
                logger.warning("lease takeover on %s failed: %s", self.path, e)
                return False
            self._rv = str((updated.get("metadata") or {}).get("resourceVersion", ""))
            self._won()
            return True
        return False

    def _won(self) -> None:
        if not self._leader:
            logger.info(
                "acquired leadership lease %s as %s", self.path, self.identity
            )
        self._leader = True

    def _lost(self, why: str) -> None:
        self._leader = False
        logger.warning("lost leadership lease %s (%s)", self.path, why)
        if self.on_lost is not None:
            try:
                self.on_lost()
            except Exception:
                logger.exception("on_lost callback failed")

    def _renew_loop(self) -> None:
        from ..engine.store import ConflictError

        last_renew = self.clock.monotonic()
        wait = self.renew_period
        while not self._stop.wait(wait):
            wait = self.renew_period
            try:
                updated = self.client.put(
                    self.path, self._doc(self._spec(), self._rv)
                )
                self._rv = str(
                    (updated.get("metadata") or {}).get("resourceVersion", "")
                )
                last_renew = self.clock.monotonic()
            except ConflictError:
                # someone else wrote the Lease — re-read; demote unless it
                # was our own write racing (then try_acquire re-renews)
                self._leader = False
                if self.try_acquire():
                    last_renew = self.clock.monotonic()
                else:
                    self._lost("conflict — another replica holds the lease")
                    return
            except Exception:
                # transient apiserver failure: retry FAST (retry_period, not
                # renew_period) and DEMOTE once renew_deadline passes with
                # no successful write — strictly before a standby's
                # lease_duration takeover clock can expire, so two replicas
                # never both lead (client-go renewDeadline semantics). The
                # deadline runs on the injectable monotonic clock: an NTP
                # step must not fabricate (or eat) elapsed renew time.
                logger.exception("lease renew failed; retrying")
                if self.clock.monotonic() - last_renew > self.renew_deadline:
                    self._lost(
                        f"renew deadline passed ({self.renew_deadline:.0f}s "
                        "without a successful write)"
                    )
                    return
                wait = self.retry_period

    def acquire(self, stop: Optional[threading.Event] = None) -> bool:
        """Block until leadership is acquired (True) or ``stop`` fires
        (False); starts the background renewer on success."""
        waiting_logged = False
        while True:
            if self.try_acquire():
                self._stop.clear()
                self._renewer = threading.Thread(
                    target=self._renew_loop, name="lease-renew", daemon=True
                )
                self._renewer.start()
                return True
            if not waiting_logged:
                logger.info(
                    "lease %s held by another replica; standing by", self.path
                )
                waiting_logged = True
            if stop is not None:
                if stop.wait(self.retry_period):
                    return False
            else:
                time.sleep(self.retry_period)

    def release(self) -> None:
        """Stop renewing and relinquish by zeroing the holder (a clean
        hand-off; a crashed leader is simply taken over on expiry)."""
        self._stop.set()
        if self._renewer is not None:
            self._renewer.join(timeout=2)
            self._renewer = None
        if not self._leader:
            return
        self._leader = False
        try:
            spec = self._spec()
            spec["holderIdentity"] = ""
            self.client.put(self.path, self._doc(spec, self._rv))
        except Exception:
            pass  # expiry will free it
        logger.info("released leadership lease %s", self.path)
