"""Leader election for the standalone daemon.

The reference inherits leader election from the embedded kube-scheduler
(the ``leaderElection`` block of KubeSchedulerConfiguration —
deploy/config.yaml in both repos); a standby replica blocks until the
lease is free. This module provides the standalone analog: an exclusive
``flock`` lease on a file, acquired with the same block-until-leader
behavior. Single-host/shared-filesystem scope — for multi-host HA the
daemon would sit behind a real Lease object on the control-plane store,
which the in-memory apiserver doesn't persist by design (crash-only,
SURVEY §5).
"""

from __future__ import annotations

import fcntl
import logging
import os
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)


class FileLeaseElector:
    """Blocking file-lock lease: ``acquire`` polls flock(LOCK_EX|LOCK_NB)
    until it wins or ``stop`` is set; the OS releases the lease on process
    death, so a crashed leader frees its standby automatically."""

    def __init__(self, lock_path: str, retry_period: float = 2.0):
        self.lock_path = lock_path
        self.retry_period = retry_period
        self._fd: Optional[int] = None

    @property
    def is_leader(self) -> bool:
        return self._fd is not None

    def try_acquire(self) -> bool:
        if self._fd is not None:
            return True
        try:
            fd = os.open(self.lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        except OSError as e:
            # unusable path (missing dir, permission-denied) is a config
            # error, not a held lease — fail loudly instead of retrying
            raise RuntimeError(
                f"cannot open leadership lease {self.lock_path}: {e}"
            ) from e
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        self._fd = fd  # leadership is held from here even if the pid write fails
        try:
            os.ftruncate(fd, 0)
            os.write(fd, str(os.getpid()).encode())
        except OSError:
            pass  # the pid note is advisory only
        return True

    def acquire(self, stop: Optional[threading.Event] = None) -> bool:
        """Block until leadership is acquired (True) or ``stop`` fires
        (False)."""
        waiting_logged = False
        while True:
            if self.try_acquire():
                logger.info("acquired leadership lease %s", self.lock_path)
                return True
            if not waiting_logged:
                logger.info(
                    "lease %s held by another replica; standing by", self.lock_path
                )
                waiting_logged = True
            if stop is not None:
                if stop.wait(self.retry_period):
                    return False
            else:
                time.sleep(self.retry_period)

    def release(self) -> None:
        if self._fd is None:
            return
        try:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
        finally:
            os.close(self._fd)
            self._fd = None
        logger.info("released leadership lease %s", self.lock_path)
