"""JAX platform-selection helper.

This environment's sitecustomize registers the tunnel TPU backend and sets
``jax_platforms`` programmatically at interpreter start, which OVERRIDES the
``JAX_PLATFORMS`` env var. Any entrypoint that wants an operator's explicit
``JAX_PLATFORMS=cpu`` (e.g. when the tunnel is down) to actually take effect
must re-assert it through the config API before the first backend init.
"""

import os


def honor_jax_platforms_env() -> None:
    """Re-assert the JAX_PLATFORMS env var through ``jax.config``.

    No-op when the env var is unset (the ambient platform selection stands)
    or when a backend is already initialized (too late to change).
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def enable_persistent_compilation_cache(backend: str, path: str = "") -> bool:
    """Point XLA's persistent compilation cache at a writable directory.

    Compiles dominate cold-start on a TPU tunnel (seconds per shape; the
    prewarm ladder alone is ~30 shapes) and are pure recomputation across
    processes — the bench's backend probe, every daemon restart. The
    on-disk cache makes the second process deserialize in milliseconds
    instead. Accelerator backends ONLY: pass the already-initialized
    backend's platform name (``jax.devices()[0].platform``) — this helper
    deliberately never queries the backend itself, because a
    ``default_backend()`` probe INITIALIZES it as a side effect and can
    block indefinitely on a dead tunnel (or poison the in-process backend
    cache) when called pre-init. On ``"cpu"`` it returns False: XLA's CPU
    AOT loader logs a machine-feature warning (and threatens SIGILL on
    feature drift) for every cache hit, while CPU compiles are only
    ~10-100ms anyway. Also returns False when the config knob is
    unavailable or the dir cannot be created/owned.
    """
    import stat
    import tempfile

    if not backend or backend in ("cpu", "none"):
        return False

    path = path or os.environ.get(
        "KT_JAX_CACHE_DIR",
        # per-user path in shared tmp: a fixed name would let another user
        # pre-create the dir and plant cache entries this process would
        # deserialize as compiled executables
        os.path.join(tempfile.gettempdir(), f"kt-jax-cache-{os.getuid()}"),
    )
    try:
        os.makedirs(path, mode=0o700, exist_ok=True)
        st = os.stat(path)
        if st.st_uid != os.getuid() or (st.st_mode & stat.S_IWOTH):
            return False  # someone else's (or world-writable) dir — refuse
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # cache small computations too — this workload is many small
        # scatter/gather shapes whose individual compile times sit under
        # the default min-compile-time threshold
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        return True
    except Exception:
        return False
