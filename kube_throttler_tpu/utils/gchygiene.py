"""GC hygiene for the serving daemon: freeze the startup heap, defer
full collections, and run them from a controlled background cadence.

Why this exists (measured at the 100k-pod × 10k-throttle scale, one CPU
core): a CPython generation-2 collection scans every tracked object, and
the daemon's steady-state heap is ~1.4M tracked objects — each automatic
full collection paused every thread 500-750 ms. Those pauses land inside
reconcile drains and are the single largest contributor to the
throttled-flip publication tail (a flip otherwise publishes in ~2 drain
periods; one GC pause multiplies that 5×).

The treatment is the standard long-lived-heap posture (cf. Instagram's
``gc.freeze`` deployment):

- ``freeze_startup_heap()`` — ONE full collection while the daemon is
  not yet serving, then ``gc.freeze()``: the startup object graph (store
  objects, device mirror planes, compiled-kernel caches) moves to the
  permanent generation and is never scanned again. Frozen objects that
  later become garbage are still freed by REFERENCE COUNTING — freezing
  only removes them from the cycle collector's scan set, so the only
  objects it can pin are members of cycles formed before the freeze, and
  those were just collected.
- generation-2 auto-collection is deferred (threshold raised so it
  effectively never self-triggers): the engine's churn is acyclic —
  frozen dataclasses replaced whole on every write — measured at ZERO
  cyclic objects over a full-scale paced window, so deferring the cycle
  collector does not grow the heap; gen-0/1 keep running (sub-25 ms).
- ``GcHygieneThread`` — the leak backstop: every ``interval_s`` it runs
  one full collection over the (small) unfrozen remainder and re-freezes
  the survivors. The pause cost scales with ONE interval's surviving
  allocations, not the whole heap, and the cadence bounds how much any
  future cyclic garbage could accumulate. Pause durations are observed
  into the phase tracer (``gc_full_collect``) so the tail is attributable
  from /metrics.

``KT_GC_FREEZE=0`` disables the whole posture (the only reason to do so
is debugging with ``gc.get_objects``, which cannot see the permanent
generation).

Re-measured on the PR 11 columnar arena heap: the store no longer holds
a per-pod object graph (pods live in interned struct-of-arrays columns,
materialized lazily at the API edge and freed by refcounting), so a
full-scale 100k-pod serving heap drops from ~1.4M tracked objects to
~150-300k — a full collection over it is tens of ms, not 500-750 ms.
The posture is therefore CONDITIONAL now: ``freeze_startup_heap``
measures the post-collect tracked-object count and only freezes +
defers gen-2 when it exceeds ``KT_GC_FREEZE_MIN_OBJECTS`` (default
200k — see the floor's comment for the churn measurement that set it).
Below the floor the default generational GC is measurably cheaper than
carrying a permanent generation, and ``gc.get_objects`` keeps working
for debugging.
"""

from __future__ import annotations

import gc
import logging
import os
import threading
import time

logger = logging.getLogger("kube_throttler_tpu")

# effectively-never for automatic gen2 self-triggering (collections still
# run explicitly from the hygiene thread); gen0/gen1 defaults are kept
_DEFERRED_GEN2_THRESHOLD = 1_000_000

# tracked-object floor below which the freeze posture is skipped.
# Re-measured on the columnar arena heap (bench --mega, 100k×10k rung):
# the PER-POD object population is gone, but a serving stack still
# carries ~300-400k tracked objects (throttle/status dataclasses,
# kernel caches, runtime) and an unfrozen gen-2 pass over them pauses
# ~300+ ms — churn throughput collapsed ~8× when the floor left that
# heap unfrozen. So the posture RETIRES only for genuinely small heaps
# (CLIs, tests, sub-10k-pod daemons land well under 200k); every real
# serving heap still freezes.
_DEFAULT_MIN_OBJECTS = 200_000


def enabled() -> bool:
    return os.environ.get("KT_GC_FREEZE", "1") != "0"


def freeze_min_objects() -> int:
    try:
        return int(os.environ.get("KT_GC_FREEZE_MIN_OBJECTS", _DEFAULT_MIN_OBJECTS))
    except ValueError:
        return _DEFAULT_MIN_OBJECTS


def freeze_startup_heap() -> int:
    """Collect, then freeze + defer gen-2 ONLY if the surviving tracked
    heap is large enough for full-collection pauses to matter (see
    module docstring — the columnar store keeps most serving heaps under
    the floor). Call once, after the daemon's stores/mirrors/caches are
    built but before it takes traffic (the collection itself is the last
    uncontrolled full-heap pause). Returns the frozen-object count, 0
    when the heap stayed below the floor (no freeze), or -1 when
    disabled via KT_GC_FREEZE=0."""
    if not enabled():
        return -1
    t0 = time.perf_counter()
    gc.collect()
    tracked = len(gc.get_objects())
    floor = freeze_min_objects()
    if tracked < floor:
        logger.info(
            "gc hygiene: %d tracked objects < %d floor — keeping default "
            "generational GC (no freeze; collected in %.0fms)",
            tracked, floor, (time.perf_counter() - t0) * 1e3,
        )
        return 0
    gc.freeze()
    g0, g1, _ = gc.get_threshold()
    gc.set_threshold(g0, g1, _DEFERRED_GEN2_THRESHOLD)
    frozen = gc.get_freeze_count()
    logger.info(
        "gc hygiene: froze %d startup objects in %.0fms; gen2 deferred",
        frozen, (time.perf_counter() - t0) * 1e3,
    )
    return frozen


class GcHygieneThread(threading.Thread):
    """Periodic collect-and-refreeze backstop (see module docstring).

    The interval trades pause size against cyclic-garbage residency: each
    tick's pause scans only allocations that survived since the last
    tick. The default (300 s) keeps the tick far rarer than status flips
    while bounding residency to minutes; latency-critical deployments
    can stretch it via KT_GC_COLLECT_INTERVAL_S."""

    def __init__(self, interval_s: float | None = None, tracer=None):
        super().__init__(name="gc-hygiene", daemon=True)
        if interval_s is None:
            try:
                interval_s = float(os.environ.get("KT_GC_COLLECT_INTERVAL_S", "300"))
            except ValueError:
                interval_s = 300.0
        self.interval_s = interval_s
        self.tracer = tracer
        self.last_pause_s: float | None = None
        self.ticks = 0
        self._stop_requested = threading.Event()

    def run(self) -> None:
        while not self._stop_requested.wait(self.interval_s):
            # loop-level routing (threads checker): the backstop must not
            # die of a tracer/logging hiccup — a silently dead hygiene
            # thread re-grows the gen2 heap for the process lifetime
            try:
                t0 = time.perf_counter()
                unreachable = gc.collect()
                gc.freeze()
                pause = time.perf_counter() - t0
                self.last_pause_s = pause
                self.ticks += 1
                if self.tracer is not None:
                    self.tracer.observe("gc_full_collect", pause)
                logger.info(
                    "gc hygiene: full collect freed %d cyclic objects in %.0fms "
                    "(%d now frozen)", unreachable, pause * 1e3, gc.get_freeze_count(),
                )
            except Exception:  # noqa: BLE001 — keep the backstop alive
                logger.exception("gc hygiene tick failed")

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_requested.set()
        self.join(timeout=timeout)
