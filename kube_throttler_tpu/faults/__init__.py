"""Deterministic fault injection (see plan.py for the site table)."""

from .plan import FaultInjected, FaultPlan, FaultRule, FiredFault

__all__ = ["FaultInjected", "FaultPlan", "FaultRule", "FiredFault"]
