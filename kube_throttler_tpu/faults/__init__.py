"""Deterministic fault injection (see plan.py for the site table)."""

from .plan import FaultInjected, FaultPlan, FaultRule, FiredFault, maybe_crash

__all__ = ["FaultInjected", "FaultPlan", "FaultRule", "FiredFault", "maybe_crash"]
