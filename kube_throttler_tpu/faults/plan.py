"""Deterministic, seedable fault injection for the failure paths this
standalone recast owns.

The reference is crash-only because the apiserver is its state of record
(SURVEY §5): a dropped watch, a 410 relist, a torn write are all somebody
else's recovery problem. Here the transport, the watch fan-out, the journal,
and the device dispatch are OUR code — so their failure paths need to be
drivable on demand, deterministically, from tests and soaks.

Design:

- a :class:`FaultPlan` is a seed plus an ordered list of :class:`FaultRule`
  entries, each scoped to a *site* pattern (``fnmatch`` glob over dotted
  site names like ``transport.watch.read`` or ``journal.append``);
- instrumented code calls ``plan.check(site)`` (or the raising convenience
  ``plan.maybe_raise(site)``) at each fault point; a hit either fires a
  :class:`FiredFault` or passes through;
- **determinism**: the fire/no-fire decision for hit *n* at site *s* under
  rule *r* is a pure function of ``(seed, r, s, n)`` — per-decision RNG,
  no shared stream — so concurrent threads hitting different sites cannot
  perturb each other's fault sequences. Same seed → bit-for-bit the same
  per-site fault sequence, regardless of thread interleaving;
- every firing is recorded in ``plan.history[site]`` (hit index + mode),
  which doubles as the reproducibility witness and the soak's post-mortem
  trace.

Sites are interpreted by the instrumented layer: the plan only decides
*when*; the site decides *what* a firing means (raise, torn write, stream
cut, forced 409, added delay). The instrumented sites in-tree:

==========================  ==================================================
site                        effect of a firing
==========================  ==================================================
transport.request           ConnectionResetError before the HTTP round trip
transport.put.conflict      ConflictError from put() (409 storm)
transport.watch.open        ApiError(500) opening the watch stream
transport.watch.read        per-event: mode "close" ends the stream, "gone"
                            raises GoneError (410 storm), "error" raises,
                            "delay" stalls the read
journal.append              mode "torn" writes half the line (interior
                            corruption for the NEXT append), "error" skips
                            the write
journal.fsync               OSError during compaction fsync
device.dispatch             dispatch raises (opens the circuit breaker)
ingest.batch.partial        one op of a micro-batch fails mid-apply
                            (engine/ingest.py splits around it; the ops
                            before and after still land)
crash.journal.append        SIGKILL before the event's journal line is
                            written (event reached the store, not the log)
crash.journal.torn          half the line is written+flushed, then SIGKILL
                            (the canonical torn-final-line crash artifact)
crash.journal.compact       SIGKILL right after the compacted log replaces
                            the live one (snapshot journal offsets stale)
crash.journal.group_commit  SIGKILL mid group-commit write: half the batch
                            buffer reaches the file (cut mid-line), so
                            recovery must see a clean batch prefix with
                            one torn tail (engine/journal.py on_batch)
gang.reserve.partial        one member add of a gang reserve raises
                            (engine/gang.py rolls the whole group back —
                            the all-or-nothing failure path)
crash.gang.partial_reserve  SIGKILL mid-gang-reserve: some members'
                            reservations added, the rest not — recovery
                            must land fully-reserved or fully-rolled-back,
                            never a partial group (engine/gang.py)
crash.snapshot.begin        SIGKILL before a snapshot write starts
crash.snapshot.tmp_partial  SIGKILL with half the snapshot tmp file flushed
crash.snapshot.pre_rename   SIGKILL after tmp fsync, before the atomic
                            rename (orphan tmp left behind)
crash.snapshot.post_rename  SIGKILL after the rename, before pruning
crash.snapshot.prune        SIGKILL mid-prune of superseded snapshots
mock.list                   mockserver LIST answers 500 ("error"), 410
                            ("gone"), or stalls ("delay")
mock.watch.cut              mockserver cuts the watch stream mid-flight
mock.watch.gone             mockserver emits a 410 ERROR event mid-stream
mock.status.conflict        mockserver 409s a status PUT
mock.status.error           mockserver 500s a status PUT
mock.lease                  mockserver lease endpoint: "conflict" 409s a
                            lease write, "error" 500s any lease verb,
                            "delay" stalls it (leader-election chaos)
ha.journal.batch            SIGKILL the leader after a batch mutated the
                            store but before ANY of its journal lines were
                            written (the whole batch is unreplicated)
ha.snapshot.write           SIGKILL the leader mid-snapshot (tmp complete,
                            rename pending) during an HA failover run
ha.status.commit            SIGKILL the leader after a throttle status
                            write mutated the store but before its journal
                            line landed (a flip computed but uncommitted —
                            the standby must re-derive it)
ha.replication.send         SIGKILL the leader mid-way through sending a
                            journal chunk to a standby (torn replication
                            stream; the standby must discard the partial)
mock.status.delay           mockserver stalls a status PUT for the rule's
                            ``delay`` seconds before serving it (publication
                            slowdown — the scenario engine's injected-
                            regression knob)
scenario.apiserver.restart  the scenario engine restarts the mock apiserver
                            (stop, reset the RV retention window, start on
                            the same port) — clients see connection
                            failures, then 410 on re-watch, then the
                            paginated-relist storm (scenarios/engine.py)
scenario.leader.kill        the scenario engine runs one kill-the-leader
                            failover episode through tools/harness.py (the
                            PR 6 ha.* machinery) and gates its window
scenario.churn.stall        the scenario engine's trace replayer pauses the
                            arrival process for the rule's ``delay`` (a
                            driver stall — tests the idle→burst transition)
scenario.regression.flip_stall  the deliberately-injected SLO regression:
                            the engine routes this into a per-status-PUT
                            stall (``mock.status.delay``) so the flip-p99
                            gate demonstrably fails (scenarios/slo.py)
shard.ipc.send              front→shard event-frame send raises: the shard
                            looks dead to the front — events count as
                            route misses, the shard goes dirty, and the
                            supervisor's resync repairs it (sharding/ipc.py)
shard.worker.kill           SIGKILL the shard worker at the next routed
                            event batch (the kill-a-shard chaos smoke;
                            sharding/worker.py handle_events)
reshard.handoff.torn        the live-resharding slice stream tears: mode
                            "torn" corrupts a chunk byte (the sink's
                            prefix-hash check MUST refuse it), any other
                            mode tears the stream outright — either way
                            the range aborts back to the source
                            (sharding/worker.py reshard_chunk)
reshard.dest.crash          the handoff DESTINATION dies mid-warm-up: mode
                            "kill" SIGKILLs the worker at the next import
                            chunk, "error" fails the import RPC — the
                            coordinator aborts and retries after the
                            supervisor restart (worker reshard_import)
net.connect.refused         the TCP shard client's connect() attempt is
                            refused — the reconnector backs off (jittered
                            exponential, PR 1 Backoff) and retries
                            (sharding/ipc.py TcpShardClient)
net.send.torn_frame         a framed send writes only a PREFIX of the
                            frame and then the socket dies: the peer's
                            read_frame sees a short read → treats the
                            stream as closed (no partial frame is ever
                            surfaced to the dispatcher)
net.recv.stall              the receive path stalls for the rule's
                            ``delay`` before reading the next frame (a
                            slow link / half-open socket — deadlines must
                            fire, dispatch must not block)
net.partition               the link blackholes: sends raise without
                            writing a byte and the connection is torn
                            down. Armed per-direction, so one rule makes
                            an ASYMMETRIC partition; the client degrades
                            to fail-safe verdicts until heal + resync
net.reconnect.storm         a just-reestablished connection is killed
                            again immediately (flapping link): the
                            reconnector must keep backing off, not
                            hot-loop (sharding/ipc.py TcpShardClient)
reshard.fence.race          the fence step loses a race (a concurrent
                            epoch superseded the handoff): the source
                            unfences and the range aborts back to it
                            (sharding/reshard.py, post-fence check)
reshard.front.crash         the coordinator dies between prepare and
                            cutover: mode "kill" SIGKILLs the front, any
                            other mode abandons the handoff WITHOUT
                            cleanup — both sides' two-phase reapers must
                            TTL the orphan (zero orphan reservations)
shm.ring.full               the shared-memory event ring reports itself
                            saturated: mode "delay" makes the writer
                            backpressure (counted) for the rule's delay,
                            any other mode fails the push — the lane dies
                            and the supervisor restart + resync repairs
                            it (sharding/shmring.py ShmRingWriter.push)
shm.slot.torn_commit        the writer commits a slot with a garbage
                            commit word — exactly what dying mid-commit
                            leaves behind. The reader MUST detect it
                            (TornSlotError), never consume the slot, and
                            route its own death so restart + resync
                            repairs (shmring ShmRingReader._check)
shm.doorbell.lost           the post-commit doorbell byte is dropped: the
                            reader's bounded poll must still find the
                            frame — latency, never loss (ShmRingWriter)
shm.reader.stall            the worker's ring pump stalls for the rule's
                            ``delay`` before polling — the writer must
                            backpressure, counted, without dropping a
                            committed frame (ShmRingReader.peek)
shm.segment.unlink          the creator loses the segment-unlink race at
                            close: the name is left behind and the
                            supervisor's sweep_segments backstop must
                            remove it — no leaked /dev/shm segments
                            (ShmRingWriter.close / supervisor.stop)
==========================  ==================================================

Virtual-time rules (the scenario engine's vocabulary): a rule may carry
``window=(t0, t1)`` — it only considers firing while the plan's installed
time source reads within [t0, t1) — and/or ``at_times=[...]`` — it fires
exactly once per listed instant, at the first matching hit observed at or
after that virtual time. Both extend the per-hit decision model: the plan
stays deterministic given the same hit sequence and the same clock
readings (scenarios replay committed traces, so both are pinned). A
virtual-time rule on a plan with NO time source installed never fires.

The ``crash.*`` family is the SIGKILL crash-point harness
(tools/crashtest.py): a rule with mode ``"kill"`` makes the process die by
uncatchable SIGKILL at that exact instant — no atexit, no flush, no
``finally`` — so recovery (engine/recovery.py) is exercised against the
worst on-disk artifacts each instant can leave behind.
"""

from __future__ import annotations

import fnmatch
import hashlib
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.lockorder import guard_attrs, make_lock


# The instrumented sites in-tree (the table above, as code). This is the
# registry the static analyzer's `registry` checker enforces: every literal
# site passed to check()/maybe_raise() must be a member, and every
# FaultRule site pattern must fnmatch at least one member — an
# unregistered site string silently never fires, which is exactly the
# drift class this exists to catch. Keep it a plain literal set (the
# analyzer reads it from the AST without importing this module).
KNOWN_SITES = frozenset(
    {
        "transport.request",
        "transport.put.conflict",
        "transport.watch.open",
        "transport.watch.read",
        "journal.append",
        "journal.fsync",
        "device.dispatch",
        "ingest.batch.partial",
        "gang.reserve.partial",
        "crash.gang.partial_reserve",
        "crash.preempt.partial_evict",
        "crash.journal.append",
        "crash.journal.torn",
        "crash.journal.compact",
        "crash.journal.group_commit",
        "crash.snapshot.begin",
        "crash.snapshot.tmp_partial",
        "crash.snapshot.pre_rename",
        "crash.snapshot.post_rename",
        "crash.snapshot.prune",
        "mock.list",
        "mock.watch.cut",
        "mock.watch.gone",
        "mock.status.conflict",
        "mock.status.error",
        "mock.lease",
        "ha.journal.batch",
        "ha.snapshot.write",
        "ha.status.commit",
        "ha.replication.send",
        "mock.status.delay",
        "scenario.apiserver.restart",
        "scenario.leader.kill",
        "scenario.churn.stall",
        "scenario.regression.flip_stall",
        "shard.ipc.send",
        "shard.worker.kill",
        "reshard.handoff.torn",
        "reshard.dest.crash",
        "reshard.fence.race",
        "reshard.front.crash",
        "net.connect.refused",
        "net.send.torn_frame",
        "net.recv.stall",
        "net.partition",
        "net.reconnect.storm",
        "shm.ring.full",
        "shm.slot.torn_commit",
        "shm.doorbell.lost",
        "shm.reader.stall",
        "shm.segment.unlink",
    }
)


class FaultInjected(Exception):
    """Default exception raised at a firing fault point with mode
    ``error`` and no explicit ``error`` factory."""


@dataclass(frozen=True)
class FiredFault:
    """One firing at one site: what the instrumented code should do."""

    site: str
    hit: int  # 1-based hit index at this site
    mode: str  # "error" | "close" | "gone" | "torn" | "delay" | ...
    rule_site: str  # the rule pattern that fired
    delay: float = 0.0
    _error: Optional[Callable[[], BaseException]] = None

    def make_error(self) -> BaseException:
        if self._error is not None:
            return self._error()
        return FaultInjected(f"injected fault at {self.site} (hit {self.hit})")

    def sleep(self) -> None:
        if self.delay > 0:
            time.sleep(self.delay)

    def kill(self) -> None:
        """Die by SIGKILL right here — uncatchable, no cleanup handlers, no
        buffered-file flushes. The crash harness's seeded worst-instant
        process death (mode ``"kill"`` at a ``crash.*`` site)."""
        os.kill(os.getpid(), signal.SIGKILL)


@dataclass
class FaultRule:
    """When to fire at matching sites.

    ``schedule`` (1-based hit indices, applied after ``after`` is skipped)
    beats ``probability``; ``times`` caps total firings per site; ``after``
    lets the first N hits through untouched (e.g. let the initial sync
    succeed, then storm).

    Virtual-time extensions (scenario engine): ``window=(t0, t1)`` gates
    the rule to hits observed while the plan's time source reads within
    [t0, t1); ``at_times=[...]`` fires exactly once per listed virtual
    instant — at the first matching hit at/after it — and beats
    probability/schedule the way ``schedule`` beats ``probability``.
    Either requires a time source on the plan (``set_time_source``)."""

    site: str  # fnmatch pattern over dotted site names
    mode: str = "error"
    error: Optional[Callable[[], BaseException]] = None
    probability: float = 1.0
    times: Optional[int] = None
    after: int = 0
    schedule: Optional[Sequence[int]] = None
    delay: float = 0.0
    window: Optional[Tuple[float, float]] = None
    at_times: Optional[Sequence[float]] = None
    _schedule_set: Optional[frozenset] = field(default=None, repr=False)

    def __post_init__(self) -> None:
        if self.schedule is not None:
            self._schedule_set = frozenset(int(i) for i in self.schedule)

    def canonical(self) -> Dict[str, object]:
        """Minimal, stable, JSON-able form: default-valued fields dropped,
        ``schedule``/``at_times`` sorted, ``window`` a 2-list. Two rules
        with equal canonical forms decide identically for every
        ``(seed, rule_idx, site, hit, clock)`` tuple. ``error`` factories
        are represented by their qualified name only (callables don't
        serialize; the factory's identity is what distinguishes rules)."""
        out: Dict[str, object] = {"site": self.site}
        if self.mode != "error":
            out["mode"] = self.mode
        if self.error is not None:
            out["error"] = getattr(self.error, "__qualname__", repr(self.error))
        if self.probability < 1.0:
            out["probability"] = float(self.probability)
        if self.times is not None:
            out["times"] = int(self.times)
        if self.after:
            out["after"] = int(self.after)
        if self.schedule is not None:
            out["schedule"] = sorted(int(i) for i in self.schedule)
        if self.delay:
            out["delay"] = float(self.delay)
        if self.window is not None:
            out["window"] = [float(self.window[0]), float(self.window[1])]
        if self.at_times is not None:
            out["at_times"] = sorted(float(t) for t in self.at_times)
        return out


def _decision(seed: int, rule_idx: int, site: str, hit: int) -> float:
    """Uniform [0,1) that depends ONLY on (seed, rule, site, hit) — sha256,
    not ``hash()``, because PYTHONHASHSEED would break cross-process
    reproducibility of a recorded fault plan."""
    digest = hashlib.sha256(
        f"{seed}\x00{rule_idx}\x00{site}\x00{hit}".encode()
    ).digest()
    return int.from_bytes(digest[:8], "big") / 2**64


@guard_attrs
class FaultPlan:
    """A seeded set of fault rules plus the per-site hit/firing bookkeeping.

    Thread-safe; the decision function is stateless per hit (see module
    docstring), so the per-site fault sequence is reproducible from the
    seed alone."""

    GUARDED_BY = {
        "_hits": "self._lock",
        "_fired": "self._lock",
        "history": "self._lock",
        "_times_pending": "self._lock",
    }

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rules: List[FaultRule] = []
        self._lock = make_lock("faults.plan")
        self._hits: Dict[str, int] = {}
        self._fired: Dict[Tuple[int, str], int] = {}  # (rule idx, site) → count
        # site → [(hit, mode)] — the reproducibility witness
        self.history: Dict[str, List[Tuple[int, str]]] = {}
        # virtual clock for window/at_times rules (scenarios install the
        # trace replayer's virtual-time reader); None ⇒ those rules are inert
        self._time_source: Optional[Callable[[], float]] = None
        # (rule idx, site) → sorted not-yet-fired at_times instants
        self._times_pending: Dict[Tuple[int, str], List[float]] = {}

    def set_time_source(self, fn: Optional[Callable[[], float]]) -> None:
        """Install the virtual clock that ``window``/``at_times`` rules read
        (monotone float seconds; the scenario engine's trace time)."""
        self._time_source = fn

    def rule(
        self,
        site: str,
        *,
        mode: str = "error",
        error: Optional[Callable[[], BaseException]] = None,
        probability: float = 1.0,
        times: Optional[int] = None,
        after: int = 0,
        schedule: Optional[Sequence[int]] = None,
        delay: float = 0.0,
        window: Optional[Tuple[float, float]] = None,
        at_times: Optional[Sequence[float]] = None,
    ) -> "FaultPlan":
        """Add a rule; returns self for chaining."""
        self._rules.append(
            FaultRule(
                site=site,
                mode=mode,
                error=error,
                probability=probability,
                times=times,
                after=after,
                schedule=schedule,
                delay=delay,
                window=window,
                at_times=at_times,
            )
        )
        return self

    # -- the fault point API ------------------------------------------------

    def check(self, site: str) -> Optional[FiredFault]:
        """Count a hit at ``site``; return the fault to apply, or None.
        First matching rule that decides to fire wins (rule order is
        priority order)."""
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            now_v: Optional[float] = None
            if self._time_source is not None and any(
                r.window is not None or r.at_times is not None for r in self._rules
            ):
                now_v = self._time_source()  # one read serves every rule
            for idx, rule in enumerate(self._rules):
                if not fnmatch.fnmatchcase(site, rule.site):
                    continue
                if hit <= rule.after:
                    continue
                key = (idx, site)
                if rule.times is not None and self._fired.get(key, 0) >= rule.times:
                    continue
                if rule.window is not None:
                    if now_v is None or not (rule.window[0] <= now_v < rule.window[1]):
                        continue
                if rule.at_times is not None:
                    # fires once per scheduled instant, at the first hit
                    # observed at/after it (beats probability/schedule)
                    if now_v is None:
                        continue
                    pend = self._times_pending.get(key)
                    if pend is None:
                        pend = self._times_pending[key] = sorted(
                            float(t) for t in rule.at_times
                        )
                    if not pend or now_v < pend[0]:
                        continue
                    pend.pop(0)
                    fire = True
                elif rule._schedule_set is not None:
                    fire = (hit - rule.after) in rule._schedule_set
                elif rule.probability >= 1.0:
                    fire = True
                else:
                    fire = _decision(self.seed, idx, site, hit) < rule.probability
                if not fire:
                    continue
                self._fired[key] = self._fired.get(key, 0) + 1
                self.history.setdefault(site, []).append((hit, rule.mode))
                return FiredFault(
                    site=site,
                    hit=hit,
                    mode=rule.mode,
                    rule_site=rule.site,
                    delay=rule.delay,
                    _error=rule.error,
                )
        return None

    def maybe_raise(
        self, site: str, default: Callable[[], BaseException] = None
    ) -> None:
        """Convenience for sites whose only failure mode is raising: check,
        apply any delay, then raise the fault's error (``default`` supplies
        the exception factory when the rule carries none)."""
        fault = self.check(site)
        if fault is None:
            return
        fault.sleep()
        if fault.mode == "delay":
            return  # pure stall, no error
        if fault._error is None and default is not None:
            raise default()
        raise fault.make_error()

    # -- introspection ------------------------------------------------------

    def canonical_rules(self) -> List[Dict[str, object]]:
        """The effective rule set in priority order, each rule in its
        stable canonical form (:meth:`FaultRule.canonical`). Order is
        PRESERVED — first-match-wins makes priority part of the plan's
        semantics — so equality of canonical forms means behavioral
        equality, and the scenario hunt dedupes mutants by the sha of this
        list (trace headers commit it; scenarios/trace.py)."""
        return [r.canonical() for r in self._rules]

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def fired(self, site: Optional[str] = None) -> int:
        """Total firings, optionally for one site."""
        with self._lock:
            if site is not None:
                return len(self.history.get(site, []))
            return sum(len(v) for v in self.history.values())

    def snapshot(self) -> Dict[str, List[Tuple[int, str]]]:
        """Deep-ish copy of the per-site firing history (the determinism
        witness: equal across runs for equal seeds and site hit counts)."""
        with self._lock:
            return {site: list(v) for site, v in self.history.items()}

    def reset(self) -> None:
        """Clear hit counts and history, keep the rules (new run, same
        plan)."""
        with self._lock:
            self._hits.clear()
            self._fired.clear()
            self.history.clear()
            self._times_pending.clear()


def maybe_crash(plan: Optional[FaultPlan], site: str) -> None:
    """Crash-point hook: count a hit at ``site`` and, if a rule with mode
    ``"kill"`` fires, SIGKILL the process on the spot. Instrumented code
    sprinkles these at the instants whose on-disk artifacts recovery must
    survive (mid-snapshot rename, between journal append and fsync, ...).
    Production passes ``plan=None`` — a single ``is None`` branch."""
    if plan is None:
        return
    fault = plan.check(site)
    if fault is not None and fault.mode == "kill":
        fault.sleep()
        fault.kill()
