"""Exact Kubernetes resource.Quantity arithmetic.

The reference relies on k8s.io/apimachinery's ``resource.Quantity`` — exact
decimal numbers with SI / binary suffixes — for every threshold comparison
(reference pkg/resourcelist/resourcelist.go:64-74 uses ``Quantity.Cmp``).
Throttling decisions are exact: ``100m`` CPU is 1/10, not 0.1000000001.

This module parses the full Quantity grammar and represents values as exact
``Fraction``s for host-side (oracle) arithmetic, plus a lossless conversion
to integer *milli-units* for the device tensor path (int64 milli covers
[1e-3, 9.2e15] — micro/nano-scale quantities are rejected at tensor-encode
time rather than silently rounded; see ``to_milli``).

Grammar (k8s apimachinery quantity.go):
    <quantity>   ::= <signedNumber><suffix>
    <suffix>     ::= <binarySI> | <decimalExponent> | <decimalSI>
    <binarySI>   ::= Ki | Mi | Gi | Ti | Pi | Ei
    <decimalSI>  ::= n | u | m | "" | k | M | G | T | P | E
    <decimalExponent> ::= "e"<signedNumber> | "E"<signedNumber>
"""

from __future__ import annotations

import re
from fractions import Fraction
from functools import lru_cache
from typing import Union

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}

_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<int>[0-9]*)(?:\.(?P<frac>[0-9]*))?"
    r"(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE]|[eE][+-]?[0-9]+)?$"
)


class QuantityParseError(ValueError):
    """Raised for strings that are not valid k8s quantities."""


@lru_cache(maxsize=65536)
def parse_quantity(s: Union[str, int, float]) -> Fraction:
    """Parse a k8s quantity string into an exact Fraction.

    Accepts ints/floats too (YAML often yields bare numbers for thresholds);
    floats go through ``str()`` so ``0.1`` means decimal 0.1.
    """
    if isinstance(s, Fraction):
        return s
    if isinstance(s, int):
        return Fraction(s)
    if isinstance(s, float):
        s = repr(s)
    if not isinstance(s, str):
        raise QuantityParseError(f"unsupported quantity type: {type(s)!r}")
    text = s.strip()
    if not text:
        raise QuantityParseError("empty quantity string")
    m = _QUANTITY_RE.match(text)
    if m is None:
        raise QuantityParseError(f"invalid quantity: {s!r}")
    int_part = m.group("int") or ""
    frac_part = m.group("frac")
    if not int_part and not frac_part:
        raise QuantityParseError(f"invalid quantity (no digits): {s!r}")

    mantissa = Fraction(int(int_part or "0"))
    if frac_part:
        mantissa += Fraction(int(frac_part), 10 ** len(frac_part))
    if m.group("sign") == "-":
        mantissa = -mantissa

    suffix = m.group("suffix") or ""
    if suffix in _BINARY_SUFFIXES:
        value = mantissa * _BINARY_SUFFIXES[suffix]
    elif suffix and suffix[0] in "eE" and len(suffix) > 1:
        value = mantissa * Fraction(10) ** int(suffix[1:])
    elif suffix in _DECIMAL_SUFFIXES:
        value = mantissa * _DECIMAL_SUFFIXES[suffix]
    else:  # pragma: no cover — regex should prevent this
        raise QuantityParseError(f"invalid suffix in quantity: {s!r}")
    return value


class SubMilliPrecisionError(ValueError):
    """A quantity cannot be represented in integer milli-units.

    The device tensor path stores quantities as int64 milli-units. Quantities
    with sub-milli precision (``n``/``u`` suffixes, or fractions like 1/3)
    cannot be encoded losslessly; rather than silently diverge from the exact
    host oracle, encoding raises this error.
    """


def to_milli(value: Fraction) -> int:
    """Losslessly convert an exact quantity to integer milli-units."""
    scaled = value * 1000
    if scaled.denominator != 1:
        raise SubMilliPrecisionError(
            f"quantity {value} has sub-milli precision; cannot encode exactly"
        )
    result = int(scaled)
    if not -(2**63) <= result < 2**63:
        raise SubMilliPrecisionError(f"quantity {value} overflows int64 milli-units")
    return result


@lru_cache(maxsize=65536)
def from_milli(milli: int) -> Fraction:
    """Milli-units → exact Fraction. Cached: the reconcile gather decodes
    the same small set of milli values (request sizes × throttle counts)
    thousands of times per second, and Fraction construction normalizes
    via gcd each call."""
    return Fraction(int(milli), 1000)


def format_quantity(value: Fraction) -> str:
    """Canonical-ish string form (integral → bare, milli-integral → ``m``).

    Not byte-identical to k8s canonicalization (which preserves the parsed
    suffix family); used only for human-readable status output and metrics
    labels, never for comparisons.
    """
    if value.denominator == 1:
        return str(value.numerator)
    m = value * 1000
    if m.denominator == 1:
        return f"{m.numerator}m"
    u = value * 10**6
    if u.denominator == 1:
        return f"{u.numerator}u"
    n = value * 10**9
    if n.denominator == 1:
        return f"{n.numerator}n"
    return str(float(value))


def cmp_quantity(a: Fraction, b: Fraction) -> int:
    """Three-way compare, mirroring ``Quantity.Cmp``."""
    if a < b:
        return -1
    if a > b:
        return 1
    return 0
