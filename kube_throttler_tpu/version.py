"""Version & format contracts for rolling upgrades.

Single source of truth for everything two *different* builds of this
package must agree on before they exchange bytes:

- the shard wire protocol's ``(major, minor)`` version and the minor
  capability set the ``sub``/hello handshake negotiates
  (sharding/ipc.py ↔ sharding/worker.py);
- the durable-format registry: every framed-pickle frame type, journal
  control-line type, and snapshot payload version maps to the minimum
  reader version that understands it (``FORMAT_REGISTRY``).

Compatibility rules (docs/robustness.md "Upgrades & version skew"):

- equal MAJOR is required; a major mismatch is refused with a typed
  ``VersionMismatch`` frame — degraded health, counted metric, never a
  crash loop;
- MINOR differences negotiate down: the effective capability set is the
  intersection of what both ends advertise, so an old worker and a new
  front interoperate for the whole roll (capabilities gate encodings,
  never semantics);
- durable formats only ever ADD registry entries; removing or re-keying
  one breaks replay of committed journals/snapshots and is forbidden
  (the pre-bump fixture pair under tests/fixtures/ pins this forever).

Deliberately jax-free: the journal, the snapshot reader, and the IPC
framing layer consult this module at runtime on paths where importing
the device stack would be dead weight.
"""

from __future__ import annotations

import os
from typing import Dict, FrozenSet, Iterable, Optional, Tuple

from . import __version__ as BUILD_VERSION

# Wire-protocol version of THIS build. Bump MINOR when adding a
# negotiable capability; bump MAJOR only for changes an old peer cannot
# safely ignore (frame layout, handshake shape, fencing semantics).
PROTO_MAJOR = 1
PROTO_MINOR = 2
PROTO_VERSION: Tuple[int, int] = (PROTO_MAJOR, PROTO_MINOR)

# Human-debuggable build identity carried in the hello exchange and the
# build_info gauge — never an input to negotiation.
BUILD_ID = f"kube-throttler-tpu/{BUILD_VERSION}"

# Minor capabilities THIS build can speak. Negotiation intersects both
# ends' advertised sets; using a capability the peer did not advertise
# is a bug (the interop sweep in tests/test_upgrade.py gates this).
#
#   evt-columnar   "evt" store-op batches may ship column-packed
#                  (struct-of-arrays transpose) instead of the v1
#                  row-list pickle — same events, cheaper frames
#   build-info     the peer answers stats RPCs with negotiated
#                  version/caps/build fields (kube_throttler_build_info)
#   evt-shm        the worker attached the supervisor's shared-memory
#                  event ring (sharding/shmring.py): the front may move
#                  "evt" batches through it as ring-v1 columnar frames
#                  instead of pickle frames on the socket. A worker
#                  only advertises this when its ring attach succeeded;
#                  either side masking it falls back to pickle frames
#                  byte-identically (mixed fleets / rolling upgrades)
CAPABILITIES: FrozenSet[str] = frozenset({"evt-columnar", "build-info", "evt-shm"})

# Durable/wire format registry: ``<domain>:<name> -> minimum reader
# version`` (the oldest PROTO_MAJOR-series reader that understands the
# format). The static analyzer's `protocol` checker machine-checks this
# literal: every frame mtype passed to send_frame, every journal
# control-line type emitted anywhere in the package, and every entry of
# SUPPORTED_SNAPSHOT_VERSIONS must have a row here, and every row must
# still be referenced by code (a stale row is a finding). Keep it a
# plain literal — the checker reads it from the AST without importing.
FORMAT_REGISTRY: Dict[str, int] = {
    # framed-pickle shard protocol (sharding/ipc.py)
    "frame:evt": 1,
    "frame:req": 1,
    "frame:res": 1,
    "frame:push": 1,
    "frame:sub": 1,
    "frame:hello": 1,
    # journal control lines (engine/journal.py, engine/replication.py)
    "journal:EPOCH": 1,
    "journal:GANG": 1,
    "journal:PREEMPT": 1,
    # snapshot payload versions (engine/snapshot.py)
    "snapshot:1": 1,
    "snapshot:2": 1,
    # shared-memory event-ring layouts (sharding/shmring.py SHM_FORMATS)
    "shm:ring-v1": 1,
}


def min_reader_version(domain: str, name: object) -> Optional[int]:
    """Minimum reader version for a registered format, or None if the
    format is unknown to this build."""
    return FORMAT_REGISTRY.get(f"{domain}:{name}")


def advertised_capabilities(env: Optional[Dict[str, str]] = None) -> FrozenSet[str]:
    """The capability set this process advertises in its hello.

    ``KT_PROTO_CAPS_MASK`` (comma-separated capability names) restricts
    the advertisement to the named subset — the rolling-upgrade harness
    uses it to make a current binary *behave* like an older minor
    (empty string ⇒ advertise nothing, i.e. the 1.0 baseline). Unset ⇒
    the full built-in set.
    """
    env = os.environ if env is None else env
    mask = env.get("KT_PROTO_CAPS_MASK")
    if mask is None:
        return CAPABILITIES
    allowed = {c.strip() for c in mask.split(",") if c.strip()}
    return CAPABILITIES & frozenset(allowed)


def local_proto_version(env: Optional[Dict[str, str]] = None) -> Tuple[int, int]:
    """This process's advertised ``(major, minor)``.

    ``KT_PROTO_MAJOR`` overrides the major — the upgrade chaos matrix
    uses it to stage an incompatible-major pairing without building a
    second wheel. A non-integer value is ignored (never crash on env).
    """
    env = os.environ if env is None else env
    raw = env.get("KT_PROTO_MAJOR")
    if raw:
        try:
            return (int(raw), PROTO_MINOR)
        except ValueError:
            pass
    return (PROTO_MAJOR, PROTO_MINOR)


def local_hello(env: Optional[Dict[str, str]] = None) -> Dict[str, object]:
    """The hello payload carried by the lane-0 ``sub`` frame (front →
    worker) and echoed back in the worker's ``hello`` reply."""
    return {
        "proto": list(local_proto_version(env)),
        "caps": sorted(advertised_capabilities(env)),
        "build": BUILD_ID,
    }


class NegotiationError(ValueError):
    """Raised by :func:`negotiate` on an incompatible-major pairing.
    Wire layers translate this into the typed ``VersionMismatch``
    refusal; it never crosses a process boundary itself."""


def negotiate(
    ours: Tuple[int, int],
    our_caps: Iterable[str],
    theirs: object,
    their_caps: object,
) -> Tuple[Tuple[int, int], FrozenSet[str]]:
    """Intersect two hellos into the effective ``(version, caps)``.

    A peer that sent no hello (``theirs is None`` — a pre-handshake
    build) negotiates as the ``(major, 1.0-minor)`` baseline with zero
    capabilities: old peers keep working, they just get v1 encodings.
    Raises :class:`NegotiationError` on a major mismatch.
    """
    if theirs is None:
        return ((ours[0], 0), frozenset())
    try:
        their_major, their_minor = int(theirs[0]), int(theirs[1])
    except (TypeError, ValueError, IndexError):
        raise NegotiationError(f"malformed peer proto version {theirs!r}")
    if their_major != ours[0]:
        raise NegotiationError(
            f"incompatible protocol major: ours {ours[0]}.{ours[1]}, "
            f"peer {their_major}.{their_minor}"
        )
    caps = frozenset(our_caps) & frozenset(
        c for c in (their_caps or ()) if isinstance(c, str)
    )
    return ((ours[0], min(ours[1], their_minor)), caps)
